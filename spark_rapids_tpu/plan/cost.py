"""Cost-based host/device placement (ROADMAP item 1 / ISSUE 7 tentpole).

The engine's losses are concentrated on small inputs: a tunneled TPU pays
a ~70-100ms host-sync floor per dispatch funnel (the r4 q3 profile), so a
query over a few tens of MB spends seconds in round trips that a host
pass finishes in milliseconds — the reference's own economics say device
offload is "worthwhile >= 30s" (docs/FAQ.md:82-84). This module gives the
planner the number it was missing: a per-subtree estimate of device time
(sync floor x sync count + bytes over the device pipeline) vs host time
(bytes over the host engine, one pass per operator), grounded in the same
parquet/ORC footer stats that feed autoBroadcastJoinThreshold
(plan/pruning.py estimate_bytes, cached footer parses in io/scan.py).

Placement is maximal-subtree: the walk is top-down, and the FIRST node
whose whole subtree estimates cheaper on the host flips that entire
subtree to the host engine (the existing ``execute_host`` path, promoted
from the OOM-fallback rung to a first-class placement). The conversion
layer then bridges engines exactly as it does for capability fallbacks,
so a host-placed subtree under a device parent uploads once at its root.

Estimates are heuristics with calibrated, conf-overridable constants
(``spark.rapids.sql.cost.*``, defaults fit to the round-5 SF1 bench);
they only steer placement — results are engine-independent either way.

Gates (all leave the legacy all-device plan untouched):
- ``spark.rapids.sql.cost.enabled`` false, or ``SRT_COST=0``;
- test mode (``spark.rapids.sql.test.enabled`` asserts device planning);
- an armed fault schedule (chaos targets device dispatch sites);
- a non-inprocess shuffle transport (mesh/hostfile runs measure those
  paths, not placement);
- no file scan in the plan (in-memory/range plans have no footer stats
  to ground the model — unit-test currency stays on the device path).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import LogicalPlan

# Process-global counters for bench.py's `cost` JSON block (mirrors
# pipeline.counters()): how often placement ran and what it chose.
_COUNTERS: Dict[str, float] = {}
_COUNTERS_LOCK = threading.Lock()


def _record(name: str, amount: float = 1) -> None:
    with _COUNTERS_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    with _COUNTERS_LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _COUNTERS_LOCK:
        _COUNTERS.clear()


# ---------------------------------------------------------------------------
# Self-calibration: the model's two machine constants — the per-dispatch
# sync floor and the device pipeline throughput — default to hand
# calibrations of one round-5 chip. With cost.calibration.enabled the
# flight recorder's observed numbers EWMA into process-global effective
# values (clamped to [1/4x, 4x] of the configured constants), so
# placement tracks the machine it actually runs on. An explicitly-set
# cost.* conf key always wins over the calibrated value.
# ---------------------------------------------------------------------------

_CAL_LOCK = threading.Lock()
_CAL: Dict[str, Optional[float]] = {
    "sync_floor_ms": None, "device_gbps": None, "samples": 0.0,
    "last_error_pct": None}


def calibration_enabled(conf: "C.TpuConf") -> bool:
    if conf.raw.get(C.COST_CALIBRATION.key) is not None:
        return bool(conf.get(C.COST_CALIBRATION))
    env = os.environ.get("SRT_COST_CALIBRATION")
    if env is not None:
        return env.strip() not in ("0", "false", "no")
    return bool(C.COST_CALIBRATION.default)


def _clamped(value: float, default: float) -> float:
    return min(max(value, default / 4.0), default * 4.0)


_CPU_ONLY_BACKEND: Optional[bool] = None


def _cpu_only_backend() -> bool:
    """True when the "device" engine itself runs on host CPU (tests,
    local dev: JAX_PLATFORMS=cpu). There is no dispatch tunnel between
    the planner and a CPU backend, so the per-sync floor the model is
    calibrated for physically does not exist — charging it would
    host-place nearly every small plan."""
    global _CPU_ONLY_BACKEND
    if _CPU_ONLY_BACKEND is None:
        try:
            import jax
            _CPU_ONLY_BACKEND = jax.default_backend() == "cpu"
        except Exception:
            _CPU_ONLY_BACKEND = False
    return _CPU_ONLY_BACKEND


def effective_sync_floor_ms(conf: "C.TpuConf") -> float:
    """The sync floor the estimator charges: an explicit conf key wins;
    else zero on a CPU-only backend (no tunnel to sync through); else
    the calibrated observation (clamped); else the default."""
    configured = float(conf.get(C.COST_SYNC_FLOOR_MS))
    if conf.raw.get(C.COST_SYNC_FLOOR_MS.key) is not None:
        return configured
    if _cpu_only_backend() and not conf.get(C.COST_ASSUME_TUNNEL):
        return 0.0
    if not calibration_enabled(conf):
        return configured
    with _CAL_LOCK:
        cal = _CAL["sync_floor_ms"]
    return configured if cal is None else _clamped(cal, configured)


def effective_device_gbps(conf: "C.TpuConf") -> float:
    configured = float(conf.get(C.COST_DEVICE_GBPS))
    if conf.raw.get(C.COST_DEVICE_GBPS.key) is not None or \
            not calibration_enabled(conf):
        return configured
    with _CAL_LOCK:
        cal = _CAL["device_gbps"]
    return configured if cal is None else _clamped(cal, configured)


def observe(sync_floor_ms: Optional[float] = None,
            device_gbps: Optional[float] = None,
            error_pct: Optional[float] = None,
            alpha: float = 0.2) -> None:
    """Fold one query's observations into the calibration state.
    ``error_pct`` (the Cost@query estimateErrorPct) dampens the update:
    a query whose byte estimates were far off earns less trust."""
    weight = alpha
    if error_pct is not None:
        weight = alpha / (1.0 + max(error_pct, 0.0) / 100.0)
    with _CAL_LOCK:
        if error_pct is not None:
            _CAL["last_error_pct"] = float(error_pct)
        for key, obs in (("sync_floor_ms", sync_floor_ms),
                         ("device_gbps", device_gbps)):
            if obs is None or obs <= 0:
                continue
            cur = _CAL[key]
            _CAL[key] = float(obs) if cur is None \
                else (1.0 - weight) * cur + weight * float(obs)
        if sync_floor_ms is not None or device_gbps is not None:
            _CAL["samples"] += 1
    _record("costCalibrationUpdates")


def calibration_state() -> Dict[str, Optional[float]]:
    with _CAL_LOCK:
        return dict(_CAL)


def reset_calibration() -> None:
    with _CAL_LOCK:
        _CAL.update({"sync_floor_ms": None, "device_gbps": None,
                     "samples": 0.0, "last_error_pct": None})


def observe_query(ctx) -> None:
    """Feed one finished query's flight-recorder spans (and its
    Cost@query estimateErrorPct) back into the calibration state.
    Called from the collect tail; a no-op when tracing is off (no spans
    to learn from) or calibration is disabled."""
    if not calibration_enabled(ctx.conf):
        return
    from spark_rapids_tpu import monitoring
    if not monitoring.enabled():
        return
    qid = ctx.cache.get("trace_query")
    if qid is None:
        return
    evs = monitoring.events(qid)
    sync_ns: List[float] = []
    upload_bytes = 0.0
    upload_ns = 0.0
    for e in evs:
        if e[0] != "X":
            continue
        cat, dur = e[2], e[4]
        if cat == "sync":
            sync_ns.append(dur)
        elif cat == "upload":
            args = e[7] or {}
            b = args.get("bytes")
            if b:
                upload_bytes += float(b)
                upload_ns += float(dur)
    sync_floor = (sum(sync_ns) / len(sync_ns)) / 1e6 if sync_ns else None
    gbps = (upload_bytes / (upload_ns / 1e9)) / 1e9 \
        if upload_ns > 0 and upload_bytes > 0 else None
    err = None
    try:
        # Read-only: query_metrics_entry would CREATE an empty
        # Cost@query group and change the query's metric shape.
        cm = ctx.metrics.get("Cost@query")
        if cm is not None:
            err = cm.values.get("estimateErrorPct")
    except Exception:
        pass
    if sync_floor is None and gbps is None:
        return
    alpha = float(ctx.conf.get(C.COST_CALIBRATION_ALPHA))
    observe(sync_floor_ms=sync_floor, device_gbps=gbps, error_pct=err,
            alpha=alpha)


def cost_enabled(conf: "C.TpuConf") -> bool:
    """Conf key wins; else the SRT_COST env (CI matrix hook); else the
    registered default."""
    if conf.raw.get(C.COST_ENABLED.key) is not None:
        return bool(conf.get(C.COST_ENABLED))
    env = os.environ.get("SRT_COST")
    if env is not None:
        return env.strip() not in ("0", "false", "no")
    return bool(C.COST_ENABLED.default)


def _placement_gates(conf: "C.TpuConf", plan: LogicalPlan) -> Optional[str]:
    """Why placement must not run, or None when it may."""
    if not cost_enabled(conf):
        return "disabled"
    if conf.test_enabled:
        return "test mode asserts device planning"
    if conf.raw.get(C.TEST_FAULTS.key) is not None or \
            os.environ.get("SRT_FAULTS", "").strip():
        return "fault schedule armed (chaos targets device sites)"
    from spark_rapids_tpu.parallel import transport as T
    if T.transport_name(conf) != "inprocess":
        return "non-inprocess shuffle transport"
    if not _has_file_scan(plan):
        return "no footer-stats-backed scan in the plan"
    return None


def _has_file_scan(plan: LogicalPlan) -> bool:
    if isinstance(plan, L.FileScan):
        return True
    return any(_has_file_scan(c) for c in plan.children)


# ---------------------------------------------------------------------------
# Per-node estimates
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NodeEstimate:
    """One logical node's subtree estimate (totals INCLUDE children)."""

    name: str
    bytes_out: Optional[int]      # estimated output bytes (None = unknown)
    subtree_bytes: Optional[int]  # max bytes flowing through any node
    device_ms: float              # subtree device estimate
    host_ms: float                # subtree host estimate
    syncs: int                    # subtree device sync count


# Device host-sync round trips charged per node kind: how many times the
# node's execution forces the driver to wait on the device (exchange
# sizes pull + serve, join build stats pull + expansion count, aggregate
# shrink, range-sort sample). Scans charge one for the upload dispatch.
def _node_syncs(plan: LogicalPlan, conf: "C.TpuConf") -> int:
    if isinstance(plan, (L.FileScan, L.InMemoryScan, L.LogicalRange)):
        return 1
    if isinstance(plan, L.LogicalAggregate):
        # partial -> exchange (sizes + serve) -> final shrink.
        return 3
    if isinstance(plan, L.LogicalJoin):
        return _join_syncs(plan, conf)
    if isinstance(plan, L.LogicalSort):
        return 3                  # range sample + exchange + serve
    if isinstance(plan, L.LogicalWindow):
        return 3                  # hash exchange + partition sort
    if isinstance(plan, L.LogicalLimit):
        return 2                  # single-partition exchange
    if isinstance(plan, L.LogicalRepartition):
        # The exchange's sizes pull, then every reduce partition served
        # downstream is its own round trip — the term that makes a
        # tiny-input repartition a guaranteed device loss.
        return 1 + max(int(plan.num_partitions), 1)
    if isinstance(plan, L.LogicalGenerate):
        return 1
    return 0


def _join_syncs(plan: "L.LogicalJoin", conf: "C.TpuConf") -> int:
    """Broadcast: build collect + expansion-count pull. Shuffle: two
    exchanges (sizes + serve each) + build + expansion."""
    strategy = plan.strategy
    if strategy == "auto" and plan.join_type != "full":
        from spark_rapids_tpu.plan.pruning import estimate_bytes
        threshold = int(conf.get(C.AUTO_BROADCAST_THRESHOLD))
        build = plan.children[1] if plan.join_type != "right" \
            else plan.children[0]
        est = estimate_bytes(build)
        strategy = "broadcast" if threshold >= 0 and est is not None \
            and est <= threshold else "shuffle"
    return 2 if strategy == "broadcast" else 6


def estimate_plan(plan: LogicalPlan, conf: "C.TpuConf",
                  out: Optional[Dict[int, NodeEstimate]] = None,
                  ) -> Dict[int, NodeEstimate]:
    """Bottom-up estimates for every node, keyed by id(plan)."""
    from spark_rapids_tpu.plan.pruning import estimate_bytes
    if out is None:
        out = {}
    for c in plan.children:
        estimate_plan(c, conf, out)
    kids = [out[id(c)] for c in plan.children]
    bytes_out = estimate_bytes(plan)
    # Bytes flowing INTO this node = children's outputs (leaf nodes read
    # their own bytes). Unknown child bytes poison the subtree estimate.
    if plan.children:
        child_out = [k.bytes_out for k in kids]
        bytes_in = None if any(b is None for b in child_out) \
            else sum(child_out)
    else:
        bytes_in = bytes_out
    # ROLLUP/CUBE expand the input once per grouping set before the
    # partial aggregate — both engines pay the multiplication.
    mult = 1
    if isinstance(plan, L.LogicalAggregate) and plan.grouping is not None:
        nk = len(plan.group_by)
        mult = (nk + 1) if plan.grouping == "rollup" else (1 << nk)
    sync_ms = effective_sync_floor_ms(conf)
    dev_bw = max(effective_device_gbps(conf), 1e-3) * 1e9 / 1e3
    host_bw = max(float(conf.get(C.COST_HOST_GBPS)), 1e-3) * 1e9 / 1e3
    syncs = _node_syncs(plan, conf)
    if bytes_in is None:
        # Unknown size: charge only the sync floor on the device side and
        # a token host pass — the placement step refuses to host-place a
        # subtree whose bytes are unknown anyway.
        dev_node_ms = syncs * sync_ms
        host_node_ms = 0.5
        subtree_bytes = None
    else:
        moved = bytes_in * mult
        dev_node_ms = syncs * sync_ms + moved / dev_bw
        host_node_ms = 0.5 + moved / host_bw
        kid_bytes = [k.subtree_bytes for k in kids]
        subtree_bytes = None if any(b is None for b in kid_bytes) \
            else max([moved] + kid_bytes) if kids else moved
    out[id(plan)] = NodeEstimate(
        name=plan.name,
        bytes_out=bytes_out,
        subtree_bytes=subtree_bytes,
        device_ms=sum(k.device_ms for k in kids) + dev_node_ms,
        host_ms=sum(k.host_ms for k in kids) + host_node_ms,
        syncs=sum(k.syncs for k in kids) + syncs)
    return out


# ---------------------------------------------------------------------------
# Placement
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CostReport:
    """What the model decided, for explain / Cost@query metrics."""

    skipped: Optional[str] = None          # gate that disabled placement
    placements: int = 0                    # host-placed subtree roots
    nodes_host_placed: int = 0             # nodes inside those subtrees
    est_device_ms: float = 0.0             # root subtree estimates
    est_host_ms: float = 0.0
    est_syncs: int = 0
    lines: List[str] = dataclasses.field(default_factory=list)

    def explain_lines(self) -> List[str]:
        if self.skipped is not None:
            return [f"Cost model: skipped ({self.skipped})"]
        head = (f"Cost model: {self.placements} host placement(s); root "
                f"estimate device {self.est_device_ms:.0f}ms "
                f"({self.est_syncs} syncs) vs host "
                f"{self.est_host_ms:.0f}ms")
        return [head] + [f"  {ln}" for ln in self.lines]


def _mark_host(meta) -> int:
    """Flip one whole subtree to the host engine; returns nodes marked."""
    meta.cost_host = True
    return 1 + sum(_mark_host(c) for c in meta.children)


def apply_placement(meta, conf: "C.TpuConf") -> CostReport:
    """Top-down maximal-subtree placement over the tagged meta tree.

    A subtree is host-placed when its estimate is known, its bytes fit
    the ``cost.maxHostBytes`` ceiling, and the host estimate strictly
    beats the device estimate (ties keep the device — the device's
    numbers only improve as inputs grow). Children of a host-placed
    subtree are not revisited: the placement is maximal by construction.
    """
    report = CostReport()
    report.skipped = _placement_gates(conf, meta.plan)
    _record("costPlanningRuns")
    if report.skipped is not None:
        return report
    ests = estimate_plan(meta.plan, conf)
    max_host = int(conf.get(C.COST_MAX_HOST_BYTES))
    explain = bool(conf.get(C.COST_EXPLAIN)) or \
        conf.explain in ("ALL", "NOT_ON_GPU")
    root_est = ests[id(meta.plan)]
    report.est_device_ms = root_est.device_ms
    report.est_host_ms = root_est.host_ms
    report.est_syncs = root_est.syncs

    def walk(m, depth: int):
        est = ests[id(m.plan)]
        placeable = m.on_device and est.subtree_bytes is not None and \
            est.subtree_bytes <= max_host and est.host_ms < est.device_ms
        if explain:
            b = "?" if est.bytes_out is None else f"{est.bytes_out:,}"
            report.lines.append(
                "  " * depth + f"{m.plan.name}: ~{b} bytes, device "
                f"{est.device_ms:.0f}ms/{est.syncs} syncs, host "
                f"{est.host_ms:.0f}ms"
                + (" -> HOST" if placeable else ""))
        if placeable:
            report.placements += 1
            report.nodes_host_placed += _mark_host(m)
            m.notes.append(
                f"cost model: host placement (est device "
                f"{est.device_ms:.0f}ms incl {est.syncs} syncs > host "
                f"{est.host_ms:.0f}ms over ~{est.subtree_bytes:,} bytes)")
            return                 # maximal subtree: stop descending
        for c in m.children:
            walk(c, depth + 1)

    walk(meta, 0)
    if report.placements:
        _record("costHostPlacements", report.placements)
        _record("costHostPlacedNodes", report.nodes_host_placed)
    return report
