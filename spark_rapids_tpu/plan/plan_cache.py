"""Parameterized plan cache: zero re-plan, zero re-trace repeated-query
serving (ROADMAP item 2; the serving half of the reference's
plan-once-per-query economics — the plugin rewrites Catalyst plans once
and Spark re-executes the cached physical plan per batch).

Every ``collect()`` today re-runs analysis -> pruning/pushdown ->
capability tagging -> cost placement -> conversion -> fusion from
scratch; only the jitted kernels are cached. For short queries that
host-side plan pipeline is a dominant share of latency (the flight
recorder makes it visible as non-device wall), and a serving tier
issuing the SAME query shape with new literals every call pays it —
plus, worse, a full kernel RE-TRACE, because literal values are
trace-time constants folded into the kernel-cache fingerprints.

This module fixes both with one mechanism:

1. :func:`parameterize` rewrites a logical plan's bindable literal
   leaves — numeric/bool/date operands of comparisons and arithmetic in
   filters and projections, plus ``limit(n)`` budgets — into positional
   BIND SLOTS (``("bindslot", i, dtype)`` Column nodes resolved to
   value-free :class:`~spark_rapids_tpu.exprs.bindslots.BindSlotExpr`
   leaves). Literals in structural positions (string widths, regex
   patterns, isin sets, pad/round/slice arguments, aggregate internals)
   are deliberately NOT hoisted: their values shape the traced program.
2. The parameterized shape keys a process-global LRU:
   ``(structural plan fingerprint incl. input schemas, conf snapshot)``.
   A hit returns the fully planned/fused/cost-placed
   :class:`~spark_rapids_tpu.plan.planner.PhysicalPlan` TEMPLATE.
3. :class:`BoundPlan` marries the shared template with THIS call's
   literal values. ``collect()`` installs them into the execution
   context, where kernel call sites (ops/basic.py, ops/fused.py) pass
   them as traced runtime inputs and host-side consumers (limit
   budgets, scan row-group pruning) resolve them as python values —
   so compiled executables are shared across bindings and a repeat
   execution goes straight to the execution funnel.

Correctness lines:

- Invalidation is conservative: ANY conf change misses (the snapshot
  keys the WHOLE raw conf — a superset of the cost/fusion/transport/
  wire keys that actually affect planning), schema/path/option changes
  miss structurally, and an armed fault schedule (conf or SRT_FAULTS
  env) BYPASSES the cache entirely — chaos targets per-plan state.
- Per-query state stays per-execution: ExecContext, owner tags, AQE
  replan decisions (parallel/replan.py keys them in ``ctx.cache``) and
  trace rings are fresh per collect; nothing writes back into the
  template.
- In-memory sources key by source-batch OBJECT identity; the key tuple
  holds strong references, so an id can never be recycled into a
  false hit (the LRU bound caps what that pins).
- Plans containing opaque callables (pandas UDF nodes, generate, etc.)
  raise :class:`Uncacheable` and plan fresh — correctness first.

``SRT_PLAN_CACHE=0`` (env) or ``spark.rapids.sql.planCache.enabled``
=false restores the plan-every-collect engine byte-for-byte (the CI
``plan-cache-off`` matrix entry runs the whole suite that way).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.exprs.bindslots import BindValue
from spark_rapids_tpu.ops.kernel_cache import schema_fingerprint
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import Column, LogicalPlan, canonical_node

# ---------------------------------------------------------------------------
# Process-global counters (bench.py's ``plan_cache`` JSON block)
# ---------------------------------------------------------------------------

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def _record(name: str, amount: float = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _COUNTERS.clear()


def plan_cache_enabled(conf) -> bool:
    """Conf key wins; else the SRT_PLAN_CACHE env (CI matrix hook); else
    the registered default."""
    if conf.raw.get(C.PLAN_CACHE_ENABLED.key) is not None:
        return bool(conf.get(C.PLAN_CACHE_ENABLED))
    env = os.environ.get("SRT_PLAN_CACHE")
    if env is not None:
        return env.strip() not in ("0", "false", "no")
    return bool(C.PLAN_CACHE_ENABLED.default)


# ---------------------------------------------------------------------------
# Literal hoisting (parameterization)
# ---------------------------------------------------------------------------

# Expression kinds whose DIRECT literal operands flow as pure data —
# evaluation expands the scalar into a column and the kernel shape never
# depends on the value. Everything else (strings: width buckets; round
# scales, substr bounds, isin sets, like patterns, ...) keeps its
# literal inline as a trace constant.
_SAFE_BINARY = {"add", "sub", "mul", "div", "mod",
                "eq", "lt", "le", "gt", "ge"}


def _bindable_dtype(v) -> Optional[dt.DataType]:
    """The slot dtype for a hoistable literal value — EXACTLY the
    inference ``exprs.base.lit`` applies, so a template plans with the
    same types the unhoisted plan would."""
    if isinstance(v, bool):
        return dt.BOOL
    if isinstance(v, int):
        return dt.INT32 if -2**31 <= v < 2**31 else dt.INT64
    if isinstance(v, float):
        return dt.FLOAT64
    return None


class _Hoister:
    """Collects hoisted values/dtypes in deterministic DFS order (two
    equal-shaped plans allocate identical slot numbering)."""

    def __init__(self):
        self.values: List[Any] = []
        self.dtypes: List[dt.DataType] = []

    def _slot(self, value, dtype) -> int:
        self.values.append(value)
        self.dtypes.append(dtype)
        return len(self.values) - 1

    def rewrite(self, c: Column) -> Column:
        node = c.node
        kind = node[0]
        hoist_at = (1, 2) if kind in _SAFE_BINARY else ()
        out: List[Any] = [kind]
        changed = False
        for idx, x in enumerate(node[1:], start=1):
            if isinstance(x, Column):
                if idx in hoist_at and x.node[0] == "lit":
                    t = _bindable_dtype(x.node[1])
                    if t is not None:
                        out.append(Column(
                            ("bindslot", self._slot(x.node[1], t), t)))
                        changed = True
                        continue
                nx = self.rewrite(x)
                changed |= nx is not x
                out.append(nx)
            elif isinstance(x, tuple):
                nx, tchanged = self._rewrite_tuple(x)
                out.append(nx if tchanged else x)
                changed |= tchanged
            else:
                out.append(x)
        if not changed:
            return c
        return Column(tuple(out))

    def _rewrite_tuple(self, t: tuple) -> Tuple[tuple, bool]:
        out: List[Any] = []
        changed = False
        for y in t:
            if isinstance(y, Column):
                ny = self.rewrite(y)
                changed |= ny is not y
                out.append(ny)
            elif isinstance(y, tuple):
                ny, ychanged = self._rewrite_tuple(y)
                out.append(ny if ychanged else y)
                changed |= ychanged
            else:
                out.append(y)
        return tuple(out), changed


def parameterize(plan: LogicalPlan):
    """Rewrite ``plan`` with bindable literals hoisted into slots.
    Returns ``(parameterized_plan, values, dtypes)``; the plan is
    returned unchanged (identity) where nothing hoists."""
    h = _Hoister()
    new = _walk(plan, h)
    return new, tuple(h.values), tuple(h.dtypes)


def _walk(plan: LogicalPlan, h: _Hoister) -> LogicalPlan:
    kids = [_walk(c, h) for c in plan.children]
    same_kids = all(a is b for a, b in zip(kids, plan.children))
    if isinstance(plan, L.LogicalFilter):
        cond = h.rewrite(plan.condition)
        if cond is plan.condition and same_kids:
            return plan
        return L.LogicalFilter(kids[0], cond)
    if isinstance(plan, L.LogicalProject):
        projections = [(n, h.rewrite(c)) for n, c in plan.projections]
        if same_kids and all(a[1] is b[1] for a, b in
                             zip(projections, plan.projections)):
            return plan
        return L.LogicalProject(kids[0], projections)
    if isinstance(plan, L.LogicalLimit) and isinstance(plan.n, int):
        # Limit budgets are host-side python ints: hoisted as BindValue
        # markers the limit execs resolve per execution.
        return L.LogicalLimit(kids[0], BindValue(h._slot(
            int(plan.n), dt.INT64)))
    if same_kids:
        return plan
    import copy
    cp = copy.copy(plan)
    cp.children = tuple(kids)
    return cp


# ---------------------------------------------------------------------------
# Structural plan keys
# ---------------------------------------------------------------------------

class Uncacheable(Exception):
    """This plan shape cannot be keyed safely (opaque callables, unknown
    node types): plan fresh every time."""


class _IdKey:
    """Identity-hashed strong reference: keys an in-memory source batch
    by OBJECT identity while pinning the object, so a garbage-collected
    id can never be recycled into a false cache hit."""

    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _IdKey) and other.obj is self.obj


def _canon_cols(pairs) -> Tuple:
    return tuple((n, canonical_node(c)) for n, c in pairs)


def plan_key(plan: LogicalPlan) -> Tuple:
    """Hashable structural fingerprint of a (parameterized) logical
    plan: node types, schemas, canonical expression ASTs (bind slots are
    value-free), join/grouping shapes. Two plans with equal keys must
    plan to semantically identical templates — the cache correctness
    contract (literal VALUES are excluded exactly where bind slots
    carry them at runtime)."""
    kids = tuple(plan_key(c) for c in plan.children)
    if isinstance(plan, L.InMemoryScan):
        # Source-batch OBJECT identity: the key tuple strong-refs the
        # batches, so a recycled id can never produce a false hit.
        return ("mem", schema_fingerprint(plan.source_schema),
                tuple(tuple(_IdKey(hb) for hb in p)
                      for p in plan.partitions))
    if isinstance(plan, L.FileScan):
        return ("scan", plan.fmt, tuple(plan.paths),
                schema_fingerprint(plan.source_schema),
                tuple(sorted((str(k), repr(v))
                             for k, v in plan.options.items())),
                canonical_node(plan.predicates))
    if isinstance(plan, L.LogicalRange):
        return ("range", plan.start, plan.end, plan.step,
                plan.num_partitions)
    if isinstance(plan, L.LogicalFilter):
        return ("filter", canonical_node(plan.condition)) + kids
    if isinstance(plan, L.LogicalProject):
        return ("project", _canon_cols(plan.projections)) + kids
    if isinstance(plan, L.LogicalAggregate):
        return ("agg", plan.grouping, _canon_cols(plan.group_by),
                _canon_cols(plan.aggregates)) + kids
    if isinstance(plan, L.LogicalWindow):
        return ("window", _canon_cols(plan.exprs), plan.spec_key()) + kids
    if isinstance(plan, L.LogicalSort):
        return ("sort", tuple(canonical_node(o)
                              for o in plan.orders)) + kids
    if isinstance(plan, L.LogicalLimit):
        n = plan.n
        return ("limit",
                ("bindval", n.slot) if isinstance(n, BindValue)
                else int(n)) + kids
    if isinstance(plan, L.LogicalRepartition):
        return ("repart", plan.num_partitions,
                tuple(canonical_node(k) for k in (plan.keys or ()))) + kids
    if isinstance(plan, L.LogicalUnion):
        return ("union",) + kids
    if isinstance(plan, L.LogicalJoin):
        return ("join", plan.join_type, plan.strategy,
                tuple(canonical_node(k) for k in plan.left_keys),
                tuple(canonical_node(k) for k in plan.right_keys),
                None if plan.condition is None
                else canonical_node(plan.condition)) + kids
    # Generate / pandas-UDF / ingest-exotic nodes carry opaque callables
    # or shapes this keyer does not model — refuse rather than guess.
    raise Uncacheable(plan.name)


def _conf_key(conf) -> Tuple:
    return tuple(sorted((k, repr(v)) for k, v in conf.raw.items()))


def _faults_armed(conf) -> bool:
    from spark_rapids_tpu import faults
    if str(conf.get(C.TEST_FAULTS) or "").strip():
        return True
    if os.environ.get("SRT_FAULTS", "").strip():
        return True
    return faults.injector() is not None


# ---------------------------------------------------------------------------
# The cache
# ---------------------------------------------------------------------------

class PlanCacheEntry:
    __slots__ = ("template", "dtypes", "nbinds")

    def __init__(self, template, dtypes):
        self.template = template
        self.dtypes = tuple(dtypes)
        self.nbinds = len(self.dtypes)


class PlanCache:
    """Bounded LRU of physical plan templates keyed by parameterized
    structure + conf snapshot."""

    def __init__(self, max_entries: int = 256):
        self._entries: "collections.OrderedDict[Any, PlanCacheEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def configure(self, max_entries: int) -> None:
        with self._lock:
            self.max_entries = max(int(max_entries), 1)
            self._evict()

    def lookup(self, key) -> Optional[PlanCacheEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _record("planCacheHits")
            else:
                self.misses += 1
                _record("planCacheMisses")
            return entry

    def insert(self, key, entry: PlanCacheEntry) -> PlanCacheEntry:
        """First writer wins: a concurrent planner of the same key keeps
        the stored template so every caller shares one exec tree."""
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                return existing
            self._entries[key] = entry
            self._evict()
            return entry

    def _evict(self) -> None:
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            self.evictions += 1
            _record("planCacheEvictions")

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "entries": len(self._entries)}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0


_CACHE = PlanCache()


def cache() -> PlanCache:
    """The process-global plan cache."""
    return _CACHE


# ---------------------------------------------------------------------------
# Bound plans
# ---------------------------------------------------------------------------

class BoundPlan:
    """Execution view over a shared plan template plus THIS call's
    literal bindings — the ``df.prepare()`` prepared-statement handle.
    Attribute access falls through to the template (root, meta, conf,
    cost_report, last_ctx ...); ``collect`` threads the bindings into
    the execution context."""

    def __init__(self, template, values, dtypes, cache_hit: bool):
        self.template = template
        self.bind_values = tuple(values)
        self.bind_dtypes = tuple(dtypes)
        self.cache_hit = bool(cache_hit)

    @property
    def provenance(self) -> str:
        return "plan-cache hit, bind-only" if self.cache_hit \
            else "plan-cache miss, template planned"

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "template"), name)

    def install(self, ctx) -> None:
        """Install the binding vector on a caller-built context (the
        non-collect funnels: to_jax)."""
        ctx.cache["plan_binds"] = self.bind_values
        ctx.cache["plan_bind_dtypes"] = self.bind_dtypes

    def collect(self, ctx=None, timeout_ms=None, cancel_event=None,
                priority=None, tenant=None):
        if self.cache_hit:
            _record("bindOnlyExecutions")
        return self.template.collect(
            ctx, timeout_ms=timeout_ms, cancel_event=cancel_event,
            bindings=(self.bind_values, self.bind_dtypes),
            plan_cache_hit=self.cache_hit, priority=priority,
            tenant=tenant)

    def explain(self, mode: str = "ALL") -> str:
        report = self.template.explain(mode)
        return (f"[{self.provenance}; "
                f"{len(self.bind_values)} bind slot(s)]\n{report}")


def plan_or_bind(conf, logical: LogicalPlan):
    """THE planning funnel behind ``DataFrame._physical``: parameterize,
    fingerprint, and either bind against a cached template (hit) or
    plan one and cache it (miss). Returns a :class:`BoundPlan`, or a
    plain :class:`PhysicalPlan` when the cache is disabled, bypassed
    (armed faults), or the shape is uncacheable."""
    from spark_rapids_tpu import monitoring
    from spark_rapids_tpu.plan.planner import Planner
    if not plan_cache_enabled(conf):
        return Planner(conf).plan(logical)
    if _faults_armed(conf):
        # Chaos schedules target per-plan state; a shared template would
        # couple independently-armed queries. Bypass, don't poison.
        _record("planCacheBypasses")
        return Planner(conf).plan(logical)
    t0 = time.perf_counter_ns()
    try:
        param, values, dtypes = parameterize(logical)
        key = (plan_key(param), _conf_key(conf))
        hash(key)
    except (Uncacheable, TypeError):
        _record("planCacheUncacheable")
        return Planner(conf).plan(logical)
    _CACHE.configure(int(conf.get(C.PLAN_CACHE_MAX_ENTRIES)))
    entry = _CACHE.lookup(key)
    hit = entry is not None
    if not hit:
        entry = _CACHE.insert(
            key, PlanCacheEntry(Planner(conf).plan(param), dtypes))
    dur = time.perf_counter_ns() - t0
    _record("planBindNs", dur)
    if monitoring.enabled():
        # The acceptance probe: steady-state plan+bind must stay in the
        # low single-digit ms (vs tens-to-hundreds for a full plan).
        monitoring.record_span(
            "plan-bind", "planning", monitoring.now_ns() - dur, dur,
            args={"planCacheHit": hit, "bindSlots": len(values)},
            level=monitoring.LEVEL_QUERY)
        monitoring.instant(
            "plan-cache-hit" if hit else "plan-cache-miss", "planning",
            args={"bindSlots": len(values)})
    return BoundPlan(entry.template, values, dtypes, hit)
