"""Plan rewrite: wrap -> tag -> convert (ref: GpuOverrides.scala:1991,
RapidsMeta.scala:189, GpuTransitionOverrides.scala).

The reference's crown jewel, rebuilt for the standalone engine:
- every logical node and expression is wrapped in a Meta carrying
  fallback ``reasons`` (RapidsMeta.willNotWorkOnGpu analog);
- per-node kill-switch configs are auto-registered
  (``spark.rapids.sql.exec.<Node>`` / ``spark.rapids.sql.expression.<Kind>``
  — RapidsMeta confKey, SURVEY.md §5.6);
- incompat expressions (locale-sensitive case mapping, order-dependent
  float aggregation) fall back to the host engine unless
  ``spark.rapids.sql.incompatibleOps.enabled`` (GpuOverrides incompat
  flags);
- conversion emits the physical Exec tree with explicit
  HostToDevice/DeviceToHost transitions at placement changes
  (GpuTransitionOverrides insertColumnarToGpu/FromGpu), two-stage
  aggregation across hash exchanges, range exchanges under global sorts,
  and broadcast-vs-shuffle join planning;
- ``explain`` renders the will/will-not-run report
  (RapidsMeta.explain:291), and test mode
  ``spark.rapids.sql.test.enabled`` fails any query with a
  non-allowlisted host node (GpuTransitionOverrides.assertIsOnTheGpu:391).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference, Expression
from spark_rapids_tpu.ops import (
    AggSpec, Average, Count, CountStar, ExpandExec, FilterExec, First,
    GlobalLimitExec, HashAggregateExec, Last, LocalLimitExec, Max, Min,
    ProjectExec, RangeExec, SortExec, SortOrder, Sum, UnionExec)
from spark_rapids_tpu.ops.base import (
    DeviceToHostExec, Exec, HostToDeviceExec, InMemorySourceExec)
from spark_rapids_tpu.ops.join import (
    BroadcastHashJoinExec, BroadcastNestedLoopJoinExec,
    ShuffledHashJoinExec)
from spark_rapids_tpu.parallel import (
    HashPartitioning, RangePartitioning, RoundRobinPartitioning,
    ShuffleExchangeExec, SinglePartitioning)
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import (
    Column, LogicalPlan, ResolutionError, resolve)


# ---------------------------------------------------------------------------
# Expression tagging rules (GpuOverrides expr registry analog)
# ---------------------------------------------------------------------------

# Kinds whose device implementation can differ from the JVM in corner cases.
_INCOMPAT_EXPRS = {
    "upper": "locale-sensitive case mapping is ASCII-only on TPU",
    "lower": "locale-sensitive case mapping is ASCII-only on TPU",
    "initcap": "locale-sensitive case mapping is ASCII-only on TPU",
}

# Kinds that execute on the host even inside the device plan (regex etc.).
_HOST_ROUNDTRIP_EXPRS = {"regexp_replace", "regexp_extract", "translate",
                         "lpad", "rpad", "replace"}

# Transcendentals whose XLA lowering can round differently from
# java.lang.Math (GpuOverrides marks the same family incompat); allowed by
# spark.rapids.sql.improvedFloatOps.enabled or incompatibleOps.enabled.
_IMPROVED_FLOAT_EXPRS = {
    "exp", "expm1", "log", "log10", "log2", "log1p", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "cbrt", "pow", "atan2",
}

# Kinds whose value depends on the task context rather than column inputs.
_CONTEXTUAL_EXPRS = {
    "rand": "nondeterministic (distribution-equal to Spark, not "
            "sequence-equal)",
    "input_file_name": "reads the per-batch host file path; disables "
                       "projection jit",
}

# All task-context kinds; only Project/Filter thread an EvalContext, so
# anywhere else these would silently evaluate with pid=0/row_base=0
# (Spark's CheckAnalysis draws the same line for nondeterministic exprs).
_CONTEXTUAL_KINDS = {"rand", "spark_partition_id",
                     "monotonically_increasing_id", "input_file_name"}


def _column_kinds(c: Column, out: set):
    out.add(c.node[0])
    for x in c.node[1:]:
        if isinstance(x, Column):
            _column_kinds(x, out)
        elif isinstance(x, tuple):
            for y in x:
                if isinstance(y, Column):
                    _column_kinds(y, out)
                elif isinstance(y, tuple):
                    for z in y:
                        if isinstance(z, Column):
                            _column_kinds(z, out)
    return out


def _uses_input_file(plan: LogicalPlan) -> bool:
    """True when any Project/Filter column references input_file_name():
    scans must then stay per-file (the reference's disableCoalesceUntilInput
    fence, GpuExpressions.scala:64-74) so the published path is exact."""
    cols: List[Column] = []
    if isinstance(plan, L.LogicalProject):
        cols = [c for _, c in plan.projections]
    elif isinstance(plan, L.LogicalFilter):
        cols = [plan.condition]
    for c in cols:
        if "input_file_name" in _column_kinds(c, set()):
            return True
    return any(_uses_input_file(ch) for ch in plan.children)


def _forbid_contextual(c: Column, where: str):
    """Analysis-time guard: contextual expressions are only valid where the
    evaluating operator threads an EvalContext (select/filter)."""
    bad = _column_kinds(c, set()) & _CONTEXTUAL_KINDS
    if bad:
        raise ResolutionError(
            f"nondeterministic/task-context expression(s) {sorted(bad)} are "
            f"only supported in select/filter/with_column, not in {where} "
            "(evaluate them into a column first)")


def _expr_conf_key(kind: str) -> str:
    return f"spark.rapids.sql.expression.{kind}"


def _exec_conf_key(name: str) -> str:
    return f"spark.rapids.sql.exec.{name}"


def tag_column(c: Column, conf: C.TpuConf, reasons: List[str],
               notes: List[str], schema=None):
    """Walk an untyped Column AST, collecting fallback reasons. ``schema``
    (when available) enables type-directed gates like the float<->string
    cast checks (GpuCast meta tagging, GpuOverrides.scala:442)."""
    kind = c.node[0]
    if not conf.is_op_enabled(_expr_conf_key(kind)):
        reasons.append(f"expression {kind} disabled by "
                       f"{_expr_conf_key(kind)}")
    if kind in _INCOMPAT_EXPRS and not conf.incompatible_ops:
        reasons.append(
            f"expression {kind} is incompatible ({_INCOMPAT_EXPRS[kind]}); "
            "enable spark.rapids.sql.incompatibleOps.enabled to allow")
    if kind in _IMPROVED_FLOAT_EXPRS and not conf.incompatible_ops and \
            not conf.get(C.IMPROVED_FLOAT_OPS):
        reasons.append(
            f"expression {kind} can round differently from java.lang.Math "
            "on TPU; enable spark.rapids.sql.improvedFloatOps.enabled")
    if kind == "cast" and schema is not None:
        try:
            src = resolve(c.node[1], schema).data_type()
        except Exception:
            src = None
        dst = c.node[2]
        if src is not None and src.is_floating and dst.is_string and \
                not conf.get(C.CAST_FLOAT_TO_STRING):
            reasons.append(
                "casting floats to string formats differently from Spark; "
                "enable spark.rapids.sql.castFloatToString.enabled")
        if src is not None and src.is_string and dst.is_floating and \
                not conf.get(C.CAST_STRING_TO_FLOAT):
            reasons.append(
                "casting strings to float differs in corner cases; "
                "enable spark.rapids.sql.castStringToFloat.enabled")
    if kind in _HOST_ROUNDTRIP_EXPRS:
        notes.append(f"expression {kind} runs via a host roundtrip")
    if kind == "pyudf":
        fname = getattr(c.node[1], "__name__", "udf")
        notes.append(
            f"python UDF {fname!r} could not be compiled to native "
            f"expressions ({c.node[4]}); runs via host roundtrip "
            "(GpuArrowEvalPythonExec-style fallback)")
    if kind in _CONTEXTUAL_EXPRS:
        notes.append(f"expression {kind}: {_CONTEXTUAL_EXPRS[kind]}")
    for x in c.node[1:]:
        if isinstance(x, Column):
            tag_column(x, conf, reasons, notes, schema)
        elif isinstance(x, tuple):
            for y in x:
                if isinstance(y, Column):
                    tag_column(y, conf, reasons, notes, schema)
                elif isinstance(y, tuple):
                    for z in y:
                        if isinstance(z, Column):
                            tag_column(z, conf, reasons, notes, schema)


def _float_agg_reasons(agg_col: Column, schema, conf: C.TpuConf,
                       reasons: List[str]):
    """Order-dependent float aggregation gate (GpuOverrides checks on
    variableFloatAgg, RapidsConf.scala:149 analog in config.py)."""
    kind = agg_col.node[1]
    child = agg_col.node[2]
    if kind in ("sum", "avg") and child is not None:
        try:
            t = resolve(child, schema).data_type()
        except Exception:
            return
        if t.is_floating and not conf.get(C.VARIABLE_FLOAT_AGG):
            reasons.append(
                f"{kind} over {t.name} can vary with evaluation order on "
                "TPU; enable spark.rapids.sql.variableFloatAgg.enabled")


# ---------------------------------------------------------------------------
# Node meta (RapidsMeta analog)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NodeMeta:
    plan: LogicalPlan
    children: List["NodeMeta"]
    reasons: List[str] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)
    # Cost-based placement (plan/cost.py): True flips this node to the
    # host engine as a PLACEMENT choice, not a capability fallback —
    # kept separate from ``reasons`` so explain reasons and test-mode
    # allowlists keep their capability meaning.
    cost_host: bool = False

    @property
    def on_device(self) -> bool:
        return not self.reasons and not self.cost_host

    def explain_lines(self, depth: int = 0, not_on_device_only=False):
        mark = "*" if self.on_device else "!"
        line = "  " * depth + f"{mark}Exec <{self.plan.name}>"
        if self.reasons:
            line += " cannot run on TPU because " + "; ".join(self.reasons)
        elif self.notes:
            line += " (" + "; ".join(self.notes) + ")"
        out = [] if (not_on_device_only and self.on_device and
                     not self.notes) else [line]
        for ch in self.children:
            out.extend(ch.explain_lines(depth + 1, not_on_device_only))
        return out


def wrap_and_tag(plan: LogicalPlan, conf: C.TpuConf) -> NodeMeta:
    meta = NodeMeta(plan, [wrap_and_tag(c, conf) for c in plan.children])
    reasons, notes = meta.reasons, meta.notes
    if not conf.sql_enabled:
        reasons.append("spark.rapids.sql.enabled is false")
    if not conf.is_op_enabled(_exec_conf_key(plan.name)):
        reasons.append(f"disabled by {_exec_conf_key(plan.name)}")

    if isinstance(plan, L.FileScan):
        fmt_gates = {
            "parquet": (C.ENABLE_PARQUET, C.ENABLE_PARQUET_READ),
            "orc": (C.ENABLE_ORC, C.ENABLE_ORC_READ),
            "csv": (C.ENABLE_CSV, C.ENABLE_CSV_READ),
        }
        for entry in fmt_gates.get(plan.fmt, ()):
            if not bool(conf.get(entry)):
                reasons.append(f"{plan.fmt} scan disabled by {entry.key}")
    elif isinstance(plan, L.LogicalFilter):
        tag_column(plan.condition, conf, reasons, notes,
                   plan.child.schema)
    elif isinstance(plan, L.LogicalProject):
        for _, c in plan.projections:
            tag_column(c, conf, reasons, notes, plan.child.schema)
    elif isinstance(plan, L.LogicalAggregate):
        for _, c in plan.group_by:
            _forbid_contextual(c, "group_by")
            tag_column(c, conf, reasons, notes, plan.child.schema)
        for _, c in plan.aggregates:
            _forbid_contextual(c, "aggregates")
            ac = _unalias(c)
            inner = ac.node[2] if ac.node[0] in ("agg", "aggd") else None
            if inner is not None:
                tag_column(inner, conf, reasons, notes, plan.child.schema)
            if ac.node[0] in ("agg", "aggd"):
                _float_agg_reasons(ac, plan.child.schema, conf, reasons)
    elif isinstance(plan, L.LogicalSort):
        for o in plan.orders:
            inner = o.node[1] if o.node[0] == "sortorder" else o
            _forbid_contextual(inner, "order_by")
            tag_column(inner, conf, reasons, notes, plan.child.schema)
    elif isinstance(plan, L.LogicalJoin):
        if plan.strategy == "shuffle" and plan.left_keys and \
                not conf.get(C.REPLACE_SORT_MERGE_JOIN):
            reasons.append(
                "co-partitioned (sort-merge-shaped) join replacement "
                "disabled by spark.rapids.sql.replaceSortMergeJoin.enabled")
        ls = plan.children[0].schema
        rs = plan.children[1].schema
        for k in plan.left_keys:
            _forbid_contextual(k, "join keys")
            tag_column(k, conf, reasons, notes, ls)
        for k in plan.right_keys:
            _forbid_contextual(k, "join keys")
            tag_column(k, conf, reasons, notes, rs)
        if plan.condition is not None:
            _forbid_contextual(plan.condition, "join condition")
            tag_column(plan.condition, conf, reasons, notes,
                       tuple(ls) + tuple(rs))
    elif isinstance(plan, L.LogicalRepartition):
        for k in (plan.keys or []):
            _forbid_contextual(k, "repartition keys")
            tag_column(k, conf, reasons, notes, plan.child.schema)
    elif isinstance(plan, L.LogicalGenerate):
        for c in plan.elements:
            _forbid_contextual(c, "explode elements")
            tag_column(c, conf, reasons, notes, plan.child.schema)
    elif isinstance(plan, L.LogicalWindow):
        for c in plan.window.partition_cols:
            _forbid_contextual(c, "window partition keys")
            tag_column(c, conf, reasons, notes, plan.child.schema)
        for o in plan.window.order_cols:
            inner = o.node[1] if o.node[0] == "sortorder" else o
            _forbid_contextual(inner, "window order keys")
            tag_column(inner, conf, reasons, notes, plan.child.schema)
        for _, fn_col in plan.exprs:
            node = fn_col.node
            if len(node) > 2 and isinstance(node[2], Column):
                tag_column(node[2], conf, reasons, notes,
                           plan.child.schema)
    return meta


def merge_windows(plan: LogicalPlan) -> LogicalPlan:
    """Collapse chains of LogicalWindow nodes with the SAME window spec
    into one multi-expression node: each node plans an exchange + a
    partition sort, so N window columns over one spec would otherwise
    shuffle and sort N times (Spark's ExtractWindowExpressions groups the
    same way before planning one Window operator)."""
    kids = [merge_windows(c) for c in plan.children]
    if not all(a is b for a, b in zip(kids, plan.children)):
        import copy
        plan = copy.copy(plan)
        plan.children = tuple(kids)
    if isinstance(plan, L.LogicalWindow) and \
            isinstance(plan.child, L.LogicalWindow) and \
            plan.spec_key() == plan.child.spec_key():
        inner = plan.child
        # Only merge when the outer expressions don't read the inner
        # node's outputs (a window fn over another window's result must
        # stay a separate pass).
        from spark_rapids_tpu.plan.pruning import refs_of
        refs: set = set()
        for _, fn_col in plan.exprs:
            refs_of(fn_col, refs)
        if not refs & {n for n, _ in inner.exprs}:
            return merge_windows(L.LogicalWindow(
                inner.child, list(inner.exprs) + list(plan.exprs),
                inner.window))
    return plan


# ---------------------------------------------------------------------------
# Aggregate resolution
# ---------------------------------------------------------------------------

def _unalias(c: Column) -> Column:
    while c.node[0] == "alias":
        c = c.node[1]
    return c


def resolve_agg(c: Column, schema) -> "AggFunctionLike":
    c = _unalias(c)
    assert c.node[0] in ("agg", "aggd"), f"not an aggregate: {c.node[0]}"
    distinct = c.node[0] == "aggd"
    kind = c.node[1]
    child_col = c.node[2]
    child = None if child_col is None else resolve(child_col, schema)
    if distinct and kind in ("first", "last"):
        raise L.ResolutionError(f"{kind}(DISTINCT) is not meaningful")
    if kind == "count":
        fn = CountStar(None) if child is None else Count(child)
    elif kind == "sum":
        fn = Sum(child)
    elif kind == "min":
        fn = Min(child)
    elif kind == "max":
        fn = Max(child)
    elif kind == "avg":
        fn = Average(child)
    elif kind == "first":
        fn = First(child, c.node[3] if len(c.node) > 3 else True)
    elif kind == "last":
        fn = Last(child, c.node[3] if len(c.node) > 3 else True)
    else:
        raise L.ResolutionError(f"unknown aggregate {kind!r}")
    # min/max(DISTINCT) == min/max: drop the flag so no rewrite happens.
    fn.is_distinct = distinct and kind not in ("min", "max")
    if fn.is_distinct:
        # Structural key of the (unresolved) input expression, for the
        # single-distinct-input restriction check.
        fn.distinct_key = L.canonical_node(child_col)
    return fn


AggFunctionLike = object


# ---------------------------------------------------------------------------
# Conversion (convertIfNeeded + transition insertion)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PhysicalPlan:
    """Planner output: root exec + which engine the root runs on + the
    tagged meta tree for explain/test-mode + the conf the query was
    planned with (runtime-read configs must see the same values)."""

    root: Exec
    root_on_device: bool
    meta: NodeMeta
    conf: "C.TpuConf" = dataclasses.field(default_factory=C.TpuConf)

    def explain(self, mode: str = "ALL") -> str:
        lines = self.meta.explain_lines(
            not_on_device_only=(mode.upper() == "NOT_ON_GPU"))
        from spark_rapids_tpu.plan.fusion import collect_fused
        fused = collect_fused(self.root)
        if fused:
            # Render fused stages with their member operator names so the
            # physical shape (and each stage's metrics owner) stays
            # readable next to the logical fallback report.
            lines.append(f"Fused stages: {len(fused)}")
            for i, f in enumerate(fused):
                members = ", ".join(type(o).__name__ for o in f.ops)
                lines.append(f"  *Stage #{i} <{f.name}> fuses [{members}]")
        report = getattr(self, "cost_report", None)
        if report is not None and (report.placements or report.lines or
                                   bool(self.conf.get(C.COST_EXPLAIN))):
            lines.extend(report.explain_lines())
        return "\n".join(lines)

    def collect(self, ctx=None, timeout_ms=None, cancel_event=None,
                bindings=None, plan_cache_hit=None, priority=None,
                tenant=None):
        """``bindings`` is the plan cache's ``(values, dtypes)`` pair for
        a parameterized template: installed into every execution
        context (including fresh-context retries) so bind slots, limit
        budgets and scan predicates resolve to THIS call's literals.
        ``plan_cache_hit`` (when not None) records the per-tenant
        plan-cache outcome on the Scheduler@query entry.

        ``priority``/``tenant`` feed the QoS subsystem (parallel/qos/):
        the priority class routes the query through the weighted-fair
        queue, the tenant tag enforces per-tenant quotas, and
        ``timeout_ms`` doubles as the deadline the cost estimate is
        tested against at admit time. With QoS off, both collapse to
        pure attribution on the ticket."""
        import time as _time

        from spark_rapids_tpu import faults, monitoring
        from spark_rapids_tpu.memory.oom import (
            backoff_delay_ms, is_transient_error, reset_degradation)
        from spark_rapids_tpu.ops.base import (ExecContext, Metrics,
                                               query_metrics_entry)
        from spark_rapids_tpu.parallel import scheduler as SC
        from spark_rapids_tpu.parallel import stages as S
        owned = ctx is None
        # Adopt the trace + telemetry configuration BEFORE admission so
        # the admission-queue span AND the rejection counters of THIS
        # query record (a shed query never reaches the dispatch funnel).
        monitoring.maybe_configure(self.conf)
        monitoring.telemetry.maybe_configure(self.conf)
        # Multi-query admission (parallel/scheduler.py): one ticket per
        # top-level collect. A thread already carrying a token (a nested
        # collect issued by this same query — e.g. a gated write) rides
        # the existing admission instead of deadlocking on a second
        # slot. Caller-provided contexts are the caller's query.
        ticket = None
        mgr = None
        if owned and faults.get_query_token() is None:
            mgr = SC.get_query_manager(self.conf)
            # The admission cost estimate: the plan's device+host
            # wall-clock projection (plan/cost.py). Plan-cache hits
            # reuse the template's CostReport, so repeat shapes carry
            # their SJF ordering key for free.
            est = getattr(self, "cost_report", None)
            est_ms = None
            if est is not None and est.skipped is None:
                est_ms = float(est.est_device_ms) + float(est.est_host_ms)
            ticket = mgr.admit(self.conf, cancel=cancel_event,
                               priority=priority, tenant=tenant,
                               cost_ms=est_ms, deadline_ms=timeout_ms)
            ticket.arm_deadline(timeout_ms)
            faults.set_query_token(ticket.token)
        ctx = ctx or ExecContext(self.conf, query=ticket)

        def install_bindings(c):
            if bindings is not None:
                c.cache["plan_binds"] = tuple(bindings[0])
                c.cache["plan_bind_dtypes"] = tuple(bindings[1])

        install_bindings(ctx)
        # The ring the flight recorder attributes this query's events to
        # (trace_export / explain_analyze read it off last_ctx).
        if ticket is not None:
            trace_qid = ticket.token.query_id
        else:
            tok = faults.get_query_token()
            trace_qid = tok.query_id if tok is not None else 0
        ctx.cache.setdefault("trace_query", trace_qid)
        if ticket is not None:
            mgr.register_context(ticket, ctx)
            sched = SC.metrics_entry(ctx)
            sched.add("admitted", 1)
            sched.add("queuedMs", ticket.queued_ms)
            if ticket.qos_class is not None:
                sched.add(f"class.{ticket.qos_class}", 1)
            if ticket.tenant is not None:
                sched.add(f"tenant.{ticket.tenant}", 1)
            if plan_cache_hit is not None:
                # Per-tenant plan-cache stats (plan/plan_cache.py): a
                # hit means this execution was bind-only — zero
                # re-plan, zero re-trace.
                SC.record_plan_cache(ctx, plan_cache_hit)
        # Cost@query audit trail: static placement decisions land here at
        # admission; runtime re-planning (parallel/replan.py) adds its
        # demotion counters to the same entry during execution.
        report = getattr(self, "cost_report", None)
        if report is not None and report.skipped is None:
            cm = query_metrics_entry(ctx, "Cost")
            cm.add("placements", report.placements)
            cm.add("hostPlacedNodes", report.nodes_host_placed)
            cm.add("estDeviceMs", report.est_device_ms)
            cm.add("estHostMs", report.est_host_ms)
            cm.add("estSyncs", report.est_syncs)
        # Arm the fault schedule ONCE per query (not per attempt: a
        # retried attempt must run against the REMAINING schedule, or a
        # count-based transient fault re-fires forever), and clear any
        # batch-target degradation a previous query's OOM ladder left.
        faults.maybe_configure(self.conf)
        reset_degradation()
        # Failure recovery ladder (SURVEY §5.3 + lineage-scoped recovery,
        # parallel/stages.py), scoped-smallest-first:
        #
        # 1. STAGE RECOMPUTE — a failure attributable to one stage's lost
        #    durable output (lostoutput injection, persistent checksum
        #    failure of a materialized exchange buffer) invalidates just
        #    that stage and re-runs the collect on the SAME context:
        #    every sibling stage serves its cached materialization, so
        #    only the lost lineage recomputes. Bounded by
        #    spark.rapids.sql.recovery.maxStageRecomputes.
        # 2. SAME-CONTEXT TRANSIENT RETRY — the first transient
        #    backend/tunnel error also retries on the same context
        #    (materialized stage outputs are data at rest; discarding
        #    them re-runs work the failure never touched).
        # 3. WHOLE-QUERY RETRY — repeated transients (possibly poisoned
        #    device state) or an unattributable/budget-exhausted loss
        #    fall back to a fresh context, with exponential backoff +
        #    deterministic jitter, bounded by the per-query budget.
        #
        # Owned contexts only: a caller-provided context may hold state
        # the caller still needs.
        max_retries = max(int(self.conf.get(C.RETRY_TRANSIENT_MAX)), 0)
        base_ms = int(self.conf.get(C.RETRY_BACKOFF_MS))
        max_ms = int(self.conf.get(C.RETRY_MAX_BACKOFF_MS))
        seed = int(self.conf.get(C.TEST_FAULTS_SEED))
        graph = None
        if owned and bool(self.conf.get(C.STAGE_RECOVERY_ENABLED)):
            graph = S.build_stage_graph(self.root)
        # Cluster mode (parallel/cluster/, ISSUE 13): dispatch the
        # stage DAG to registered worker processes and fetch their
        # committed outputs locally. None (disabled, no dispatchable
        # stage, unpicklable plan, host fallback, mesh transport) =
        # execute locally exactly as before.
        qrun = None
        if owned and bool(self.conf.get(C.CLUSTER_ENABLED)):
            from spark_rapids_tpu.parallel import cluster as CL
            qrun = CL.maybe_prepare(self, ctx, graph)
        stage_budget = max(
            int(self.conf.get(C.RECOVERY_MAX_STAGE_RECOMPUTES)), 0)
        stage_recomputes = 0
        same_ctx_retry_used = False
        preempt_count = 0
        attempt = 0
        import logging
        log = logging.getLogger("spark_rapids_tpu")
        t0_query = _time.perf_counter()
        status = "ok"
        err_text = None
        try:
            while True:
                try:
                    if qrun is not None:
                        # Dispatch barrier: every remote stage task is
                        # committed to the spool before the local
                        # collect starts fetching. Dispatch failures
                        # (worker exhaustion, timeout) unwind through
                        # the same ladder below.
                        qrun.run(ctx)
                    return self.root.collect(ctx,
                                             device=self.root_on_device)
                except Exception as e:
                    if not owned:
                        raise
                    # Cancelled/deadlined queries unwind through every
                    # retry rung: whatever error the cancellation
                    # surfaced as (a killed stall, a poll raise, a torn
                    # stream), the query is done — converting here also
                    # stops the transient ladder from retrying it.
                    if ticket is not None and ticket.token.cancelled():
                        if not isinstance(e, faults.QueryCancelledError):
                            raise ticket.token.error() from e
                        raise
                    # Rung 0: class-aware preemption (ISSUE 18) — not a
                    # failure at all. The classed TPU gate asked this
                    # query to yield at a partition boundary; spill its
                    # live device buffers through the existing ladder,
                    # wait for the preemptor to drain, then re-collect
                    # on the SAME context: durable stage outputs serve
                    # from their materializations, so resumption loses
                    # no completed work and stays byte-identical.
                    if isinstance(e, faults.QueryPreemptedError) \
                            and ticket is not None:
                        preempt_count += 1
                        budget = max(int(self.conf.get(
                            C.PREEMPTION_MAX_PER_QUERY)), 0)
                        if preempt_count > budget:
                            # Budget spent: this query never yields
                            # again — starving a victim to death on
                            # repeated preemptions is worse than one
                            # slow interactive query.
                            ticket.token.preempt_enabled = False
                            ticket.token.clear_preempt()
                            continue
                        try:
                            # Chaos checkpoint: seeded faults can land
                            # exactly mid-preemption-spill (armed as
                            # kind@preempt.spill) — they re-enter the
                            # ladder below like any execution fault.
                            faults.fault_point("preempt.spill")
                            freed = 0
                            if bool(self.conf.get(
                                    C.PREEMPTION_SPILL_ENABLED)) \
                                    and ctx._catalog is not None:
                                # The victim vacates HBM for the
                                # preemptor via the same device->host
                                # ladder the OOM path uses (handles stay
                                # owned: nothing leaks, everything pages
                                # back on resume).
                                freed = ctx._catalog.handle_oom()
                            sched = SC.metrics_entry(ctx)
                            sched.add("preemptions", 1)
                            SC._record("preemptions")
                            monitoring.instant(
                                "query-preempted", "recovery",
                                qid=trace_qid,
                                args={"preemptor": e.preemptor or "-",
                                      "spilledBytes": freed,
                                      "count": preempt_count})
                            monitoring.telemetry.inc(
                                "srt_preemptions",
                                **{"class": str(ticket.qos_class
                                                or "-")})
                            log.warning(
                                "query %d preempted by a %s query "
                                "(%d/%d, spilled %d bytes); resuming "
                                "after the preemptor drains", trace_qid,
                                e.preemptor or "higher-priority",
                                preempt_count, budget, freed)
                            from spark_rapids_tpu.memory.stores import \
                                get_tpu_semaphore
                            sem = get_tpu_semaphore(max(
                                int(self.conf.get(
                                    C.CONCURRENT_TPU_TASKS)), 1))
                            t0_pre = _time.perf_counter()
                            # Blocks in class order until a permit
                            # would be ours again — i.e. the preemptor
                            # (and anything ranked ahead) drained.
                            # Cancellation/deadline aborts the wait via
                            # the token.
                            sem.wait_resume(ticket.token)
                            ticket.token.clear_preempt()
                            preempted_ms = (_time.perf_counter()
                                            - t0_pre) * 1e3
                            resumed = S.materialized_stage_count(
                                ctx, graph)
                            sched.add("preemptedMs", preempted_ms)
                            sched.add("resumedStages", resumed)
                            SC._record("preemptedMs", preempted_ms)
                            SC._record("resumedStages", resumed)
                            monitoring.instant(
                                "query-resumed", "recovery",
                                qid=trace_qid,
                                args={"preemptedMs":
                                      round(preempted_ms, 2),
                                      "resumedStages": resumed})
                            # Mid-resume chaos checkpoint
                            # (kind@preempt.resume).
                            faults.fault_point("preempt.resume")
                            continue
                        except faults.QueryCancelledError:
                            raise
                        except Exception as e2:
                            # A fault landed mid-spill or mid-resume:
                            # clear the preempt flag (the gate wait, if
                            # reached, already honored it) and re-enter
                            # the ladder with the NEW error — stage
                            # recompute / transient retry / fresh
                            # context apply exactly as for any
                            # execution-time fault.
                            ticket.token.clear_preempt()
                            e = e2
                    # Rung 1: lineage-scoped stage recompute.
                    st = S.stage_for_error(graph, e)
                    if st is not None and stage_recomputes < stage_budget:
                        S.invalidate_stage(ctx, st)
                        S.record_recompute(ctx, st)
                        if qrun is not None:
                            # The lost output is a REMOTE stage's spool:
                            # requeue its task so a worker rewrites it
                            # before the re-collect fetches again.
                            qrun.recompute(st.stage_id)
                        stage_recomputes += 1
                        log.warning(
                            "lost stage output (%s, recompute %d/%d); "
                            "recomputing only that stage: %s",
                            st.name, stage_recomputes, stage_budget, e)
                        continue
                    if not is_transient_error(e) or attempt >= max_retries:
                        raise
                    delay_ms = backoff_delay_ms(attempt, base_ms, max_ms,
                                                seed)
                    faults.record("retriesAttempted")
                    if graph is not None and not same_ctx_retry_used:
                        # Rung 2: retry on the same context — completed
                        # stages serve their durable outputs instead of
                        # recomputing.
                        same_ctx_retry_used = True
                        log.warning(
                            "transient device error (attempt %d/%d), "
                            "retrying on the same context in %.0fms "
                            "(materialized stage outputs are kept): %s",
                            attempt + 1, max_retries, delay_ms, e)
                        _time.sleep(delay_ms / 1000.0)
                    else:
                        # Rung 3: whole-query retry on a fresh context.
                        log.warning(
                            "transient device error (attempt %d/%d), "
                            "retrying query on a fresh context in "
                            "%.0fms: %s",
                            attempt + 1, max_retries, delay_ms, e)
                        _time.sleep(delay_ms / 1000.0)
                        if qrun is not None:
                            qrun.reset()
                        ctx.close()
                        ctx = ExecContext(self.conf, query=ticket)
                        install_bindings(ctx)
                        ctx.cache.setdefault("trace_query", trace_qid)
                        if ticket is not None:
                            mgr.register_context(ticket, ctx)
                        if qrun is not None:
                            qrun.install(ctx)
                    rec = query_metrics_entry(ctx, "Recovery")
                    rec.add("retriesAttempted", 1)
                    attempt += 1
        except BaseException as e:
            status = "error"
            err_text = f"{type(e).__name__}: {e}"
            raise
        finally:
            if ticket is not None:
                # Teardown accounting BEFORE the context close captures
                # the leak report: cancelled vs deadline-killed.
                if ticket.token.cancelled():
                    sched = SC.metrics_entry(ctx)
                    if ticket.token.reason == "deadline exceeded":
                        sched.add("deadlineKills", 1)
                        SC._record("deadlineKills")
                        monitoring.instant(
                            "query-deadline-killed", "recovery",
                            qid=trace_qid)
                    else:
                        sched.add("cancelled", 1)
                        SC._record("cancelled")
                        monitoring.instant(
                            "query-cancelled", "recovery",
                            args={"reason": ticket.token.reason},
                            qid=trace_qid)
                faults.set_query_token(None)
                mgr.finish(ticket)
            if qrun is not None:
                # Retire the dispatch state and the query's spool tree
                # BEFORE the context close: sessions opened on it are
                # keep_on_close, so the coordinator owns this cleanup.
                qrun.finish()
            # Live telemetry + persistent event log, BEFORE the context
            # close (the record reads ctx.metrics and the trace ring).
            if ticket is not None and ticket.token.cancelled():
                status = ("deadline"
                          if ticket.token.reason == "deadline exceeded"
                          else "cancelled")
            qos_class = ticket.qos_class if ticket is not None else None
            q_tenant = ticket.tenant if ticket is not None else None
            dur_ms = (_time.perf_counter() - t0_query) * 1e3
            lbls = {"class": str(qos_class or "-"),
                    "tenant": str(q_tenant or "-")}
            monitoring.telemetry.inc("srt_queries", status=status, **lbls)
            monitoring.telemetry.observe("srt_query_latency_ms", dur_ms,
                                         **lbls)
            monitoring.history.log_query(
                self, ctx, query_id=trace_qid, status=status,
                qos_class=qos_class, tenant=q_tenant,
                duration_ms=dur_ms, error=err_text)
            # Metrics survive the collect for DataFrame.metrics().
            self.last_ctx = ctx
            if owned:
                ctx.close()

    def host_fallback_nodes(self) -> List[str]:
        out = []

        def rec(m: NodeMeta):
            if not m.on_device:
                out.append(m.plan.name)
            for c in m.children:
                rec(c)
        rec(self.meta)
        return out


class Planner:
    """Converts a tagged logical plan into the physical Exec tree."""

    def __init__(self, conf: Optional[C.TpuConf] = None):
        self.conf = conf or C.TpuConf()

    # -- public --------------------------------------------------------------
    def plan(self, logical: LogicalPlan) -> PhysicalPlan:
        from spark_rapids_tpu.plan.pruning import (
            prune_columns, pushdown_filters)
        logical = pushdown_filters(prune_columns(merge_windows(logical)))
        self._force_perfile = _uses_input_file(logical)
        meta = wrap_and_tag(logical, self.conf)
        # Cost-based placement (plan/cost.py): flip whole maximal
        # subtrees to the host engine when the footer-stats estimate
        # says the sync floor can't amortize. Runs after tagging so
        # capability fallbacks already shaped ``on_device``.
        from spark_rapids_tpu.plan import cost as COST
        cost_report = COST.apply_placement(meta, self.conf)
        if self.conf.explain in ("ALL", "NOT_ON_GPU"):
            print("\n".join(meta.explain_lines(
                not_on_device_only=self.conf.explain == "NOT_ON_GPU")))
        root, side = self._convert(meta)
        # Process-global kernel cache: size it from this query's conf
        # (last writer wins — it is one process-wide pool, like the
        # reference's single RMM pool).
        from spark_rapids_tpu.ops import kernel_cache
        kernel_cache.cache().configure(
            int(self.conf.get(C.KERNEL_CACHE_MAX_ENTRIES)))
        # Persistent (on-disk) compilation cache: compiled executables
        # survive process restarts, so first_run_s pays deserialization
        # instead of recompilation (idempotent; first configured dir of
        # the process wins).
        kernel_cache.configure_persistent(
            str(self.conf.get(C.KERNEL_CACHE_PERSISTENT_DIR) or ""))
        num_fused = 0
        if bool(self.conf.get(C.STAGE_FUSION_ENABLED)):
            from spark_rapids_tpu.plan.fusion import fuse_stages
            root, num_fused = fuse_stages(root, side)
        phys = PhysicalPlan(root, side, meta, self.conf)
        phys.num_fused_stages = num_fused
        phys.cost_report = cost_report
        if self.conf.test_enabled:
            allowed = {s for s in str(self.conf.get(
                C.TEST_ALLOWED_NONTPU)).split(",") if s}
            bad = [n for n in phys.host_fallback_nodes()
                   if n not in allowed]
            if bad:
                raise AssertionError(
                    f"Query would execute on host: {bad} "
                    "(spark.rapids.sql.test.enabled)")
        return phys

    # -- helpers -------------------------------------------------------------
    def _bridge(self, child_exec: Exec, child_dev: bool,
                want_dev: bool) -> Exec:
        if child_dev == want_dev:
            return child_exec
        return HostToDeviceExec(child_exec) if want_dev \
            else DeviceToHostExec(child_exec)

    def _shuffle_partitions(self) -> int:
        if self._mesh_enabled():
            from spark_rapids_tpu.parallel.mesh_exchange import mesh_size
            # An explicit partition-count conf wins: the mesh exchange
            # folds/splits arbitrary logical partition counts onto the
            # device mesh (MeshExchangeExec fold pass), so the user's
            # fan-out no longer has to match the hardware shape.
            if self.conf.raw.get(C.SHUFFLE_PARTITIONS.key) is not None:
                return self.conf.get(C.SHUFFLE_PARTITIONS)
            return mesh_size()
        if self.conf.raw.get(C.SHUFFLE_PARTITIONS.key) is None:
            # Defaulted count on a single chip: a materialized exchange
            # only chunks work (all buckets run on device 0), and every
            # extra partition costs downstream per-partition round trips
            # (~70ms each on a tunneled link — the r4 q3 sync profile).
            # One partition = one merge, fewest syncs. An explicit conf
            # value or a multi-device mesh keeps the configured fan-out.
            import jax
            if len(jax.devices()) == 1:
                return 1
        return self.conf.get(C.SHUFFLE_PARTITIONS)

    def _mesh_enabled(self) -> bool:
        # Transport SPI selection (parallel/transport/): the 'mesh'
        # transport lowers hash shuffles to MeshExchangeExec; everything
        # else plans the materialized exchange, which spools through the
        # selected transport at execution time.
        from spark_rapids_tpu.parallel import transport as T
        return T.transport_name(self.conf) == "mesh"

    def _hash_exchange(self, child: Exec, keys, n: int,
                       allow_coalesce: bool = False) -> Exec:
        """Hash shuffle: collective mesh exchange when a mesh is
        configured, else the materialized single-process exchange.
        ``allow_coalesce`` opts into AQE-lite partition merging — safe for
        aggregate/window exchanges, NOT for co-partitioned join inputs."""
        part = HashPartitioning(keys, n)
        if self._mesh_enabled():
            from spark_rapids_tpu.parallel.mesh_exchange import \
                MeshExchangeExec
            return MeshExchangeExec(child, part)
        return ShuffleExchangeExec(child, part,
                                   allow_coalesce=allow_coalesce)

    def _convert(self, meta: NodeMeta) -> Tuple[Exec, bool]:
        exec_, dev = self._convert_inner(meta)
        # Tag the physical root of every logical node's conversion with
        # the logical node's identity: explain_analyze joins observed
        # per-exec metrics to the cost model's per-logical-node
        # estimates through this (monitoring/analyze.py).
        exec_._logical_id = id(meta.plan)
        return exec_, dev

    def _convert_inner(self, meta: NodeMeta) -> Tuple[Exec, bool]:
        plan = meta.plan
        want_dev = meta.on_device
        kids = [self._convert(c) for c in meta.children]

        if isinstance(plan, L.InMemoryScan):
            return InMemorySourceExec(plan.schema, plan.partitions), want_dev
        if isinstance(plan, L.FileScan):
            from spark_rapids_tpu.io import make_scan_exec
            return make_scan_exec(
                plan, self.conf,
                force_perfile=getattr(self, "_force_perfile", False)
            ), want_dev
        if isinstance(plan, L.LogicalRange):
            return RangeExec(plan.start, plan.end, plan.step,
                             plan.num_partitions,
                             batch_rows=int(self.conf.get(
                                 C.BATCH_SIZE_ROWS))), want_dev
        if isinstance(plan, L.LogicalFilter):
            child, cdev = kids[0]
            cond = resolve(plan.condition, plan.child.schema)
            return FilterExec(self._bridge(child, cdev, want_dev),
                              cond), want_dev
        if isinstance(plan, L.LogicalProject):
            child, cdev = kids[0]
            projections = [(n, resolve(c, plan.child.schema))
                           for n, c in plan.projections]
            return ProjectExec(self._bridge(child, cdev, want_dev),
                               projections), want_dev
        if isinstance(plan, L.LogicalUnion):
            bridged = [self._bridge(ch, cdev, want_dev)
                       for ch, cdev in kids]
            return UnionExec(*bridged), want_dev
        if isinstance(plan, L.LogicalLimit):
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            local = LocalLimitExec(child, plan.n)
            single = ShuffleExchangeExec(local, SinglePartitioning())
            return GlobalLimitExec(single, plan.n), want_dev
        if isinstance(plan, L.LogicalRepartition):
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            if plan.keys:
                keys = [resolve(k, plan.child.schema) for k in plan.keys]
                if self._mesh_enabled():
                    # The mesh exchange folds the requested partition
                    # count onto the mesh, so the user's repartition
                    # fan-out is honored as-is.
                    from spark_rapids_tpu.parallel.mesh_exchange import \
                        MeshExchangeExec
                    return MeshExchangeExec(
                        child,
                        HashPartitioning(keys, plan.num_partitions)), \
                        want_dev
                part = HashPartitioning(keys, plan.num_partitions)
            else:
                part = RoundRobinPartitioning(plan.num_partitions)
            return ShuffleExchangeExec(child, part), want_dev
        if isinstance(plan, L.LogicalSort):
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            orders = self._sort_orders(plan)
            # Global order: range-exchange into sorted partition ranges
            # first (Spark's requiredChildDistribution for global sort).
            ex = ShuffleExchangeExec(
                child, RangePartitioning(orders, self._shuffle_partitions()),
                allow_coalesce=want_dev)
            return SortExec(ex, orders), want_dev
        if isinstance(plan, L.LogicalAggregate):
            return self._convert_aggregate(plan, meta, kids[0], want_dev)
        if isinstance(plan, L.LogicalJoin):
            return self._convert_join(plan, meta, kids, want_dev)
        if isinstance(plan, L.LogicalWindow):
            return self._convert_window(plan, kids[0], want_dev)
        if isinstance(plan, L.LogicalGenerate):
            from spark_rapids_tpu.ops.generate import GenerateExec
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            schema = plan.child.schema
            elements = [resolve(c, schema) for c in plan.elements]
            return GenerateExec(
                child, elements, position=plan.position, outer=plan.outer,
                element_name=plan.out_name,
                skip_nulls=plan.outer), want_dev
        if isinstance(plan, L.LogicalMapInPandas):
            from spark_rapids_tpu.ops.pandas_exec import MapInPandasExec
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            return MapInPandasExec(child, plan.fn,
                                   plan.out_schema), want_dev
        if isinstance(plan, L.LogicalGroupedMapInPandas):
            from spark_rapids_tpu.ops.pandas_exec import \
                FlatMapGroupsInPandasExec
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            child = self._pandas_group_exchange(child, plan.child.schema,
                                                plan.key_names, want_dev)
            return FlatMapGroupsInPandasExec(
                child, plan.key_names, plan.fn, plan.out_schema), want_dev
        if isinstance(plan, L.LogicalCoGroupedMapInPandas):
            from spark_rapids_tpu.ops.pandas_exec import \
                CoGroupedMapInPandasExec
            lch, ldev = kids[0]
            rch, rdev = kids[1]
            lch = self._bridge(lch, ldev, want_dev)
            rch = self._bridge(rch, rdev, want_dev)
            lch = self._pandas_group_exchange(
                lch, plan.children[0].schema, plan.left_keys, want_dev)
            rch = self._pandas_group_exchange(
                rch, plan.children[1].schema, plan.right_keys, want_dev)
            return CoGroupedMapInPandasExec(
                lch, rch, plan.left_keys, plan.right_keys, plan.fn,
                plan.out_schema), want_dev
        if isinstance(plan, L.LogicalAggInPandas):
            from spark_rapids_tpu.ops.pandas_exec import \
                AggregateInPandasExec
            child, cdev = kids[0]
            child = self._bridge(child, cdev, want_dev)
            child = self._pandas_group_exchange(child, plan.child.schema,
                                                plan.key_names, want_dev)
            return AggregateInPandasExec(child, plan.key_names,
                                         plan.aggs), want_dev
        raise NotImplementedError(f"cannot convert {plan.name}")

    def _pandas_group_exchange(self, child: Exec, schema, key_names,
                               want_dev: bool) -> Exec:
        """Co-partition a pandas-UDF child by its grouping keys so each
        partition holds whole groups (requiredChildDistribution of the
        grouped python execs). Host-engine children skip the exchange —
        the oracle runs single-partition."""
        if not want_dev:
            return child
        names = [n for n, _ in schema]
        keys = []
        for k in key_names:
            if k not in names:
                raise L.ResolutionError(f"unknown grouping key {k!r}")
            i = names.index(k)
            keys.append(BoundReference(i, schema[i][1]))
        return self._hash_exchange(child, keys, self._shuffle_partitions())

    def _convert_window(self, plan: "L.LogicalWindow", kid,
                        want_dev: bool) -> Tuple[Exec, bool]:
        """Window exec with its required distribution underneath
        (GpuWindowExec.scala:92: hash-partition by the PARTITION BY keys,
        or a single partition for empty PARTITION BY; ordering happens
        inside the kernel's frame sort)."""
        from spark_rapids_tpu.ops.window import (
            DenseRank, Lag, Lead, Rank, RowNumber, WindowAgg, WindowExec,
            WindowExprSpec, WindowFrame, WindowSpec)
        child, cdev = kid
        child = self._bridge(child, cdev, want_dev)
        schema = plan.child.schema
        win = plan.window
        pcols = [resolve(c, schema) for c in win.partition_cols]
        orders = []
        for o in win.order_cols:
            if o.node[0] == "sortorder":
                inner, asc, nf = o.node[1], o.node[2], o.node[3]
            else:
                inner, asc, nf = o, True, True
            from spark_rapids_tpu.ops.sort import SortOrder
            orders.append(SortOrder(resolve(inner, schema), asc, nf))
        spec = WindowSpec(pcols, orders)
        wx_specs = []
        for out_name, fn_col in plan.exprs:
            node = fn_col.node
            if node[0] == "winfn":
                kind, child_col, offset = node[1], node[2], node[3]
                if kind in ("rank", "dense_rank", "row_number") \
                        and not orders:
                    raise L.ResolutionError(f"{kind}() requires ORDER BY")
                if kind == "row_number":
                    fn = RowNumber()
                elif kind == "rank":
                    fn = Rank()
                elif kind == "dense_rank":
                    fn = DenseRank()
                elif kind == "lead":
                    fn = Lead(resolve(child_col, schema), offset)
                elif kind == "lag":
                    fn = Lag(resolve(child_col, schema), offset)
                else:
                    raise L.ResolutionError(f"unknown window fn {kind!r}")
            else:   # ("agg", kind, child)
                kind, child_col = node[1], node[2]
                agg_child = None if child_col is None \
                    else resolve(child_col, schema)
                if win.frame is not None:
                    _, start, end = win.frame
                    if (start is not None and start > 0) or \
                            (end is not None and end < 0):
                        raise L.ResolutionError(
                            "rows_between bounds must straddle the "
                            "current row")
                    frame = WindowFrame(
                        None if start is None else -start, end)
                elif orders:
                    # Spark default: RANGE UNBOUNDED..CURRENT ROW.
                    frame = WindowFrame(None, 0, running_with_peers=True)
                else:
                    frame = WindowFrame(None, None)   # whole partition
                fn = WindowAgg(kind, agg_child, frame)
            wx_specs.append(WindowExprSpec(out_name, fn, spec))
        if pcols:
            ex = self._hash_exchange(child, pcols,
                                     self._shuffle_partitions(),
                                     allow_coalesce=want_dev)
        else:
            ex = ShuffleExchangeExec(child, SinglePartitioning())
        return WindowExec(ex, wx_specs), want_dev

    def _sort_orders(self, plan: L.LogicalSort) -> List[SortOrder]:
        orders = []
        for o in plan.orders:
            if o.node[0] == "sortorder":
                inner, asc, nf = o.node[1], o.node[2], o.node[3]
            else:
                inner, asc, nf = o, True, True
            orders.append(SortOrder(resolve(inner, plan.child.schema),
                                    asc, nf))
        return orders

    def _convert_aggregate(self, plan: L.LogicalAggregate, meta: NodeMeta,
                           kid, want_dev: bool) -> Tuple[Exec, bool]:
        child, cdev = kid
        child = self._bridge(child, cdev, want_dev)
        schema = plan.child.schema
        group_by = [(n, resolve(c, schema)) for n, c in plan.group_by]
        aggs = [AggSpec(n, fn, distinct=getattr(fn, "is_distinct", False))
                for n, fn in ((n, resolve_agg(c, schema))
                              for n, c in plan.aggregates)]
        if plan.grouping is not None:
            if any(s.distinct for s in aggs):
                raise L.ResolutionError(
                    "DISTINCT aggregates under rollup/cube are unsupported")
            return self._convert_grouping_sets(
                plan.grouping, group_by, aggs, child, want_dev)
        if any(s.distinct for s in aggs):
            return self._convert_distinct_aggregate(
                group_by, aggs, child, want_dev)
        # Two-stage: partial -> exchange on group keys -> final
        # (aggregate.scala partial/final mode pair across the shuffle).
        return self._two_stage(group_by, aggs, child, want_dev)

    def _convert_grouping_sets(self, kind: str, group_by, aggs, child,
                               want_dev: bool) -> Tuple[Exec, bool]:
        """ROLLUP/CUBE via ExpandExec (GpuExpandExec.scala; Spark lowers
        grouping sets to Expand + Aggregate keyed by (keys...,
        grouping_id)): each input row is emitted once per grouping set,
        with aggregated-out keys NULLed and a grouping-id literal so a
        data NULL never merges with a subtotal NULL. A final projection
        drops the grouping id."""
        from spark_rapids_tpu.exprs.base import Literal
        nk = len(group_by)
        if kind == "rollup":
            # Set i keeps the first nk-i keys; gid bit per dropped key.
            masks = [(1 << i) - 1 for i in range(nk + 1)]
        else:
            masks = list(range(1 << nk))
        agg_children = []
        for s in aggs:
            agg_children.append(s.fn.child)
        names = [n for n, _ in group_by] + \
            [f"__agg_in{i}" for i in range(len(agg_children))] + \
            ["__grouping_id"]
        projections = []
        for mask in masks:
            proj = []
            for i, (_, e) in enumerate(group_by):
                dropped = mask & (1 << (nk - 1 - i)) if kind == "cube" \
                    else (i >= nk - bin(mask).count("1"))
                proj.append(Literal(e.data_type(), None) if dropped else e)
            for ce in agg_children:
                proj.append(ce if ce is not None
                            else Literal(dt.INT32, 1))
            proj.append(Literal(dt.INT64, mask))
            projections.append(proj)
        expand = ExpandExec(child, projections, names)
        # Re-key everything by ordinal over the expand output.
        ex_group = [(n, BoundReference(i, e.data_type()))
                    for i, (n, e) in enumerate(group_by)]
        ex_group.append(("__grouping_id", BoundReference(
            nk + len(agg_children), dt.INT64)))
        ex_aggs = []
        for i, s in enumerate(aggs):
            if s.fn.child is None:
                ex_aggs.append(s)
                continue
            ref = BoundReference(nk + i, s.fn.child.data_type())
            if isinstance(s.fn, (First, Last)):
                fn = type(s.fn)(ref, s.fn.ignore_nulls)
            else:
                fn = type(s.fn)(ref)
            ex_aggs.append(AggSpec(s.name, fn))
        final, dev = self._two_stage(ex_group, ex_aggs, expand, want_dev,
                                     allow_partial_skip=False)
        # Drop the grouping id from the output.
        out = [(n, BoundReference(i, e.data_type()))
               for i, (n, e) in enumerate(ex_group[:nk])]
        out += [(s.name, BoundReference(nk + 1 + i, s.fn.result_type))
                for i, s in enumerate(ex_aggs)]
        return ProjectExec(final, out), dev

    def _two_stage(self, group_by, aggs, child, want_dev: bool,
                   allow_partial_skip: bool = True) -> Tuple[Exec, bool]:
        """partial -> hash exchange -> final (shared by plain and
        grouping-set aggregates). Grouping-set plans keep the partial
        pass unconditionally: the expand multiplies rows N-fold, and the
        coarse rollup levels reduce massively even when the finest level
        does not — skipping would shuffle the whole expansion."""
        partial = HashAggregateExec(child, group_by, aggs, mode="partial")
        partial.allow_partial_skip = allow_partial_skip
        nkeys = len(group_by)
        if nkeys:
            keys = [BoundReference(i, e.data_type())
                    for i, (_, e) in enumerate(group_by)]
            ex = self._hash_exchange(partial, keys,
                                     self._shuffle_partitions(),
                                     allow_coalesce=want_dev)
        else:
            ex = ShuffleExchangeExec(partial, SinglePartitioning())
        final_groups = [
            (n, BoundReference(i, e.data_type()))
            for i, (n, e) in enumerate(group_by)]
        final = HashAggregateExec(ex, final_groups, aggs, mode="final")
        return final, want_dev

    def _convert_distinct_aggregate(self, group_by, aggs, child,
                                    want_dev: bool) -> Tuple[Exec, bool]:
        """DISTINCT aggregates via the reference's partial-merge mode
        combos (aggregate.scala:305 distinct handling):

          partial  group by (keys..., x) w/ partial non-distinct aggs
          -> hash exchange on keys (x rides along; co-location by keys
             suffices since dedup completes in the merge stage)
          -> merge  group by (keys..., x): dedup complete, buffers merged
          -> mixed_final group by keys: distinct aggs UPDATE over the
             now-unique x values, non-distinct aggs MERGE their buffers

        All distinct aggregates must share one input expression (Spark's
        planner has the same single-distinct-column restriction before
        falling back to expand-based rewrites)."""
        d_specs = [s for s in aggs if s.distinct]
        nd_specs = [s for s in aggs if not s.distinct]
        x_exprs = {s.fn.distinct_key for s in d_specs}
        if len(x_exprs) > 1:
            raise L.ResolutionError(
                "multiple DISTINCT aggregates must share the same input "
                f"expression; got {len(x_exprs)} different ones")
        x = d_specs[0].fn.child
        xt = x.data_type()
        nkeys = len(group_by)
        # Stage A: partial, keyed by (keys..., x).
        gb_a = list(group_by) + [("__distinct_x", x)]
        stage_a = HashAggregateExec(child, gb_a, nd_specs, mode="partial")
        # Exchange on the group keys only (zero keys -> single partition).
        if nkeys:
            keys = [BoundReference(i, e.data_type())
                    for i, (_, e) in enumerate(group_by)]
            ex = self._hash_exchange(stage_a, keys,
                                     self._shuffle_partitions(),
                                     allow_coalesce=want_dev)
        else:
            ex = ShuffleExchangeExec(stage_a, SinglePartitioning())
        # Stage B: merge, still keyed by (keys..., x) over the buffer
        # layout [keys..., x, nd buffers...].
        gb_b = [(n, BoundReference(i, e.data_type()))
                for i, (n, e) in enumerate(group_by)]
        gb_b.append(("__distinct_x", BoundReference(nkeys, xt)))
        stage_b = HashAggregateExec(ex, gb_b, nd_specs, mode="merge")
        # Stage C: mixed final keyed by keys; distinct fns read x at
        # ordinal nkeys of stage B's output.
        final_groups = [(n, BoundReference(i, e.data_type()))
                        for i, (n, e) in enumerate(group_by)]
        specs_c = []
        for s in aggs:
            if s.distinct:
                fn = type(s.fn)(BoundReference(nkeys, xt))
                specs_c.append(AggSpec(s.name, fn, distinct=True))
            else:
                specs_c.append(s)
        final = HashAggregateExec(stage_b, final_groups, specs_c,
                                  mode="mixed_final")
        return final, want_dev

    def _convert_join(self, plan: L.LogicalJoin, meta: NodeMeta, kids,
                      want_dev: bool) -> Tuple[Exec, bool]:
        (lch, ldev), (rch, rdev) = kids
        lch = self._bridge(lch, ldev, want_dev)
        rch = self._bridge(rch, rdev, want_dev)
        ls, rs = plan.children[0].schema, plan.children[1].schema
        lkeys = [resolve(k, ls) for k in plan.left_keys]
        rkeys = [resolve(k, rs) for k in plan.right_keys]
        cond = None
        if plan.condition is not None:
            cond = resolve(plan.condition, tuple(ls) + tuple(rs))
        if not lkeys:
            return BroadcastNestedLoopJoinExec(
                lch, rch, plan.join_type, cond), want_dev
        strategy = plan.strategy
        est = None
        if strategy == "auto":
            # Stats-driven choice (autoBroadcastJoinThreshold): broadcast
            # when the build side's estimated bytes fit the threshold,
            # else hash-shuffle both sides. Full outer always needs
            # co-partitioning.
            if plan.join_type == "full":
                strategy = "shuffle"
            else:
                threshold = int(self.conf.get(C.AUTO_BROADCAST_THRESHOLD))
                from spark_rapids_tpu.plan.pruning import estimate_bytes
                build_plan = plan.children[1] \
                    if plan.join_type != "right" else plan.children[0]
                est = estimate_bytes(build_plan)
                # Spark semantics: -1 disables auto-broadcast.
                strategy = "broadcast" \
                    if threshold >= 0 and est is not None \
                    and est <= threshold else "shuffle"
                meta.notes.append(
                    f"auto join strategy -> {strategy} (build side "
                    f"~{est if est is not None else '?'} bytes, "
                    f"threshold {threshold})")
        if strategy == "broadcast":
            return BroadcastHashJoinExec(
                lch, rch, lkeys, rkeys, plan.join_type, cond), want_dev
        n = self._shuffle_partitions()
        lex = self._hash_exchange(lch, lkeys, n)
        rex = self._hash_exchange(rch, rkeys, n)
        shj = ShuffledHashJoinExec(
            lex, rex, lkeys, rkeys, plan.join_type, cond)
        # Planning-time build estimate, kept for runtime re-planning's
        # estimate-vs-actual error metric (parallel/replan.py).
        shj.est_build_bytes = est
        return shj, want_dev
