"""Spark physical-plan ingestion — the PLUGIN-MODE identity slice
(ref: SQLPlugin.scala:28-31, Plugin.scala:50-57, GpuOverrides.scala:
1991-2012: the reference's whole reason to exist is accelerating
EXISTING Spark jobs with no user-code changes).

The reference receives live Catalyst plan objects inside the JVM. This
engine runs outside any JVM, so plugin mode ingests a CAPTURED plan: the
text a user gets from ``df.explain()`` / ``queryExecution.executedPlan``
on their real Spark cluster (Spark 3.x formatted physical plan). The
ingester parses the operator tree and its expression strings back into
this engine's logical plan, re-plans it TPU-first, and executes against
local copies of the scanned tables.

Supported operators (the scan/filter/project/agg/join/sort/limit slice):
  FileScan parquet/orc/csv, Filter, Project, HashAggregate (partial /
  final pairs collapse: the planner re-inserts its own two-stage split),
  Exchange (dropped — re-planned), Sort (kept only when not join/agg
  plumbing), SortMergeJoin, ShuffledHashJoin, BroadcastHashJoin,
  BroadcastExchange (dropped), GlobalLimit/LocalLimit,
  TakeOrderedAndProject.

Expressions: attribute refs (``name#id``), int/float/string/bool
literals, arithmetic (+,-,*,/,%), comparisons (=,<,<=,>,>=,<=>, !=),
AND/OR/NOT, isnull/isnotnull, CASE WHEN, cast, substring, IN-lists, and
the sum/min/max/avg/count aggregates (with ``partial_``/``merge_``
prefixes from two-stage plans).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import Column, col, lit_col, when


class SparkPlanParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Tree extraction: indentation-based operator lines
# ---------------------------------------------------------------------------

_STAR_RE = re.compile(r"\*\(\d+\)\s*")
_NAME_START_RE = re.compile(r"[A-Za-z]\w*")


class _Node:
    def __init__(self, name: str, rest: str, depth: int):
        self.name = name
        self.rest = rest
        self.depth = depth
        self.children: List["_Node"] = []

    def __repr__(self):  # pragma: no cover - debug
        return f"_Node({self.name}, depth={self.depth})"


def _parse_tree(text: str) -> _Node:
    """Spark's formatted tree: each level adds a 3-char structural marker
    ('+- ', ':- ', ':  ', '   ') before the operator name; the codegen
    '*(n) ' star is cosmetic."""
    roots: List[_Node] = []
    stack: List[_Node] = []
    for raw in text.splitlines():
        line = _STAR_RE.sub("", raw.rstrip())
        if not line.strip() or line.lstrip().startswith("=="):
            continue
        m = _NAME_START_RE.search(line)
        if m is None:
            continue
        prefix = line[:m.start()]
        if prefix.strip(" :+-"):
            continue                      # not an operator line
        if m.start() % 3 != 0:
            # Looks like an operator line (structural-marker prefix) but
            # the indent is not a multiple of the 3-char marker width:
            # silently dropping it would drop an OPERATOR and produce
            # wrong results downstream (e.g. a vanished Filter).
            raise SparkPlanParseError(
                f"operator line has malformed indentation "
                f"(column {m.start()} is not a multiple of 3): {raw!r}")
        depth = len(prefix) // 3
        head = line[m.start():]
        name = _NAME_START_RE.match(head).group(0)
        node = _Node(name, head[len(name):].strip(), depth)
        while stack and stack[-1].depth >= depth:
            stack.pop()
        if stack:
            stack[-1].children.append(node)
        else:
            roots.append(node)
        stack.append(node)
    if not roots:
        raise SparkPlanParseError("no operator lines found")
    return roots[0]


# ---------------------------------------------------------------------------
# Expression parsing
# ---------------------------------------------------------------------------

class _ExprParser:
    """Recursive-descent parser over Spark's expression pretty-print."""

    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self) -> str:
        return self.s[self.i:self.i + 1]

    def _ws(self):
        while self.i < len(self.s) and self.s[self.i] == " ":
            self.i += 1

    def eat(self, tok: str) -> bool:
        self._ws()
        if self.s[self.i:self.i + len(tok)].upper() == tok.upper():
            self.i += len(tok)
            return True
        return False

    def expect(self, tok: str):
        if not self.eat(tok):
            raise SparkPlanParseError(
                f"expected {tok!r} at ...{self.s[self.i:self.i + 40]!r}")

    def parse(self) -> Column:
        e = self.expr()
        self._ws()
        if self.i < len(self.s):
            # Silent truncation would turn a half-understood expression
            # into wrong results; refuse instead.
            raise SparkPlanParseError(
                f"trailing text in expression: "
                f"{self.s[self.i:self.i + 40]!r} (full: {self.s!r})")
        return e

    # OR < AND < NOT < comparison < additive < multiplicative < unary
    def expr(self) -> Column:
        e = self.and_expr()
        while self.eat(" OR ") or self.eat("OR "):
            e = e | self.and_expr()
        return e

    def and_expr(self) -> Column:
        e = self.not_expr()
        while True:
            self._ws()
            if self.s[self.i:self.i + 4].upper() == "AND ":
                self.i += 4
                e = e & self.not_expr()
            else:
                return e

    def not_expr(self) -> Column:
        self._ws()
        if self.s[self.i:self.i + 4].upper() == "NOT ":
            self.i += 4
            return ~self.not_expr()
        return self.cmp_expr()

    def cmp_expr(self) -> Column:
        e = self.add_expr()
        self._ws()
        for op in ("<=>", "<=", ">=", "!=", "=", "<", ">"):
            if self.s[self.i:self.i + len(op)] == op:
                self.i += len(op)
                rhs = self.add_expr()
                if op == "=":
                    return e == rhs
                if op == "!=":
                    return e != rhs
                if op == "<=":
                    return e <= rhs
                if op == ">=":
                    return e >= rhs
                if op == "<":
                    return e < rhs
                if op == ">":
                    return e > rhs
                if op == "<=>":     # null-safe equal ~= equal for ingest
                    return e == rhs
        if self.eat(" IN (") or self.eat("IN ("):
            vals = []
            while not self.eat(")"):
                v = self.primary()
                vals.append(v.node[1])
                self.eat(",")
            return e.isin(*vals)
        return e

    def add_expr(self) -> Column:
        e = self.mul_expr()
        while True:
            self._ws()
            c = self.peek()
            if c == "+":
                self.i += 1
                e = e + self.mul_expr()
            elif c == "-" and not self.s[self.i + 1:self.i + 2].isdigit():
                self.i += 1
                e = e - self.mul_expr()
            else:
                return e

    def mul_expr(self) -> Column:
        e = self.unary()
        while True:
            self._ws()
            c = self.peek()
            if c == "*":
                self.i += 1
                e = e * self.unary()
            elif c == "/":
                self.i += 1
                e = e / self.unary()
            elif c == "%":
                self.i += 1
                e = e % self.unary()
            else:
                return e

    def unary(self) -> Column:
        self._ws()
        if self.peek() == "-" and not self.s[self.i + 1:self.i + 2] \
                .isdigit():
            self.i += 1
            return -self.unary()
        return self.primary()

    _NAME_RE = re.compile(r"[A-Za-z_][\w.]*")

    def primary(self) -> Column:
        self._ws()
        c = self.peek()
        if c == "(":
            self.i += 1
            e = self.expr()
            self.expect(")")
            return e
        if c.isdigit() or (c == "-" and
                           self.s[self.i + 1:self.i + 2].isdigit()):
            return lit_col(self._number())
        if c in "'\"":
            return lit_col(self._string(c))
        m = self._NAME_RE.match(self.s, self.i)
        if not m:
            raise SparkPlanParseError(
                f"cannot parse expression at "
                f"...{self.s[self.i:self.i + 40]!r}")
        name = m.group(0)
        self.i = m.end()
        low = name.lower()
        # attribute ref: name#123 (optionally with L suffix)
        if self.peek() == "#":
            self.i += 1
            while self.i < len(self.s) and \
                    (self.s[self.i].isdigit() or self.s[self.i] == "L"):
                self.i += 1
            return col(name)
        if self.peek() == "(":
            self.i += 1
            return self._call(low)
        if low == "true":
            return lit_col(True)
        if low == "false":
            return lit_col(False)
        if low == "null":
            return lit_col(None)
        if low == "case":
            return self._case_tail()
        # A bare word inside a physical-plan expression is an UNQUOTED
        # string literal (Spark prints `c_mktsegment#3 = BUILDING`);
        # every attribute reference carries its #exprId. Multi-word
        # literals extend across following bare words ("SM CASE").
        words = [name]
        while True:
            save = self.i
            self._ws()
            m2 = self._NAME_RE.match(self.s, self.i)
            if m2 and self.s[m2.end():m2.end() + 1] not in "#(" and \
                    m2.group(0).upper() not in ("AND", "OR", "NOT", "IN",
                                                "THEN", "ELSE", "END",
                                                "WHEN", "AS"):
                words.append(m2.group(0))
                self.i = m2.end()
            else:
                self.i = save
                break
        return lit_col(" ".join(words))

    def _args(self) -> List[Column]:
        args = []
        if self.eat(")"):
            return args
        while True:
            args.append(self.expr())
            if self.eat(")"):
                return args
            self.expect(",")

    def _call(self, fn: str) -> Column:
        from spark_rapids_tpu.plan import logical as LG
        if fn == "cast":
            e = self.expr()
            self.expect("as")
            self._ws()
            m = self._NAME_RE.match(self.s, self.i)
            ty = m.group(0).lower()
            self.i = m.end()
            self.expect(")")
            return e.cast(_SPARK_TYPES.get(ty, ty))
        if fn == "isnotnull":
            a = self._args()
            return a[0].isNotNull()
        if fn == "isnull":
            a = self._args()
            return a[0].isNull()
        if fn == "substring":
            a = self._args()
            return a[0].substr(a[1].node[1], a[2].node[1])
        agg_fn = fn
        distinct = False
        for pre in ("partial_", "merge_", "finalmerge_"):
            if agg_fn.startswith(pre):
                agg_fn = agg_fn[len(pre):]
        if agg_fn.startswith("distinct "):
            agg_fn = agg_fn[len("distinct "):]
            distinct = True
        if agg_fn in ("sum", "min", "max", "avg", "count", "first",
                      "last"):
            args = self._args()
            child = args[0] if args else None
            if agg_fn == "count" and child is not None and \
                    child.node == ("lit", 1):
                child = None
            tag = "aggd" if distinct else "agg"
            return Column((tag, agg_fn, child))
        if agg_fn in _FUNCS:
            return _FUNCS[agg_fn](*self._args())
        raise SparkPlanParseError(f"unsupported function {fn!r}")

    def _case_tail(self) -> Column:
        builder = None
        while True:
            self._ws()
            if self.eat("WHEN "):
                cond = self.expr()
                self.expect("THEN")
                val = self.expr()
                builder = when(cond, val) if builder is None \
                    else builder.when(cond, val)
            elif self.eat("ELSE "):
                other = self.expr()
                self.expect("END")
                return builder.otherwise(other)
            elif self.eat("END"):
                return builder.otherwise(None)
            else:
                raise SparkPlanParseError(
                    f"bad CASE at ...{self.s[self.i:self.i + 30]!r}")

    _DATE_RE = re.compile(r"\d{4}-\d{2}-\d{2}")

    def _number(self):
        # Spark prints date literals unquoted ('1995-01-01'); they must
        # not half-parse as the int 1995.
        dm = self._DATE_RE.match(self.s, self.i)
        if dm:
            import datetime
            y, mo, d = map(int, dm.group(0).split("-"))
            self.i = dm.end()
            return (datetime.date(y, mo, d)
                    - datetime.date(1970, 1, 1)).days
        m = re.match(r"-?\d+(\.\d+)?([eE]-?\d+)?", self.s[self.i:])
        tok = m.group(0)
        self.i += len(tok)
        # type suffixes: L (long), D (double), S/B
        suffix = self.s[self.i:self.i + 1]
        if suffix in "LDSB":
            self.i += 1
        if "." in tok or "e" in tok or "E" in tok or suffix == "D":
            return float(tok)
        return int(tok)

    def _string(self, q: str):
        self.i += 1
        j = self.s.index(q, self.i)
        out = self.s[self.i:j]
        self.i = j + 1
        return out


_SPARK_TYPES = {
    "int": "int", "bigint": "long", "smallint": "int", "tinyint": "int",
    "double": "double", "float": "float", "string": "string",
    "date": "date", "boolean": "boolean", "decimal": "double",
}

_FUNCS: Dict[str, callable] = {}


def _parse_expr(s: str) -> Column:
    return _ExprParser(s).parse()


def _split_top(s: str, sep: str = ",") -> List[str]:
    """Split on sep at bracket depth 0."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def _clean_name(attr: str) -> str:
    """'l_quantity#4L' -> 'l_quantity'; 'sum(x#1)#33' -> 'sum(x)'."""
    return re.sub(r"#\d+L?", "", attr).strip()


# ---------------------------------------------------------------------------
# Operator mapping
# ---------------------------------------------------------------------------

def ingest_spark_plan(text: str, session,
                      table_paths: Dict[str, Sequence[str]]):
    """Parse a captured Spark physical plan (df.explain() text) into a
    DataFrame on this engine. ``table_paths`` maps a table name (matched
    against the captured FileScan's Location substring) to local file
    paths for that table."""
    from spark_rapids_tpu.api.dataframe import DataFrame
    root = _parse_tree(text)
    plan = _convert(root, session, table_paths)
    return DataFrame(session, plan)


def _convert(node: _Node, session, tables) -> L.LogicalPlan:
    name = node.name
    rest = node.rest

    def child(i=0) -> L.LogicalPlan:
        return _convert(node.children[i], session, tables)

    if name in ("Exchange", "BroadcastExchange", "ShuffleQueryStage",
                "BroadcastQueryStage", "AQEShuffleRead", "InputAdapter",
                "WholeStageCodegen", "ReusedExchange", "ColumnarToRow",
                "AdaptiveSparkPlan"):
        # Plumbing: this engine re-plans distribution itself.
        return child()
    if name == "FileScan" or (name == "Scan" and not node.children):
        # 'FileScan parquet [cols]' (3.0) / 'Scan parquet tbl[cols]' (3.2+)
        return _convert_scan(rest, session, tables)
    if name == "Filter":
        return L.LogicalFilter(child(), _parse_expr(_strip_brackets(rest)))
    if name == "Project":
        projections = []
        for item in _split_top(_strip_brackets(rest)):
            projections.append(_parse_named(item))
        return L.LogicalProject(child(), projections)
    if name == "HashAggregate" or name == "SortAggregate" or \
            name == "ObjectHashAggregate":
        return _convert_aggregate(node, session, tables)
    if name in ("SortMergeJoin", "ShuffledHashJoin", "BroadcastHashJoin"):
        return _convert_join(node, session, tables)
    if name == "Sort":
        orders = _parse_orders(_strip_brackets(rest))
        kid = child()
        # Sorts under SMJ plumbing never reach here (join drops them).
        return L.LogicalSort(kid, orders)
    if name in ("GlobalLimit", "LocalLimit", "CollectLimit"):
        n = int(re.search(r"\d+", rest).group(0))
        kid = child()
        if name == "LocalLimit" and node.children and \
                node.children[0].name == "GlobalLimit":
            return kid
        return L.LogicalLimit(kid, n)
    if name == "TakeOrderedAndProject":
        m = re.search(r"limit=(\d+),\s*orderBy=\[(.*?)\],\s*"
                      r"output=\[(.*?)\]", rest)
        if not m:
            raise SparkPlanParseError(f"bad TakeOrderedAndProject: {rest}")
        limit, order_s, out_s = m.groups()
        kid = child()
        orders = _parse_orders(order_s)
        sort = L.LogicalSort(kid, orders)
        lim = L.LogicalLimit(sort, int(limit))
        projections = [_parse_named(x) for x in _split_top(out_s)]
        return L.LogicalProject(lim, projections)
    raise SparkPlanParseError(f"unsupported Spark operator {name!r}")


def _strip_brackets(s: str) -> str:
    s = s.strip()
    if s[:1] in "([" and s[-1:] in ")]":
        return s[1:-1]
    return s


def _parse_named(item: str) -> Tuple[str, Column]:
    """'(x#1 * 2) AS y#9' or bare 'l_orderkey#0L'."""
    m = re.search(r"\s+AS\s+([A-Za-z_]\w*(?:\(\w*\))?)#\d+L?$", item)
    if m:
        return m.group(1), _parse_expr(item[:m.start()])
    return _clean_name(item), _parse_expr(item)


def _parse_orders(s: str) -> List[Column]:
    orders = []
    for item in _split_top(s):
        m = re.match(r"(.*?)\s+(ASC|DESC)\s+NULLS\s+(FIRST|LAST)$",
                     item.strip())
        if m:
            e = _parse_expr(m.group(1))
            e = e.asc() if m.group(2) == "ASC" else e.desc()
        else:
            e = _parse_expr(item).asc()
        orders.append(e)
    return orders


def _convert_scan(rest: str, session, tables) -> L.LogicalPlan:
    m = re.match(r"(\w+)\s+\[(.*?)\]", rest)
    if not m:
        raise SparkPlanParseError(f"bad FileScan: {rest}")
    fmt, cols_s = m.groups()
    loc = re.search(r"Location:\s*\S*\[([^\]]*)\]", rest)
    location = loc.group(1) if loc else ""
    table = None
    for tname in tables:
        if tname in location or tname in rest:
            table = tname
            break
    if table is None:
        raise SparkPlanParseError(
            f"no local paths for scan location {location!r} "
            f"(have {list(tables)})")
    paths = tables[table]
    df = getattr(session.read, fmt.lower())(*list(paths))
    want = [_clean_name(c) for c in _split_top(cols_s)]
    have = set(df.columns)
    missing = [c for c in want if c not in have]
    if missing:
        # The captured plan scans columns the local file does not have:
        # silently filtering them out would execute a DIFFERENT query
        # (downstream operators reference the missing attrs or, worse,
        # quietly lose them).
        raise SparkPlanParseError(
            f"scan of table {table!r} wants columns {missing} that the "
            f"local {fmt} data lacks (file has {sorted(have)})")
    if want and set(want) != have:
        df = df.select(*want)
    return df._plan


def _is_partial_agg(node: _Node) -> bool:
    return "partial_" in node.rest


def _convert_aggregate(node: _Node, session, tables) -> L.LogicalPlan:
    rest = node.rest
    if _is_partial_agg(node):
        # Partial half of a two-stage pair: the FINAL node rebuilds the
        # whole aggregate over this node's input (this planner re-splits).
        return _convert(node.children[0], session, tables)
    keys_m = re.search(r"keys=\[(.*?)\]", rest)
    fns_m = re.search(r"functions=\[(.*?)\]", rest)
    out_m = re.search(r"output=\[(.*?)\]", rest)
    if fns_m is None:
        raise SparkPlanParseError(f"bad HashAggregate: {rest}")
    group_by = []
    if keys_m and keys_m.group(1).strip():
        for k in _split_top(keys_m.group(1)):
            group_by.append((_clean_name(k), _parse_expr(k)))
    fns = [f for f in _split_top(fns_m.group(1)) if f]
    # The output list names the user-visible attrs (keys first, then one
    # per aggregate) — downstream operators reference THOSE names.
    out_names = [_clean_name(o) for o in _split_top(out_m.group(1))] \
        if out_m else []
    aggs = []
    for i, f in enumerate(fns):
        oi = len(group_by) + i
        name_i = out_names[oi] if oi < len(out_names) else _clean_name(f)
        aggs.append((name_i, _parse_expr(f)))
    return L.LogicalAggregate(_convert(node.children[0], session, tables),
                              group_by, aggs)


def _convert_join(node: _Node, session, tables) -> L.LogicalPlan:
    rest = node.rest
    parts = _split_top(_strip_outer(rest))
    if len(parts) < 3:
        raise SparkPlanParseError(f"bad join: {rest}")
    lkeys = [_parse_expr(k) for k in _split_top(_strip_brackets(parts[0]))]
    rkeys = [_parse_expr(k) for k in _split_top(_strip_brackets(parts[1]))]
    jtype = parts[2].strip().lower()
    jtype = {"inner": "inner", "leftouter": "left", "rightouter": "right",
             "fullouter": "full", "leftsemi": "semi", "leftanti": "anti",
             "cross": "cross"}.get(jtype, jtype)
    cond = None
    for extra in parts[3:]:
        extra = extra.strip()
        if extra in ("", "BuildRight", "BuildLeft", "false", "true"):
            continue        # build-side marker / isSkewJoin flag
        cond = _parse_expr(extra)
        break
    kids = []
    for c in node.children:
        # Drop per-side Sort/Exchange plumbing under SMJ.
        while c.name in ("Sort", "Exchange", "InputAdapter",
                         "BroadcastExchange", "ColumnarToRow"):
            c = c.children[0]
        kids.append(_convert(c, session, tables))
    strategy = "broadcast" if node.name == "BroadcastHashJoin" else "auto"
    return L.LogicalJoin(kids[0], kids[1], lkeys, rkeys, jtype, cond,
                         strategy)


def _strip_outer(s: str) -> str:
    """Join rest: '[k1#1], [k2#2], Inner' or with surrounding brackets."""
    return s.strip()
