"""Logical plans and the untyped column DSL.

The reference rewrites Spark physical plans in place (GpuOverrides over
SparkPlan). Standalone, this engine owns the frontend too, so the input to
the plan-rewrite layer is this logical plan — built by the DataFrame API
(api/dataframe.py) — with unresolved, name-based expressions. ``resolve``
binds names to ordinals and picks typed expression classes
(exprs/*), the analog of Catalyst analysis feeding GpuOverrides.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference, Expression, Literal

Schema = Tuple[Tuple[str, DataType], ...]


# ---------------------------------------------------------------------------
# Untyped column AST (the DataFrame DSL)
# ---------------------------------------------------------------------------

class Column:
    """Unresolved expression node; operators build the AST lazily."""

    def __init__(self, node: Tuple):
        self.node = node

    # -- operators -----------------------------------------------------------
    def _bin(self, op: str, other) -> "Column":
        return Column((op, self, _as_col(other)))

    def __add__(self, o):
        return self._bin("add", o)

    def __radd__(self, o):
        return _as_col(o)._bin("add", self)

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return _as_col(o)._bin("sub", self)

    def __mul__(self, o):
        return self._bin("mul", o)

    def __rmul__(self, o):
        return _as_col(o)._bin("mul", self)

    def __truediv__(self, o):
        return self._bin("div", o)

    def __mod__(self, o):
        return self._bin("mod", o)

    def __neg__(self):
        return Column(("neg", self))

    def __eq__(self, o):  # type: ignore[override]
        return self._bin("eq", o)

    def __ne__(self, o):  # type: ignore[override]
        return Column(("not", self._bin("eq", o)))

    def __lt__(self, o):
        return self._bin("lt", o)

    def __le__(self, o):
        return self._bin("le", o)

    def __gt__(self, o):
        return self._bin("gt", o)

    def __ge__(self, o):
        return self._bin("ge", o)

    def __and__(self, o):
        return self._bin("and", o)

    def __or__(self, o):
        return self._bin("or", o)

    def __invert__(self):
        return Column(("not", self))

    def __hash__(self):
        return id(self)

    # -- named helpers --------------------------------------------------------
    def alias(self, name: str) -> "Column":
        return Column(("alias", self, name))

    def cast(self, to: Union[str, DataType]) -> "Column":
        t = dt.type_named(to) if isinstance(to, str) else to
        return Column(("cast", self, t))

    def isNull(self) -> "Column":
        return Column(("isnull", self))

    def isNotNull(self) -> "Column":
        return Column(("isnotnull", self))

    def isin(self, *values) -> "Column":
        vals = values[0] if len(values) == 1 and \
            isinstance(values[0], (list, tuple)) else values
        return Column(("isin", self, tuple(vals)))

    def substr(self, pos, length) -> "Column":
        return Column(("substr", self, _as_col(pos), _as_col(length)))

    def startswith(self, s: str) -> "Column":
        return Column(("startswith", self, s))

    def endswith(self, s: str) -> "Column":
        return Column(("endswith", self, s))

    def contains(self, s: str) -> "Column":
        return Column(("contains", self, s))

    def like(self, pattern: str) -> "Column":
        return Column(("like", self, pattern))

    def rlike_replace(self, pattern: str, repl: str) -> "Column":
        return Column(("regexp_replace", self, pattern, repl))

    def asc(self) -> "Column":
        return Column(("sortorder", self, True, True))

    def desc(self) -> "Column":
        return Column(("sortorder", self, False, False))

    def over(self, window: "WindowDef") -> "Column":
        """Evaluate this aggregate/window function over a window
        (pyspark ``Column.over``; ref GpuWindowExpression.scala)."""
        assert isinstance(window, WindowDef), "over() takes a Window spec"
        return Column(("window", self, window))

    @property
    def name_hint(self) -> str:
        n = self.node
        if n[0] == "ref":
            return n[1]
        if n[0] == "alias":
            return n[2]
        return n[0]


def col(name: str) -> Column:
    return Column(("ref", name))


def lit_col(value) -> Column:
    return Column(("lit", value))


def _as_col(v) -> Column:
    if isinstance(v, Column):
        return v
    return lit_col(v)


def canonical_node(c):
    """Hashable structural key for a Column AST. Used to decide whether two
    unresolved expressions are the same expression (e.g. the
    single-distinct-input restriction) — unlike Expression.pretty(), it
    keeps every non-child constructor arg (literals, scales, pads)."""
    if isinstance(c, Column):
        return ("col",) + tuple(canonical_node(p) for p in c.node)
    if isinstance(c, tuple):
        return tuple(canonical_node(p) for p in c)
    if isinstance(c, (list, dict, set)):
        return repr(c)
    return c


# Free functions mirroring pyspark.sql.functions.
def upper(c: Column) -> Column:
    return Column(("upper", _as_col(c)))


def lower(c: Column) -> Column:
    return Column(("lower", _as_col(c)))


def length(c: Column) -> Column:
    return Column(("length", _as_col(c)))


def concat(*cs) -> Column:
    return Column(("concat", tuple(_as_col(c) for c in cs)))


def coalesce_cols(*cs) -> Column:
    return Column(("coalesce", tuple(_as_col(c) for c in cs)))


def when(cond: Column, value) -> "WhenBuilder":
    return WhenBuilder([(cond, _as_col(value))])


class WhenBuilder(Column):
    def __init__(self, branches):
        self.branches = branches
        super().__init__(("when", tuple(branches), None))

    def when(self, cond: Column, value) -> "WhenBuilder":
        return WhenBuilder(self.branches + [(cond, _as_col(value))])

    def otherwise(self, value) -> Column:
        return Column(("when", tuple(self.branches), _as_col(value)))


def year(c):
    return Column(("year", _as_col(c)))


def month(c):
    return Column(("month", _as_col(c)))


def dayofmonth(c):
    return Column(("dayofmonth", _as_col(c)))


def sqrt_col(c):
    return Column(("sqrt", _as_col(c)))


def abs_col(c):
    return Column(("abs", _as_col(c)))


def round_col(c, scale=0):
    return Column(("round", _as_col(c), scale))


def murmur3_hash(*cs):
    return Column(("hash", tuple(_as_col(c) for c in cs)))


def md5(c) -> Column:
    """MD5 of a string column's UTF-8 bytes as a 32-char lowercase
    hex string (Spark Md5; NULL in, NULL out)."""
    return Column(("md5", _as_col(c)))


def concat_ws(sep: str, *cs) -> Column:
    return Column(("concat_ws", sep, tuple(_as_col(c) for c in cs)))


def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    return Column(("regexp_extract", _as_col(c), pattern, idx))


def translate(c, src: str, to: str) -> Column:
    return Column(("translate", _as_col(c), src, to))


def split(c, delim: str, index: int) -> Column:
    """split(str, delim)[index]: the ``index``-th (0-based) element of
    the literal-delimiter split — Spark's split(...).getItem(i) pattern
    (array columns are not a device type; the element access IS the
    expression). Out-of-range indices are NULL; trailing empty elements
    are kept (limit=-1 semantics)."""
    return Column(("split", _as_col(c), delim, int(index)))


def substring_index(c, delim: str, count: int) -> Column:
    """substring_index(str, delim, count) with Spark/Hive semantics over
    a literal delimiter."""
    return Column(("substring_index", _as_col(c), delim, int(count)))


def repeat(c, n: int) -> Column:
    return Column(("repeat", _as_col(c), n))


def reverse(c) -> Column:
    return Column(("reverse", _as_col(c)))


def initcap(c) -> Column:
    return Column(("initcap", _as_col(c)))


def lpad(c, length: int, pad: str = " ") -> Column:
    return Column(("lpad", _as_col(c), length, pad))


def rpad(c, length: int, pad: str = " ") -> Column:
    return Column(("rpad", _as_col(c), length, pad))


def trim(c) -> Column:
    return Column(("trim", _as_col(c)))


def ltrim(c) -> Column:
    return Column(("ltrim", _as_col(c)))


def rtrim(c) -> Column:
    return Column(("rtrim", _as_col(c)))


def locate(needle: str, c, pos: int = 1) -> Column:
    return Column(("locate", _as_col(c), needle, pos))


def instr(c, needle: str) -> Column:
    return Column(("locate", _as_col(c), needle, 1))


def replace_str(c, search: str, repl: str) -> Column:
    return Column(("replace", _as_col(c), search, repl))


def bround_col(c, scale: int = 0) -> Column:
    return Column(("bround", _as_col(c), scale))


def floor_col(c) -> Column:
    return Column(("floor", _as_col(c)))


def ceil_col(c) -> Column:
    return Column(("ceil", _as_col(c)))


def exp_col(c) -> Column:
    return Column(("exp", _as_col(c)))


def log_col(c) -> Column:
    return Column(("log", _as_col(c)))


def log10_col(c) -> Column:
    return Column(("log10", _as_col(c)))


def log2_col(c) -> Column:
    return Column(("log2", _as_col(c)))


def logb(base, c) -> Column:
    """log(base, x) — Spark's two-argument log (Logarithm). The base can
    be a column or a literal."""
    return Column(("logb", _as_col(base), _as_col(c)))


def at_least_n_non_nulls(n: int, *cs) -> Column:
    """True when at least n of the columns are non-null (NaN counts as
    null for floats) — the df.na.drop(thresh=n) predicate."""
    return Column(("at_least_n_non_nulls", int(n),
                   tuple(_as_col(c) for c in cs)))


def pow_col(c, p) -> Column:
    return Column(("pow", _as_col(c), _as_col(p)))


def signum_col(c) -> Column:
    return Column(("signum", _as_col(c)))


def isnan_col(c) -> Column:
    return Column(("isnan", _as_col(c)))


def nanvl(c, fallback) -> Column:
    return Column(("nanvl", _as_col(c), _as_col(fallback)))


def least(*cs) -> Column:
    return Column(("least", tuple(_as_col(c) for c in cs)))


def greatest(*cs) -> Column:
    return Column(("greatest", tuple(_as_col(c) for c in cs)))


def pmod(c, d) -> Column:
    return Column(("pmod", _as_col(c), _as_col(d)))


def _unary_fn(kind):
    def f(c):
        return Column((kind, _as_col(c)))
    f.__name__ = kind
    return f


sin_col = _unary_fn("sin")
cos_col = _unary_fn("cos")
tan_col = _unary_fn("tan")
asin_col = _unary_fn("asin")
acos_col = _unary_fn("acos")
atan_col = _unary_fn("atan")
sinh_col = _unary_fn("sinh")
cosh_col = _unary_fn("cosh")
tanh_col = _unary_fn("tanh")
asinh_col = _unary_fn("asinh")
acosh_col = _unary_fn("acosh")
atanh_col = _unary_fn("atanh")
cbrt_col = _unary_fn("cbrt")
expm1_col = _unary_fn("expm1")
log1p_col = _unary_fn("log1p")
degrees_col = _unary_fn("degrees")
radians_col = _unary_fn("radians")
rint_col = _unary_fn("rint")

quarter = _unary_fn("quarter")
dayofweek = _unary_fn("dayofweek")
weekday = _unary_fn("weekday")
dayofyear = _unary_fn("dayofyear")
last_day = _unary_fn("last_day")
hour = _unary_fn("hour")
minute = _unary_fn("minute")
second = _unary_fn("second")
to_unix_timestamp = _unary_fn("to_unix_timestamp")
from_unixtime = _unary_fn("from_unixtime")


def date_add(c, n) -> Column:
    return Column(("date_add", _as_col(c), _as_col(n)))


def date_sub(c, n) -> Column:
    return Column(("date_sub", _as_col(c), _as_col(n)))


def datediff(end, start) -> Column:
    return Column(("datediff", _as_col(end), _as_col(start)))


def add_months(c, n) -> Column:
    return Column(("add_months", _as_col(c), _as_col(n)))


def trunc(c, fmt: str) -> Column:
    return Column(("trunc", _as_col(c), fmt))


def rand(seed: int = 0) -> Column:
    """Uniform [0,1) per row (nondeterministic; seeded per partition)."""
    return Column(("rand", int(seed)))


def spark_partition_id() -> Column:
    return Column(("spark_partition_id",))


def monotonically_increasing_id() -> Column:
    return Column(("monotonically_increasing_id",))


def input_file_name() -> Column:
    return Column(("input_file_name",))


# Aggregate builders.
def agg_sum(c) -> Column:
    return Column(("agg", "sum", _as_col(c)))


def agg_count(c=None) -> Column:
    return Column(("agg", "count", None if c is None else _as_col(c)))


def agg_min(c) -> Column:
    return Column(("agg", "min", _as_col(c)))


def agg_max(c) -> Column:
    return Column(("agg", "max", _as_col(c)))


def agg_avg(c) -> Column:
    return Column(("agg", "avg", _as_col(c)))


def agg_count_distinct(c) -> Column:
    """count(DISTINCT c) — lowered via the partial-merge mode combos of
    aggregate.scala:305 (dedup by (keys, c), then count)."""
    return Column(("aggd", "count", _as_col(c)))


def agg_sum_distinct(c) -> Column:
    return Column(("aggd", "sum", _as_col(c)))


def agg_avg_distinct(c) -> Column:
    return Column(("aggd", "avg", _as_col(c)))


def agg_first(c, ignore_nulls=True) -> Column:
    return Column(("agg", "first", _as_col(c), ignore_nulls))


def agg_last(c, ignore_nulls=True) -> Column:
    return Column(("agg", "last", _as_col(c), ignore_nulls))


# ---------------------------------------------------------------------------
# Window DSL (pyspark Window analog; ref GpuWindowExec.scala:92 /
# GpuWindowExpression.scala frame envelope)
# ---------------------------------------------------------------------------

class WindowDef:
    """A window specification: partitioning, ordering, and an optional
    ROWS frame. Built via the ``Window`` builder, consumed by
    ``Column.over``."""

    def __init__(self, partition_cols=(), order_cols=(), frame=None):
        self.partition_cols = tuple(partition_cols)
        self.order_cols = tuple(order_cols)
        self.frame = frame          # None | ("rows", start, end)

    def partition_by(self, *cols) -> "WindowDef":
        return WindowDef(tuple(_name_or_col(c) for c in cols),
                         self.order_cols, self.frame)

    partitionBy = partition_by

    def order_by(self, *cols) -> "WindowDef":
        return WindowDef(self.partition_cols,
                         tuple(_name_or_col(c) for c in cols), self.frame)

    orderBy = order_by

    def rows_between(self, start, end) -> "WindowDef":
        """ROWS frame: ``start``/``end`` are row offsets relative to the
        current row (negative = preceding); ``Window.unboundedPreceding``
        / ``unboundedFollowing`` for unbounded ends."""
        return WindowDef(self.partition_cols, self.order_cols,
                         ("rows", start, end))

    rowsBetween = rows_between


def _name_or_col(c) -> "Column":
    """Strings name COLUMNS here (pyspark Window semantics), unlike the
    value-literal convention of expression operands."""
    return col(c) if isinstance(c, str) else c


class _WindowBuilder:
    """Entry point mirroring ``pyspark.sql.Window``."""

    unboundedPreceding = None
    unboundedFollowing = None
    currentRow = 0

    @staticmethod
    def partition_by(*cols) -> WindowDef:
        return WindowDef().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowDef:
        return WindowDef().order_by(*cols)

    orderBy = order_by


Window = _WindowBuilder


def row_number() -> Column:
    return Column(("winfn", "row_number", None, 0))


def rank() -> Column:
    return Column(("winfn", "rank", None, 0))


def dense_rank() -> Column:
    return Column(("winfn", "dense_rank", None, 0))


def lead(c, offset: int = 1) -> Column:
    return Column(("winfn", "lead", _as_col(c), offset))


def lag(c, offset: int = 1) -> Column:
    return Column(("winfn", "lag", _as_col(c), offset))


def is_window_column(c: Column) -> bool:
    """True when ``c`` is a window expression (possibly aliased)."""
    node = c.node
    while node[0] == "alias":
        node = node[1].node
    return node[0] == "window"


# ---------------------------------------------------------------------------
# Generate DSL (explode of inline arrays; ref GpuGenerateExec.scala)
# ---------------------------------------------------------------------------

def explode(*elements) -> Column:
    """explode(array(e1, .., ek)): one output row per element. The engine's
    type envelope is scalar-only (reference isSupportedType gate), so the
    array is inline — K element expressions per row."""
    return Column(("explode", tuple(_as_col(e) for e in elements),
                   False, False))


def explode_outer(*elements) -> Column:
    return Column(("explode", tuple(_as_col(e) for e in elements),
                   False, True))


def posexplode(*elements) -> Column:
    return Column(("explode", tuple(_as_col(e) for e in elements),
                   True, False))


def is_generate_column(c: Column) -> bool:
    node = c.node
    while node[0] == "alias":
        node = node[1].node
    return node[0] == "explode"


# ---------------------------------------------------------------------------
# Expression resolution (name -> ordinal, untyped -> typed)
# ---------------------------------------------------------------------------

class ResolutionError(ValueError):
    pass


def resolve(c: Column, schema: Schema) -> Expression:
    """Bind an untyped Column AST against a schema."""
    node = c.node
    kind = node[0]
    names = [n for n, _ in schema]

    def rec(x):
        return resolve(x, schema)

    if kind == "ref":
        name = node[1]
        if name not in names:
            raise ResolutionError(
                f"column {name!r} not in {names}")
        i = names.index(name)
        return BoundReference(i, schema[i][1], name)
    if kind == "lit":
        v = node[1]
        if v is None:
            raise ResolutionError("untyped NULL literal; use typed lit")
        return E.lit(v)
    if kind == "bindslot":
        # Hoisted literal (plan/plan_cache.py): value-free leaf whose
        # binding arrives at execution time as a runtime kernel input.
        from spark_rapids_tpu.exprs.bindslots import BindSlotExpr
        return BindSlotExpr(node[1], node[2])
    if kind == "alias":
        return rec(node[1])
    if kind == "cast":
        return E.Cast(rec(node[1]), node[2])
    if kind == "neg":
        return E.UnaryMinus(rec(node[1]))
    if kind == "not":
        return E.Not(rec(node[1]))
    if kind in ("add", "sub", "mul", "div", "mod", "eq", "lt", "le", "gt",
                "ge", "and", "or"):
        l, r = rec(node[1]), rec(node[2])
        l, r = _coerce_pair(l, r)
        table = {
            "add": E.Add, "sub": E.Subtract, "mul": E.Multiply,
            "div": E.Divide, "mod": E.Remainder, "eq": E.EqualTo,
            "lt": E.LessThan, "le": E.LessThanOrEqual, "gt": E.GreaterThan,
            "ge": E.GreaterThanOrEqual, "and": E.And, "or": E.Or,
        }
        return table[kind](l, r)
    if kind == "isnull":
        return E.IsNull(rec(node[1]))
    if kind == "isnotnull":
        return E.IsNotNull(rec(node[1]))
    if kind == "isin":
        return E.InSet(rec(node[1]), node[2])
    if kind == "substr":
        return E.Substring(rec(node[1]), rec(node[2]), rec(node[3]))
    if kind == "startswith":
        return E.StartsWith(rec(node[1]), E.lit(node[2]))
    if kind == "endswith":
        return E.EndsWith(rec(node[1]), E.lit(node[2]))
    if kind == "contains":
        return E.Contains(rec(node[1]), E.lit(node[2]))
    if kind == "like":
        return E.Like(rec(node[1]), node[2])
    if kind == "regexp_replace":
        return E.RegExpReplace(rec(node[1]), node[2], node[3])
    if kind == "upper":
        return E.Upper(rec(node[1]))
    if kind == "lower":
        return E.Lower(rec(node[1]))
    if kind == "length":
        return E.Length(rec(node[1]))
    if kind == "concat":
        return E.ConcatStrings(*[rec(x) for x in node[1]])
    if kind == "pyudf":
        from spark_rapids_tpu.exprs.pyudf import PythonUDF
        _, func, rt, arg_cols, reason = node
        return PythonUDF(func, rt,
                         [resolve(a, schema) for a in arg_cols],
                         reason or "")
    if kind == "coalesce":
        return E.Coalesce(*[rec(x) for x in node[1]])
    if kind == "when":
        branches = [(rec(cond), rec(val)) for cond, val in node[1]]
        else_e = rec(node[2]) if node[2] is not None else None
        return E.CaseWhen(branches, else_e)
    if kind == "year":
        return E.Year(rec(node[1]))
    if kind == "month":
        return E.Month(rec(node[1]))
    if kind == "dayofmonth":
        return E.DayOfMonth(rec(node[1]))
    if kind == "sqrt":
        return E.Sqrt(rec(node[1]))
    if kind == "abs":
        return E.Abs(rec(node[1]))
    if kind == "round":
        return E.Round(rec(node[1]), node[2])
    if kind == "hash":
        return E.Murmur3Hash([rec(x) for x in node[1]])
    if kind == "md5":
        return E.Md5(rec(node[1]))
    if kind == "bround":
        return E.BRound(rec(node[1]), node[2])
    if kind == "concat_ws":
        return E.ConcatWs(node[1], *[rec(x) for x in node[2]])
    if kind == "regexp_extract":
        return E.RegExpExtract(rec(node[1]), node[2], node[3])
    if kind == "translate":
        return E.Translate(rec(node[1]), node[2], node[3])
    if kind == "split":
        return E.StringSplit(rec(node[1]), node[2], node[3])
    if kind == "substring_index":
        return E.SubstringIndex(rec(node[1]), node[2], node[3])
    if kind == "repeat":
        return E.StringRepeat(rec(node[1]), node[2])
    if kind == "reverse":
        return E.StringReverse(rec(node[1]))
    if kind == "initcap":
        return E.InitCap(rec(node[1]))
    if kind == "lpad":
        return E.StringLPad(rec(node[1]), node[2], node[3])
    if kind == "rpad":
        return E.StringRPad(rec(node[1]), node[2], node[3])
    if kind == "trim":
        return E.StringTrim(rec(node[1]))
    if kind == "ltrim":
        return E.StringTrimLeft(rec(node[1]))
    if kind == "rtrim":
        return E.StringTrimRight(rec(node[1]))
    if kind == "locate":
        return E.StringLocate(E.lit(node[2]), rec(node[1]),
                              E.lit(int(node[3])))
    if kind == "replace":
        return E.StringReplace(rec(node[1]), node[2], node[3])
    if kind == "isnan":
        return E.IsNan(rec(node[1]))
    if kind == "nanvl":
        return E.NaNvl(rec(node[1]), rec(node[2]))
    if kind == "least":
        return E.Least(*[rec(x) for x in node[1]])
    if kind == "greatest":
        return E.Greatest(*[rec(x) for x in node[1]])
    if kind == "pmod":
        return E.Pmod(rec(node[1]), rec(node[2]))
    if kind == "pow":
        return E.Pow(rec(node[1]), rec(node[2]))
    if kind == "logb":
        return E.Logarithm(rec(node[1]), rec(node[2]))
    if kind == "at_least_n_non_nulls":
        return E.AtLeastNNonNulls(node[1], *[rec(x) for x in node[2]])
    _UNARY_TABLE = {
        "floor": E.Floor, "ceil": E.Ceil, "exp": E.Exp, "log": E.Log,
        "log10": E.Log10, "log2": E.Log2, "log1p": E.Log1p,
        "expm1": E.Expm1, "cbrt": E.Cbrt, "sin": E.Sin, "cos": E.Cos,
        "tan": E.Tan, "asin": E.Asin, "acos": E.Acos, "atan": E.Atan,
        "sinh": E.Sinh, "cosh": E.Cosh, "tanh": E.Tanh,
        "asinh": E.Asinh, "acosh": E.Acosh, "atanh": E.Atanh,
        "degrees": E.ToDegrees, "radians": E.ToRadians, "rint": E.Rint,
        "signum": E.Signum,
        "quarter": E.Quarter, "dayofweek": E.DayOfWeek,
        "weekday": E.WeekDay, "dayofyear": E.DayOfYear,
        "last_day": E.LastDay, "hour": E.Hour, "minute": E.Minute,
        "second": E.Second, "to_unix_timestamp": E.ToUnixTimestamp,
        "from_unixtime": E.FromUnixTime,
    }
    if kind in _UNARY_TABLE:
        return _UNARY_TABLE[kind](rec(node[1]))
    if kind == "date_add":
        return E.DateAdd(rec(node[1]), rec(node[2]))
    if kind == "date_sub":
        return E.DateSub(rec(node[1]), rec(node[2]))
    if kind == "datediff":
        return E.DateDiff(rec(node[1]), rec(node[2]))
    if kind == "add_months":
        return E.AddMonths(rec(node[1]), rec(node[2]))
    if kind == "trunc":
        return E.TruncDate(rec(node[1]), node[2])
    if kind == "rand":
        return E.Rand(node[1])
    if kind == "spark_partition_id":
        return E.SparkPartitionID()
    if kind == "monotonically_increasing_id":
        return E.MonotonicallyIncreasingID()
    if kind == "input_file_name":
        return E.InputFileName()
    if kind == "sortorder":
        raise ResolutionError("sort order only valid in orderBy")
    raise ResolutionError(f"cannot resolve expression kind {kind!r}")


def _coerce_pair(l: Expression, r: Expression):
    """Numeric literal widening so col(int32) == lit(5) type-checks."""
    lt, rt = l.data_type(), r.data_type()
    if lt == rt:
        return l, r
    if lt.is_numeric and rt.is_numeric:
        return l, r   # binary templates widen internally
    if lt.is_datetime and rt.is_integral:
        return l, r
    if rt.is_datetime and lt.is_integral:
        return l, r
    if lt.is_string != rt.is_string:
        # cast the non-string side to string? Spark casts literals; keep
        # strict here — casts must be explicit.
        raise ResolutionError(f"type mismatch: {lt} vs {rt}")
    return l, r


# ---------------------------------------------------------------------------
# Logical plan nodes
# ---------------------------------------------------------------------------

class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__


def _cached_schema(fn):
    """Memoize a node's schema keyed on the IDENTITY of its children
    tuple. Plan rewrites (pruning._with_children, planner.merge_windows)
    never mutate a node in place — they shallow-copy and install a NEW
    children tuple — so tuple identity is a sound validity token, and
    holding the tuple in the memo keeps it alive (no id-reuse hazard).
    Without this, ``schema`` re-resolves every projection recursively on
    each access: a rollup plan like q67 pays ~30k resolve() calls per
    collect just answering type questions the tree already answered."""
    def get(self):
        memo = self.__dict__.get("_schema_memo")
        if memo is not None and memo[0] is self.children:
            return memo[1]
        s = fn(self)
        self.__dict__["_schema_memo"] = (self.children, s)
        return s
    return property(get)


@dataclasses.dataclass
class InMemoryScan(LogicalPlan):
    source_schema: Schema
    partitions: list            # List[List[HostBatch]]
    children = ()

    @property
    def schema(self) -> Schema:
        return self.source_schema


@dataclasses.dataclass
class FileScan(LogicalPlan):
    fmt: str                    # parquet | csv | orc
    paths: list
    source_schema: Schema
    options: dict
    # Pushed-down filter conjuncts: (column_name, op, value) with op in
    # eq/lt/le/gt/ge/isnotnull — evaluated against parquet row-group
    # min/max stats to skip whole row groups (GpuParquetScan predicate
    # pushdown analog; the full filter still runs above the scan).
    predicates: tuple = ()
    children = ()

    @property
    def schema(self) -> Schema:
        return self.source_schema


@dataclasses.dataclass
class LogicalRange(LogicalPlan):
    start: int
    end: int
    step: int
    num_partitions: int
    children = ()

    @property
    def schema(self) -> Schema:
        return (("id", dt.INT64),)


class _Unary(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        self.children = (child,)

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]


class LogicalFilter(_Unary):
    def __init__(self, child, condition: Column):
        super().__init__(child)
        self.condition = condition

    @_cached_schema
    def schema(self) -> Schema:
        return self.child.schema


class LogicalProject(_Unary):
    def __init__(self, child, projections: Sequence[Tuple[str, Column]]):
        super().__init__(child)
        self.projections = list(projections)

    @_cached_schema
    def schema(self) -> Schema:
        out = []
        for name, c in self.projections:
            e = resolve(c, self.child.schema)
            out.append((name, e.data_type()))
        return tuple(out)


class LogicalAggregate(_Unary):
    def __init__(self, child, group_by: Sequence[Tuple[str, Column]],
                 aggregates: Sequence[Tuple[str, Column]],
                 grouping: Optional[str] = None):
        super().__init__(child)
        self.group_by = list(group_by)
        self.aggregates = list(aggregates)
        # None = plain GROUP BY; "rollup"/"cube" lower through ExpandExec
        # (GROUPING SETS, GpuExpandExec.scala).
        assert grouping in (None, "rollup", "cube")
        self.grouping = grouping

    @_cached_schema
    def schema(self) -> Schema:
        from spark_rapids_tpu.plan.planner import resolve_agg
        out = []
        for name, c in self.group_by:
            out.append((name, resolve(c, self.child.schema).data_type()))
        for name, c in self.aggregates:
            fn = resolve_agg(c, self.child.schema)
            out.append((name, fn.result_type))
        return tuple(out)


class LogicalWindow(_Unary):
    """Appends window-expression columns sharing ONE window spec
    (ExtractWindowExpressions analog: the DataFrame layer extracts window
    columns out of select/with_column into a chain of these nodes; the
    planner merges adjacent nodes with the same spec and inserts the
    co-locating exchange underneath — GpuWindowExec.scala:92
    requiredChildDistribution)."""

    def __init__(self, child, exprs, window: "WindowDef"):
        super().__init__(child)
        self.exprs = list(exprs)        # [(out_name, fn_col Column)]
        self.window = window

    def spec_key(self):
        """Hashable structural identity of the window spec, for merging
        adjacent nodes that shuffle+sort identically."""
        return (tuple(canonical_node(c) for c in self.window.partition_cols),
                tuple(canonical_node(c) for c in self.window.order_cols),
                self.window.frame)

    def result_type(self, fn_col: Column) -> DataType:
        node = fn_col.node
        if node[0] == "winfn":
            kind = node[1]
            if kind in ("row_number", "rank", "dense_rank"):
                return dt.INT32
            return resolve(node[2], self.child.schema).data_type()
        if node[0] == "agg":
            kind = node[1]
            if kind == "count":
                return dt.INT64
            if kind == "avg":
                return dt.FLOAT64
            t = resolve(node[2], self.child.schema).data_type()
            if kind == "sum":
                return dt.FLOAT64 if t.is_floating else dt.INT64
            return t
        raise ResolutionError(
            f"unsupported window function {node[0]!r}")

    @_cached_schema
    def schema(self) -> Schema:
        return tuple(self.child.schema) + tuple(
            (n, self.result_type(c)) for n, c in self.exprs)


class LogicalGenerate(_Unary):
    """explode/posexplode of an inline array (GpuGenerateExec.scala):
    appends [pos?, element] columns, one output row per (row, element)."""

    def __init__(self, child, out_name: str, elements: Sequence[Column],
                 position: bool = False, outer: bool = False):
        super().__init__(child)
        self.out_name = out_name
        self.elements = list(elements)
        self.position = position
        self.outer = outer

    def element_type(self) -> DataType:
        t0 = resolve(self.elements[0], self.child.schema).data_type()
        return t0

    @_cached_schema
    def schema(self) -> Schema:
        out = list(self.child.schema)
        if self.position:
            out.append((f"{self.out_name}__pos", dt.INT32))
        out.append((self.out_name, self.element_type()))
        return tuple(out)


class LogicalSort(_Unary):
    def __init__(self, child, orders: Sequence[Column]):
        super().__init__(child)
        self.orders = list(orders)

    @_cached_schema
    def schema(self) -> Schema:
        return self.child.schema


class LogicalLimit(_Unary):
    def __init__(self, child, n: int):
        super().__init__(child)
        self.n = n

    @_cached_schema
    def schema(self) -> Schema:
        return self.child.schema


class LogicalRepartition(_Unary):
    def __init__(self, child, num_partitions: int,
                 keys: Optional[Sequence[Column]] = None):
        super().__init__(child)
        self.num_partitions = num_partitions
        self.keys = list(keys) if keys else None

    @_cached_schema
    def schema(self) -> Schema:
        return self.child.schema


class LogicalUnion(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        self.children = tuple(children)

    @_cached_schema
    def schema(self) -> Schema:
        return self.children[0].schema


class LogicalMapInPandas(_Unary):
    """mapInPandas (GpuMapInPandasExec analog)."""

    def __init__(self, child, fn, out_schema: Schema):
        super().__init__(child)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    @property
    def schema(self) -> Schema:
        return self.out_schema


class LogicalGroupedMapInPandas(_Unary):
    """groupBy().applyInPandas (GpuFlatMapGroupsInPandasExec analog)."""

    def __init__(self, child, key_names: Sequence[str], fn,
                 out_schema: Schema):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    @property
    def schema(self) -> Schema:
        return self.out_schema


class LogicalCoGroupedMapInPandas(LogicalPlan):
    """cogroup().applyInPandas (GpuCoGroupedMapInPandasExec analog)."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 fn, out_schema: Schema):
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    @property
    def schema(self) -> Schema:
        return self.out_schema


class LogicalAggInPandas(_Unary):
    """groupBy().agg of GROUPED_AGG pandas UDFs
    (GpuAggregateInPandasExec analog). ``aggs`` entries are
    (out_name, input_column_name, series_fn, result_type)."""

    def __init__(self, child, key_names: Sequence[str], aggs):
        super().__init__(child)
        self.key_names = list(key_names)
        self.aggs = list(aggs)

    @_cached_schema
    def schema(self) -> Schema:
        key_types = dict(self.child.schema)
        return tuple([(k, key_types[k]) for k in self.key_names]
                     + [(n, t) for n, _, _, t in self.aggs])


class LogicalJoin(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Column], right_keys: Sequence[Column],
                 join_type: str = "inner",
                 condition: Optional[Column] = None,
                 strategy: str = "auto"):
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        self.strategy = strategy    # auto | broadcast | shuffle

    @_cached_schema
    def schema(self) -> Schema:
        if self.join_type in ("semi", "anti"):
            return self.children[0].schema
        return tuple(self.children[0].schema) + \
            tuple(self.children[1].schema)
