"""JAX version shim SPI (ref: SparkShims.scala:61 + the per-version shim
layer, sql-plugin/.../shims/).

The reference abstracts Spark's breaking API drift behind a shim
provider chosen at runtime; this engine's moving substrate is JAX, whose
public API drifts the same way (shard_map's home and kwargs, the tree
API's module, pytree registration). Every version-sensitive touchpoint
routes through this package so a JAX upgrade is a one-file change, and
``provider()`` names the resolved shim for diagnostics (the
SparkShimServiceProvider.matchesVersion analog)."""

from __future__ import annotations

import jax


def provider() -> str:
    """Human-readable name of the resolved shim set."""
    flavor = "jax-native-shard-map" if hasattr(jax, "shard_map") \
        else "jax-experimental-shard-map"
    return f"jax {jax.__version__} ({flavor}, tree={_TREE_FLAVOR})"


# -- shard_map (moved from jax.experimental to jax; kwargs renamed) ------

def shard_map(fn, mesh, in_specs, out_specs):
    """Version-tolerant shard_map: newer jax exposes jax.shard_map; older
    versions use jax.experimental.shard_map.shard_map with check_rep."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    except TypeError:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


# -- tree API (jax.tree since 0.4.25; jax.tree_util before) --------------

if hasattr(jax, "tree") and hasattr(jax.tree, "map"):
    _TREE_FLAVOR = "jax.tree"
    tree_map = jax.tree.map
    tree_flatten = jax.tree.flatten
    tree_unflatten = jax.tree.unflatten
else:                                           # pragma: no cover
    _TREE_FLAVOR = "jax.tree_util"
    tree_map = jax.tree_util.tree_map
    tree_flatten = jax.tree_util.tree_flatten
    tree_unflatten = jax.tree_util.tree_unflatten


def register_pytree_node(cls, flatten, unflatten):
    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
