"""spark-rapids-tpu: a TPU-native columnar SQL/ETL accelerator.

A brand-new framework with the capabilities of the RAPIDS Accelerator for Apache
Spark (reference: /root/reference, v0.3.0-SNAPSHOT), re-designed TPU-first on
JAX/XLA/Pallas rather than ported from the CUDA/cuDF design:

- Columnar batches are pytrees of fixed-capacity HBM device arrays with a
  runtime row count, so everything compiles under ``jax.jit`` with static
  shapes (ref: GpuColumnVector.java's cuDF-backed batches, re-imagined for
  XLA's compilation model).
- Physical operators (scan, project, filter, hash aggregate, join, sort,
  window, ...) evaluate whole batches with jax.numpy / Pallas kernels
  (ref: sql-plugin GpuExec nodes backed by libcudf JNI calls).
- The plan-rewrite layer keeps the reference's crown-jewel architecture:
  wrap -> tag -> convert with per-operator kill-switch configs, fallback
  reasons, and an ``explain`` report (ref: GpuOverrides.scala /
  RapidsMeta.scala), inserting explicit host<->device transitions.
- Shuffle is a planned collective exchange over the ICI mesh
  (jax.lax.all_to_all under shard_map) instead of a UCX peer-to-peer pull
  protocol (ref: shuffle-plugin/ucx/UCX.scala), with a host/disk spill tier.
"""

import os as _os

import jax as _jax

# Spark SQL semantics are 64-bit (LongType, DoubleType, TimestampType are all
# 8-byte); JAX's 32-bit default would silently truncate, so the engine
# requires x64 mode. On TPU, int64/float64 lower to emulated ops — the
# planner keeps hot paths in 32-bit/bfloat16 where Spark semantics allow.
_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: remote-TPU compiles can take minutes per
# program; the disk cache makes every shape/kernel a one-time cost across
# processes (the engine's capacity-bucket ladder keeps the program count
# bounded, so the cache converges quickly).
if not _os.environ.get("SRT_NO_COMPILE_CACHE"):
    _default_cache = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        ".jax_cache")
    _jax.config.update(
        "jax_compilation_cache_dir",
        _os.environ.get("JAX_COMPILATION_CACHE_DIR", _default_cache))
    _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

from spark_rapids_tpu.version import __version__

from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.columnar.dtypes import (
    BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE, TIMESTAMP, STRING,
    DataType,
)
from spark_rapids_tpu.columnar.batch import DeviceColumn, DeviceBatch
from spark_rapids_tpu.columnar.host import HostColumn, HostBatch

__all__ = [
    "__version__", "TpuConf", "DataType",
    "BOOL", "INT8", "INT16", "INT32", "INT64", "FLOAT32", "FLOAT64",
    "DATE", "TIMESTAMP", "STRING",
    "DeviceColumn", "DeviceBatch", "HostColumn", "HostBatch",
]
