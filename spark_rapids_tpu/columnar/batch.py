"""Device-resident columnar batches as JAX pytrees.

This is the TPU re-design of the reference's columnar runtime
(sql-plugin GpuColumnVector.java / ColumnarBatch over cuDF device columns).
cuDF allocates exact-size device buffers per kernel result; XLA instead wants
static shapes, so a batch here is a *fixed-capacity* set of device arrays plus
a runtime ``num_rows`` scalar — rows past ``num_rows`` are padding. Capacities
come from a power-of-two bucket ladder so the number of distinct compiled
programs stays bounded (SURVEY.md §7 "hard parts" #1).

Layout per column:
- fixed-width type T: ``data (capacity,) T`` + ``validity (capacity,) bool``
- string: ``data (capacity, width) uint8`` (zero-padded) +
  ``lengths (capacity,) int32`` + validity. Fixed-width padded bytes are the
  TPU-first answer to cuDF's offsets+chars: every string op becomes a dense
  (N, W) vector op on the VPU instead of a gather over a ragged buffer.

Null semantics: ``validity[i]`` True means non-null. Padding rows have
validity False and zeroed data so results stay deterministic.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.dtypes import DataType

MIN_CAPACITY = 8


def bucket_capacity(n: int) -> int:
    """Round row count up to the capacity bucket ladder.

    Rungs at 2^k and 3*2^(k-1) (8, 12, 16, 24, 32, ...): every row-movement
    kernel's cost scales with CAPACITY on this chip, so the plain
    power-of-two ladder's worst case (~2x padding) costs real wall time —
    e.g. a 750k-row parquet row group padded to 1M pays 33% on every op.
    Mid rungs cap the waste at ~33% for 2x the compiled-program count
    (amortized by the persistent compilation cache)."""
    cap = MIN_CAPACITY
    while cap < n:
        if cap * 3 // 2 >= n:
            return cap * 3 // 2
        cap *= 2
    return cap


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column of a device batch. A pytree: arrays are leaves, dtype is aux."""

    dtype: DataType
    data: jax.Array            # (capacity,) or (capacity, width) uint8 for strings
    validity: jax.Array        # (capacity,) bool, True = non-null
    lengths: Optional[jax.Array] = None   # (capacity,) int32, strings only

    # -- pytree protocol -----------------------------------------------------
    def tree_flatten(self):
        if self.dtype.is_string:
            return (self.data, self.validity, self.lengths), self.dtype
        return (self.data, self.validity), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, leaves):
        if dtype.is_string:
            data, validity, lengths = leaves
            return cls(dtype, data, validity, lengths)
        data, validity = leaves
        return cls(dtype, data, validity)

    # -- shape info ----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def string_width(self) -> int:
        assert self.dtype.is_string
        return self.data.shape[1]

    # -- construction --------------------------------------------------------
    @classmethod
    def full_null(cls, dtype: DataType, capacity: int,
                  string_width: int = 8) -> "DeviceColumn":
        if dtype.is_string:
            return cls(dtype,
                       jnp.zeros((capacity, string_width), jnp.uint8),
                       jnp.zeros((capacity,), jnp.bool_),
                       jnp.zeros((capacity,), jnp.int32))
        return cls(dtype,
                   jnp.zeros((capacity,), dtype.np_dtype),
                   jnp.zeros((capacity,), jnp.bool_))

    # -- row movement primitives --------------------------------------------
    def gather(self, indices: jax.Array, valid_dst: jax.Array) -> "DeviceColumn":
        """Take rows at ``indices``; ``valid_dst`` masks live destination rows."""
        data = jnp.take(self.data, indices, axis=0, mode="clip")
        validity = jnp.take(self.validity, indices, axis=0, mode="clip") & valid_dst
        data = _zero_dead(data, validity)
        if self.dtype.is_string:
            lengths = jnp.take(self.lengths, indices, axis=0, mode="clip")
            lengths = jnp.where(validity, lengths, 0)
            return DeviceColumn(self.dtype, data, validity, lengths)
        return DeviceColumn(self.dtype, data, validity)

    def scatter(self, positions: jax.Array, capacity: int) -> "DeviceColumn":
        """Write row i to ``positions[i]``; positions >= capacity are dropped."""
        if self.dtype.is_string:
            shape = (capacity, self.string_width)
        else:
            shape = (capacity,)
        data = jnp.zeros(shape, self.data.dtype).at[positions].set(
            self.data, mode="drop")
        validity = jnp.zeros((capacity,), jnp.bool_).at[positions].set(
            self.validity, mode="drop")
        if self.dtype.is_string:
            lengths = jnp.zeros((capacity,), jnp.int32).at[positions].set(
                self.lengths, mode="drop")
            return DeviceColumn(self.dtype, data, validity, lengths)
        return DeviceColumn(self.dtype, data, validity)

    def with_validity(self, validity: jax.Array) -> "DeviceColumn":
        data = _zero_dead(self.data, validity)
        if self.dtype.is_string:
            return DeviceColumn(self.dtype, data, validity,
                                jnp.where(validity, self.lengths, 0))
        return DeviceColumn(self.dtype, data, validity)


def _zero_dead(data: jax.Array, validity: jax.Array) -> jax.Array:
    """Zero data where validity is False (keeps padding deterministic)."""
    if data.ndim == 2:
        return jnp.where(validity[:, None], data, jnp.zeros_like(data))
    return jnp.where(validity, data, jnp.zeros_like(data))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceBatch:
    """A fixed-capacity columnar batch in HBM: the unit all operators consume.

    ``num_rows`` is a device int32 scalar so that data-dependent row counts
    (filter/join/groupby outputs) never force a recompile; ``capacity`` is
    static. Mirrors the role of the reference's ColumnarBatch of
    GpuColumnVectors (GpuColumnVector.java:from(Table)).

    ``sel`` is an optional (capacity,) bool SELECTION VECTOR: rows inside
    the ``num_rows`` prefix with sel False are deleted. Filters and join
    emits produce sel-batches instead of compacting (a 1M-row packed
    compaction costs ~100-400ms of device time on the target chip; a mask
    costs nothing) — the Velox/DuckDB selection-vector idea applied at
    batch granularity. Compaction happens only at materialization points
    (exchange, concat, sort output, download) via columnar/rowmove.py.
    """

    columns: Tuple[DeviceColumn, ...]
    num_rows: jax.Array          # int32 scalar
    # Host-known exact LIVE row count, when the producer knows it (uploads
    # do). NOT a pytree leaf: jit-produced batches lose it (None = unknown).
    # Lets consumers (exchange shrink, downloads) skip a device->host sync.
    rows_hint: Optional[int] = dataclasses.field(
        default=None, compare=False)
    sel: Optional[jax.Array] = None   # (capacity,) bool; None = all prefix

    def tree_flatten(self):
        if self.sel is not None:
            return (tuple(self.columns), self.num_rows, self.sel), True
        return (tuple(self.columns), self.num_rows), False

    @classmethod
    def tree_unflatten(cls, has_sel, leaves):
        if has_sel:
            columns, num_rows, sel = leaves
            return cls(tuple(columns), num_rows, sel=sel)
        columns, num_rows = leaves
        return cls(tuple(columns), num_rows)

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        # A zero-column batch (count(*) over fully-pruned input) still
        # carries liveness in its selection vector; its capacity is the
        # sel length, not 0, or row_mask breaks against sel.
        if self.sel is not None:
            return int(self.sel.shape[0])
        return 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def row_mask(self) -> jax.Array:
        """(capacity,) bool — True for live (non-padding, selected) rows."""
        mask = jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows
        if self.sel is not None:
            mask = mask & self.sel
        return mask

    def live_count(self) -> jax.Array:
        """int32 scalar: number of live rows (== num_rows when no sel)."""
        if self.sel is None:
            return jnp.asarray(self.num_rows, jnp.int32)
        return jnp.sum(self.row_mask().astype(jnp.int32))

    def with_sel(self, keep: jax.Array) -> "DeviceBatch":
        """Restrict live rows by ``keep`` without moving data (lazy
        filter). rows_hint is dropped — the live count changed."""
        sel = keep if self.sel is None else (self.sel & keep)
        return DeviceBatch(self.columns, self.num_rows, sel=sel)

    # -- row movement --------------------------------------------------------
    def gather(self, indices: jax.Array, new_num_rows: jax.Array) -> "DeviceBatch":
        from spark_rapids_tpu.columnar.rowmove import gather_rows
        return gather_rows(self, indices,
                           jnp.asarray(new_num_rows, jnp.int32))

    def compact(self, keep: jax.Array) -> "DeviceBatch":
        """Materialize rows where ``keep`` (ANDed with row_mask) as a packed
        prefix — the cuDF ``Table.filter`` analog, via one packed scatter
        per slab (columnar/rowmove.py)."""
        from spark_rapids_tpu.columnar.rowmove import compact_batch
        return compact_batch(self, keep)

    def head(self, n: jax.Array) -> "DeviceBatch":
        """First min(n, live) rows (GpuLocalLimit analog) — selection-only,
        no data movement."""
        live = self.row_mask()
        keep = jnp.cumsum(live.astype(jnp.int32)) <= jnp.asarray(n, jnp.int32)
        return self.with_sel(keep & live)

    def select(self, indices: Sequence[int]) -> "DeviceBatch":
        return DeviceBatch(tuple(self.columns[i] for i in indices),
                           self.num_rows, sel=self.sel)

    @property
    def dtypes(self) -> Tuple[DataType, ...]:
        return tuple(c.dtype for c in self.columns)

    def device_size_bytes(self) -> int:
        """Approximate HBM footprint (for the spill framework's accounting)."""
        total = 4
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size  # bool = 1 byte
            if c.lengths is not None:
                total += c.lengths.size * 4
        if self.sel is not None:
            total += self.sel.size
        return total


def concat_batches(batches: Sequence[DeviceBatch], capacity: int) -> DeviceBatch:
    """Concatenate the live rows of ``batches`` into one dense batch of
    ``capacity`` rows.

    The cuDF ``Table.concatenate`` analog used by GpuCoalesceBatches
    (GpuCoalesceBatches.scala:643), via one packed scatter per member
    (columnar/rowmove.py) — selection vectors compact away here.
    Capacities are static, so overflow is checked at trace time.
    """
    assert batches, "concat of zero batches"
    total_cap = sum(b.capacity for b in batches)
    assert total_cap <= capacity, (
        f"concat overflow: member capacities sum to {total_cap} > {capacity}")
    from spark_rapids_tpu.columnar.rowmove import concat_compact
    return concat_compact(batches, capacity)


def _kernel_lookup(kind: str, key_parts, builder):
    """Process-global kernel cache access (lazy import: ops.kernel_cache
    must stay import-cycle-free with this module)."""
    from spark_rapids_tpu.ops import kernel_cache as kc
    return kc.lookup(kind, key_parts, builder)


def jit_concat_batches(batches: Sequence[DeviceBatch],
                       capacity: int) -> DeviceBatch:
    """``concat_batches`` under jit. Cached per target capacity in the
    process-global kernel cache; jax's own cache handles distinct input
    pytree structures. Eager concat is a per-column scatter storm — under
    jit XLA fuses it into a few copies."""
    fn = _kernel_lookup("concat", (capacity,),
                        lambda: jax.jit(
                            lambda bs: concat_batches(bs, capacity)))
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory.oom import retry_on_oom

    def dispatch(bs):
        faults.fault_point("concat")
        return fn(bs)

    return retry_on_oom(dispatch, list(batches))


# Below this device size a shrink/compaction cannot repay its sizes-pull
# round trip on the tunneled link (shared by coalescing, broadcasts and
# downloads).
MIN_SHRINK_BYTES = 4 << 20


def coalesce_iter(batches, target_rows: int, shrink: bool = False,
                  target_bytes: int = 512 * 1024 * 1024):
    """Group a batch stream into ~``target_rows``-capacity batches with
    minimal host syncs (grouping keys off static capacities, the exchange
    serving idiom — GpuCoalesceBatches.scala:115 done the TPU way).

    Per-batch device work has a fixed floor on this chip (dispatch +
    kernel latency ~tens of ms at any size), so streaming 8 scan-file
    batches through a join probe or partial aggregate costs 8 floors
    where one coalesced batch costs one + a single packed concat gather.

    ``shrink=True`` additionally compacts sparse members first (one
    batched sizes pull per group, skipped where rows_hint is known):
    consumers whose kernels scale with CAPACITY (sort-based aggregation)
    must not pay 4M-row sorts for a selective join's 30k live rows.

    ``target_bytes`` bounds the coalesced device size as well — wide
    (many-string-column) rows must not ride the row target into
    multi-GB batches (the batchSizeBytes bound, GpuCoalesceBatches'
    byte goal).
    """
    group: List[DeviceBatch] = []
    group_cap = 0
    group_bytes = 0

    def flush():
        g = group
        if shrink:
            # Only batches worth compacting pay a sizes pull (below the
            # threshold the kernel-time saved can't repay a round trip).
            g, _ = shrink_all(g, min_bytes=MIN_SHRINK_BYTES)
        if len(g) == 1:
            return g[0]
        cap = bucket_capacity(sum(b.capacity for b in g))
        out = jit_concat_batches(g, cap)
        hints = [b.rows_hint for b in g]
        if all(h is not None for h in hints):
            out.rows_hint = sum(hints)
        return out

    for b in batches:
        bb = b.device_size_bytes()
        if group and (group_cap + b.capacity > target_rows
                      or group_bytes + bb > target_bytes):
            yield flush()
            group, group_cap, group_bytes = [], 0, 0
        group.append(b)
        group_cap += b.capacity
        group_bytes += bb
        if group_cap >= target_rows or group_bytes >= target_bytes:
            yield flush()
            group, group_cap, group_bytes = [], 0, 0
    if group:
        yield flush()


def shrink_to_capacity(batch: DeviceBatch, capacity: int) -> DeviceBatch:
    """Re-bucket a batch whose live rows fit a smaller capacity (after a
    groupby/filter the packed prefix is all that matters). Jitted;
    requires ``live_count <= capacity``. Selection vectors compact away
    (cost scales with the small OUTPUT capacity — rowmove.compact_to)."""
    if capacity >= batch.capacity and batch.sel is None:
        return batch
    hint = batch.rows_hint

    def _build():
        def _shrink(b: DeviceBatch) -> DeviceBatch:
            from spark_rapids_tpu.columnar.rowmove import compact_to
            if b.sel is not None:
                return compact_to(b, capacity, b.live_count())
            idx = jnp.arange(capacity, dtype=jnp.int32)
            return b.gather(idx, b.num_rows)
        return jax.jit(_shrink)

    out = _kernel_lookup("shrink", (capacity,), _build)(batch)
    out.rows_hint = hint
    return out


def shrink_all(batches: Sequence[DeviceBatch],
               min_bytes: int = 0) -> Tuple[List[DeviceBatch],
                                            List[Optional[int]]]:
    """Two-phase sizes-then-shrink over a batch list (SURVEY §7): pull
    every unknown live count in ONE batched ``jax.device_get`` (each sync
    is a full network round trip on a tunneled device), then re-bucket
    each batch to its live capacity. ``min_bytes`` skips the pull for
    batches too small for the saved transfer/compute to repay the sync —
    including selection-vector batches (every consumer handles sel);
    callers that NEED exact counts (the exchange's bucket accounting)
    keep the default 0. Returns (shrunk batches, live counts — None
    where the pull was skipped). The one shared implementation of this
    idiom for aggregates, exchanges, broadcasts and downloads."""
    import jax
    batches = list(batches)
    counts: List[Optional[int]] = [b.rows_hint for b in batches]
    unknown = [i for i, b in enumerate(batches)
               if counts[i] is None
               and b.device_size_bytes() > min_bytes]
    if unknown:
        pulled = jax.device_get([batches[i].live_count() for i in unknown])
        for i, c in zip(unknown, pulled):
            counts[i] = int(c)
    out = []
    for b, c in zip(batches, counts):
        if c is not None:
            b = shrink_to_capacity(b, bucket_capacity(max(c, 1)))
            b.rows_hint = c
        out.append(b)
    return out, counts


def sample_rows(batch: DeviceBatch, k: int) -> DeviceBatch:
    """Up to ``k`` evenly spaced live rows, as a k-capacity batch — the
    device-side half of range-bounds sampling (GpuRangePartitioner's
    reservoir sample): sample BEFORE downloading so a bounds probe moves
    k rows over the link instead of a whole batch."""
    def _build():
        def _sample(b: DeviceBatch) -> DeviceBatch:
            if b.sel is not None:
                from spark_rapids_tpu.columnar.rowmove import compact_batch
                b = compact_batch(b)
            n = jnp.maximum(b.num_rows, 1).astype(jnp.int64)
            slots = jnp.arange(k, dtype=jnp.int64)
            strided = ((slots * (n - 1)) // jnp.maximum(
                jnp.asarray(k - 1, jnp.int64), 1)).astype(jnp.int32)
            slots = slots.astype(jnp.int32)
            n = n.astype(jnp.int32)
            # With fewer live rows than slots the stride collapses to
            # mostly row 0; take the first n rows verbatim instead so
            # range-bound probes see distinct rows.
            idx = jnp.where(n > k, strided, jnp.minimum(slots, n - 1))
            take = jnp.minimum(jnp.asarray(k, jnp.int32), b.num_rows)
            return b.gather(idx, take)
        return jax.jit(_sample)

    return _kernel_lookup("sample", (k,), _build)(batch)


def string_repad(col: DeviceColumn, width: int) -> DeviceColumn:
    """Re-pad a string column's byte matrix to ``width`` (static)."""
    assert col.dtype.is_string
    cur = col.string_width
    if cur == width:
        return col
    if cur < width:
        pad = jnp.zeros((col.capacity, width - cur), jnp.uint8)
        return DeviceColumn(col.dtype, jnp.concatenate([col.data, pad], axis=1),
                            col.validity, col.lengths)
    # Narrowing: only legal when all lengths fit — caller's responsibility
    # (used by ops like substring that provably shrink strings). Lengths are
    # clamped so the column stays internally consistent either way.
    return DeviceColumn(col.dtype, col.data[:, :width], col.validity,
                        jnp.minimum(col.lengths, width))
