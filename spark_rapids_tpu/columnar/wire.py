"""Wire codec for host->device uploads: narrow dtypes + packed validity.

TPU-first re-design of the reference's GPU parquet decode
(GpuParquetScan.scala:1144 keeps *compressed pages* on the transfer path and
decodes on-device with cuDF). XLA has no byte-oriented snappy kernel, but the
same bandwidth win comes from a typed transform: before upload each column is
analyzed (vectorized numpy, one pass) and, when lossless, re-encoded to a
narrower wire type --

- integers whose [min, max] fits int8/int16/int32 ship narrow;
- float64 columns whose values are whole numbers in int32 range ship as
  ints (decoded by a pure int->f64 cast);
- float64 exactly representable as float32 ships as float32;
- all-valid validity vanishes (reconstructed from the row mask); otherwise
  it ships as packed bits (1/8th);
- string length columns ship int16 when the column width bounds them,
  int32 otherwise.

Only pure dtype CASTS are used on the device side. The TPU's float64 is
double-double emulation whose arithmetic (add/mul/div) is NOT correctly
rounded (measured ~2 ulps off), so any decode that computes — e.g. a
scaled-decimal ``int / 100`` — lands on a different f64 than the host
value and silently breaks bit-exact comparisons downstream (a filter
``x <= 0.07`` dropped every 0.07 row). Casts int<->f64 and f32->f64 are
exact on the emulated backend (verified), so the codec restricts itself
to them.

The device side widens back to the logical dtype inside ONE jitted decode
program per (capacity, spec) -- a few fused casts, so HBM traffic is the
only cost there. The transfer link (PCIe / a tunneled remote device) is the
scarce resource this trades against; reconstruction is bit-exact by
construction, so every engine invariant (zeroed padding, validity masking)
is preserved.

All buffers of a batch go up in a single ``jax.device_put`` call so the
transfers pipeline instead of paying one round trip per buffer.
"""

from __future__ import annotations

import struct
import threading
import zlib
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn


# ---------------------------------------------------------------------------
# Integrity framing for serialized batch blobs (spill frames, any future
# inter-process shuffle wire). A 16-byte header: magic | CRC32 | length.
# Deserialize verifies ALL THREE, so a flipped bit / truncated write /
# foreign blob raises WireCorruptionError at the frame boundary instead
# of np.frombuffer silently reinterpreting garbage into wrong rows.
# ---------------------------------------------------------------------------

_FRAME_MAGIC = b"SRTW"
_FRAME_HEADER = struct.Struct("<4sIQ")      # magic, crc32, payload length


class WireCorruptionError(ValueError):
    """A serialized frame failed its integrity check at deserialize."""


def frame_blob(blob: bytes) -> bytes:
    """Wrap ``blob`` in the checksummed wire frame."""
    return _FRAME_HEADER.pack(_FRAME_MAGIC, zlib.crc32(blob) & 0xFFFFFFFF,
                              len(blob)) + blob


def unframe_blob(framed: bytes) -> bytes:
    """Verify + strip the wire frame; raises :class:`WireCorruptionError`
    on any mismatch (magic, length, or CRC32)."""
    if len(framed) < _FRAME_HEADER.size:
        raise WireCorruptionError(
            f"frame truncated: {len(framed)} bytes < header")
    magic, crc, length = _FRAME_HEADER.unpack_from(framed)
    if magic != _FRAME_MAGIC:
        raise WireCorruptionError(f"bad frame magic {magic!r}")
    payload = framed[_FRAME_HEADER.size:]
    if len(payload) != length:
        raise WireCorruptionError(
            f"frame length mismatch: header says {length}, "
            f"payload is {len(payload)}")
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != crc:
        raise WireCorruptionError(
            f"frame CRC32 mismatch: header {crc:#010x}, "
            f"payload {actual:#010x}")
    return payload

# Column wire spec (static, hashable -- part of the decode jit cache key):
#   numeric: ("num", logical_name, wire_np_name, vmode)
#   string:  ("str", width, lengths_np_name, vmode)
#   dict num: ("dnum", logical_name, code_np_name, dict_cap, vmode)
#   dict str: ("dstr", width, code_np_name, dict_cap, vmode)
# vmode: "all" (validity == row mask) | "packed" (bit-packed uint8).
#
# Dictionary encoding is the LZ4-of-this-wire (NvcompLZ4CompressionCodec
# analog): XLA cannot run a byte-serial decompressor, but a gather from a
# small value table is one exact fused kernel — and TPC-shaped data is
# full of low-cardinality columns (flags, modes, quantities, discounts)
# where an 8-byte float or an 8..32-byte string row ships as a 1-2 byte
# code. Exactness: the gathered values ARE the host bit patterns (no
# arithmetic), so emulated-f64 rounding never enters.

_DICT_MAX = 4096            # value-table entries worth a table gather
_DICT_SAMPLE = 1 << 16


def _try_dict(values: np.ndarray, n: int):
    """(codes, uniques) via pandas factorize when cardinality is low
    enough to pay off, else None. Codes are -1-free (values prefiltered
    for NaN; nulls were zeroed upstream)."""
    if n == 0:
        return None
    if values.dtype.kind == "f":
        v = values[:n]
        # factorize hashes -0.0 == 0.0, which would drop the sign bit.
        if not np.isfinite(v).all() or np.any((v == 0) & np.signbit(v)):
            return None
    sample = values[:min(n, _DICT_SAMPLE)]
    if len(np.unique(sample)) > _DICT_MAX // 4:
        return None
    import pandas as pd
    codes, uniques = pd.factorize(values[:n], sort=False)
    if len(uniques) > _DICT_MAX:
        return None
    return codes, uniques

_INT_CANDIDATES = (
    (np.int8, -128, 127),
    (np.int16, -32768, 32767),
    (np.int32, -(2 ** 31), 2 ** 31 - 1),
)

def _narrow_int(values: np.ndarray, itemsize: int):
    """Smallest int dtype whose range covers values (None = keep)."""
    if values.size == 0:
        return np.int8
    mn = values.min()
    mx = values.max()
    for cand, lo, hi in _INT_CANDIDATES:
        if np.dtype(cand).itemsize >= itemsize:
            return None
        if lo <= mn and mx <= hi:
            return cand
    return None


def _encode_float64(values: np.ndarray):
    """Returns (wire_array, wire_np_name) or None. Lossless only, and the
    device decode must be a pure CAST (emulated-f64 arithmetic is not
    correctly rounded — see module docstring): whole numbers in int32
    range ship as narrow ints; exactly-f32-representable ships as f32.
    NaN/inf/-0.0 disqualify the int path (-0.0 would become +0.0)."""
    with np.errstate(invalid="ignore"):
        finite = np.isfinite(values).all() if values.size else True
    if finite and not (values.size
                       and np.any((values == 0) & np.signbit(values))):
        r = np.rint(values)
        if not np.any(np.abs(r) > 2 ** 31 - 1) \
                and np.array_equal(r, values):
            narrow = _narrow_int(r, 8) or np.int32
            return r.astype(narrow), np.dtype(narrow).name
    f32 = values.astype(np.float32)
    if np.array_equal(f32.astype(np.float64), values):
        return f32, "float32"
    return None


def encode_column(hc, name: str, n: int, cap: int,
                  string_widths: Optional[dict]) -> Tuple[List[np.ndarray],
                                                          tuple]:
    """Host-side encode of one column -> (wire arrays, static spec)."""
    from spark_rapids_tpu.columnar.host import strings_to_matrix
    validity = np.zeros(cap, dtype=np.bool_)
    validity[:n] = hc.validity
    all_valid = bool(validity[:n].all())
    if all_valid:
        vmode, varrs = "all", []
    else:
        vmode = "packed"
        varrs = [np.packbits(validity, bitorder="little")]

    if hc.dtype.is_string:
        # Dictionary path first: a low-cardinality string column (flags,
        # modes, segments) ships 1-2 byte codes + a tiny value table
        # instead of a (rows x width) byte matrix. All probing runs on
        # the dense byte MATRIX (never the lazy per-row object array):
        # rows keyed as (big-endian length | content bytes) void scalars,
        # compared bytewise by np.unique — fully vectorized.
        m0, lens0 = strings_to_matrix(hc)
        lens0 = np.where(hc.validity, lens0, 0).astype(np.int32)
        mw = m0.shape[1]
        d = None
        if n:
            keyed = np.zeros((n, mw + 4), np.uint8)
            keyed[:, :4] = lens0.astype(">i4").view(np.uint8) \
                .reshape(n, 4)
            if mw:
                keyed[:, 4:] = np.where(hc.validity[:, None],
                                        m0[:n], 0)
            key = np.ascontiguousarray(keyed).view(
                [("k", f"V{mw + 4}")]).ravel()
            if len(np.unique(key[:_DICT_SAMPLE])) <= _DICT_MAX // 4:
                uniq, first_idx, codes = np.unique(
                    key, return_index=True, return_inverse=True)
                if len(uniq) <= _DICT_MAX:
                    d = (codes, first_idx)
        if d is not None:
            codes, first_idx = d
            k = len(first_idx)
            ulens = lens0[first_idx]
            want = dt.string_width_bucket(int(ulens.max()) if k else 0)
            if string_widths and name in string_widths:
                want = max(want, string_widths[name])
            # The all-zero key (empty/invalid rows) is the code padding
            # rows take; add one if the column had no empty strings.
            zeros = np.flatnonzero(ulens == 0)
            dict_rows = list(first_idx)
            if zeros.size:
                zero_code = int(zeros[0])
            else:
                dict_rows.append(None)
                zero_code = k
                k += 1
            dict_cap = 8
            while dict_cap < k:
                dict_cap *= 2
            table = np.zeros((dict_cap, want), dtype=np.uint8)
            len_t = np.int16 if want <= 32767 else np.int32
            len_table = np.zeros(dict_cap, dtype=len_t)
            w = min(want, mw)
            for i, ri in enumerate(dict_rows):
                if ri is None:
                    continue
                if w:
                    table[i, :w] = np.where(hc.validity[ri],
                                            m0[ri, :w], 0)
                len_table[i] = min(int(ulens[i]) if i < len(ulens)
                                   else 0, want)
            code_t = np.int8 if dict_cap <= 128 else np.int16
            codes_arr = np.full(cap, zero_code, dtype=code_t)
            codes_arr[:n] = codes
            return [codes_arr, table, len_table] + varrs, \
                ("dstr", want, np.dtype(code_t).name, dict_cap, vmode)
        m, lens = m0, lens0
        lens = np.where(hc.validity, lens, 0)
        want = dt.string_width_bucket(int(lens.max()) if n else 0)
        if string_widths and name in string_widths:
            want = max(want, string_widths[name])
        data = np.zeros((cap, want), dtype=np.uint8)
        w = min(want, m.shape[1])
        data[:n, :w] = np.where(hc.validity[:, None], m, 0)[:, :w]
        # Lengths are bounded by the column width: int16 only when the
        # width itself fits (a >32767-byte string would otherwise wrap).
        len_t = np.int16 if want <= 32767 else np.int32
        lengths = np.zeros(cap, dtype=len_t)
        lengths[:n] = lens
        return [data, lengths] + varrs, ("str", want,
                                         np.dtype(len_t).name, vmode)

    values = np.where(hc.validity, hc.data,
                      np.zeros(1, hc.dtype.np_dtype)) \
        .astype(hc.dtype.np_dtype, copy=False)
    wire = values
    wire_name = hc.dtype.np_dtype.name
    if hc.dtype.np_dtype == np.float64:
        enc = _encode_float64(values)
        if enc is not None:
            wire, wire_name = enc
    elif hc.dtype.np_dtype.kind == "i":
        narrow = _narrow_int(values, hc.dtype.itemsize)
        if narrow is not None:
            wire = values.astype(narrow)
            wire_name = np.dtype(narrow).name
    if wire.dtype.itemsize > 2:
        # Dictionary beats the typed wire only when codes are narrower
        # than the narrowed values (a 0.00..0.10 f64 discount ships int8).
        d = _try_dict(values, n)
        if d is not None:
            codes, uniques = d
            uniques = list(uniques)
            zero = hc.dtype.np_dtype.type(0)
            zero_code = next((i for i, u in enumerate(uniques)
                              if u == zero and not (
                                  isinstance(u, float)
                                  and np.signbit(u))), None)
            if zero_code is None:
                uniques.append(zero)
                zero_code = len(uniques) - 1
            dict_cap = 8
            while dict_cap < len(uniques):
                dict_cap *= 2
            code_t = np.int8 if dict_cap <= 128 else np.int16
            if np.dtype(code_t).itemsize < wire.dtype.itemsize:
                table = np.zeros(dict_cap, dtype=hc.dtype.np_dtype)
                table[:len(uniques)] = uniques
                codes_arr = np.full(cap, zero_code, dtype=code_t)
                codes_arr[:n] = codes
                return [codes_arr, table] + varrs, \
                    ("dnum", hc.dtype.name, np.dtype(code_t).name,
                     dict_cap, vmode)
    data = np.zeros(cap, dtype=wire.dtype)
    data[:n] = wire
    return [data] + varrs, ("num", hc.dtype.name, wire_name, vmode)


# (capacity, specs) -> jitted widen. Filled from whichever thread
# uploads first (concurrent queries / stage threads under the pipelined
# executor), so insertion is double-checked under a lock — two racing
# uploads must share ONE compiled program.
_DECODE_JIT_CACHE: dict = {}
_DECODE_JIT_LOCK = threading.Lock()


def _unpack_validity(bits: jax.Array, cap: int) -> jax.Array:
    """Inverse of np.packbits(bitorder='little'): (cap/8,) uint8 -> bool."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    opened = (bits[:, None] >> shifts[None, :]) & 1
    return opened.reshape(-1)[:cap].astype(jnp.bool_)


def _decode_fn(cap: int, specs: tuple):
    def decode(arrays, num_rows):
        it = iter(arrays)
        row_mask = None
        cols = []

        def valid_of(vmode):
            nonlocal row_mask
            if vmode == "packed":
                return _unpack_validity(next(it), cap)
            if row_mask is None:
                row_mask = jnp.arange(cap, dtype=jnp.int32) < num_rows
            return row_mask

        for spec in specs:
            if spec[0] == "dnum":
                _, logical_name, _code_name, _dict_cap, vmode = spec
                logical = dt.type_named(logical_name)
                codes = next(it).astype(jnp.int32)
                table = next(it)
                data = jnp.take(table, codes, axis=0, mode="clip")
                cols.append(DeviceColumn(logical, data, valid_of(vmode)))
                continue
            if spec[0] == "dstr":
                _, width, _code_name, _dict_cap, vmode = spec
                codes = next(it).astype(jnp.int32)
                table = next(it)
                len_table = next(it).astype(jnp.int32)
                data = jnp.take(table, codes, axis=0, mode="clip")
                lengths = jnp.take(len_table, codes, axis=0, mode="clip")
                cols.append(DeviceColumn(dt.STRING, data, valid_of(vmode),
                                         lengths))
                continue
            if spec[0] == "str":
                _, width, _len_name, vmode = spec
                data = next(it)
                lengths = next(it).astype(jnp.int32)
                if vmode == "packed":
                    validity = _unpack_validity(next(it), cap)
                else:
                    if row_mask is None:
                        row_mask = jnp.arange(cap, dtype=jnp.int32) \
                            < num_rows
                    validity = row_mask
                cols.append(DeviceColumn(dt.STRING, data, validity,
                                         lengths))
                continue
            _, logical_name, wire_name, vmode = spec
            logical = dt.type_named(logical_name)
            w = next(it)
            if w.dtype == logical.np_dtype:
                data = w
            else:
                data = w.astype(logical.np_dtype)   # pure cast, exact
            if vmode == "packed":
                validity = _unpack_validity(next(it), cap)
            else:
                if row_mask is None:
                    row_mask = jnp.arange(cap, dtype=jnp.int32) < num_rows
                validity = row_mask
            cols.append(DeviceColumn(logical, data, validity))
        return DeviceBatch(tuple(cols), num_rows)
    return decode


def encode_batch(batch, capacity: Optional[int] = None,
                 string_widths: Optional[dict] = None):
    """Host-side half of the upload: analyze + narrow + pad. CPU-only, so
    scan prefetch threads can run it concurrently with device work.
    Returns (arrays, specs, n, cap)."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    n = batch.num_rows
    cap = capacity if capacity is not None else bucket_capacity(n)
    assert cap >= n, f"capacity {cap} < rows {n}"
    arrays: List[np.ndarray] = []
    specs = []
    for name, hc in zip(batch.names, batch.columns):
        arrs, spec = encode_column(hc, name, n, cap, string_widths)
        arrays.extend(arrs)
        specs.append(spec)
    arrays.append(np.asarray(n, np.int32))
    return arrays, tuple(specs), n, cap


def upload_encoded(arrays, specs, n: int, cap: int) -> DeviceBatch:
    """Device-side half: single device_put + jitted on-device widen.
    The largest single allocations in the engine happen here, so the
    dispatch runs under OOM->spill->retry (memory/oom.py)."""
    from spark_rapids_tpu.memory.oom import retry_on_oom

    def put_and_decode():
        # Injection site INSIDE the retried dispatch: an injected OOM
        # here exercises the same escalation ladder a real allocation
        # failure would (tests/test_chaos.py).
        faults.fault_point("upload")
        put = jax.device_put(arrays)
        dev_arrays, num_rows = put[:-1], put[-1]
        key = (cap, specs)
        fn = _DECODE_JIT_CACHE.get(key)
        if fn is None:
            with _DECODE_JIT_LOCK:
                fn = _DECODE_JIT_CACHE.get(key)
                if fn is None:
                    fn = jax.jit(_decode_fn(cap, specs))
                    _DECODE_JIT_CACHE[key] = fn
        return fn(dev_arrays, num_rows)

    out = retry_on_oom(put_and_decode)
    out.rows_hint = n
    return out


def upload(batch, capacity: Optional[int] = None,
           string_widths: Optional[dict] = None) -> DeviceBatch:
    """Encode + single device_put + jitted on-device widen."""
    return upload_encoded(*encode_batch(batch, capacity, string_widths))
