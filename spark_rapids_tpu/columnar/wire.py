"""Wire codec for host->device uploads: narrow dtypes + packed validity.

TPU-first re-design of the reference's GPU parquet decode
(GpuParquetScan.scala:1144 keeps *compressed pages* on the transfer path and
decodes on-device with cuDF). XLA has no byte-oriented snappy kernel, but the
same bandwidth win comes from a typed transform: before upload each column is
analyzed (vectorized numpy, one pass) and, when lossless, re-encoded to a
narrower wire type --

- integers whose [min, max] fits int8/int16/int32 ship narrow;
- float64 columns whose values are whole numbers in int32 range ship as
  ints (decoded by a pure int->f64 cast);
- float64 exactly representable as float32 ships as float32;
- all-valid validity vanishes (reconstructed from the row mask); otherwise
  it ships as packed bits (1/8th);
- string length columns ship int16 when the column width bounds them,
  int32 otherwise.

Only pure dtype CASTS are used on the device side. The TPU's float64 is
double-double emulation whose arithmetic (add/mul/div) is NOT correctly
rounded (measured ~2 ulps off), so any decode that computes — e.g. a
scaled-decimal ``int / 100`` — lands on a different f64 than the host
value and silently breaks bit-exact comparisons downstream (a filter
``x <= 0.07`` dropped every 0.07 row). Casts int<->f64 and f32->f64 are
exact on the emulated backend (verified), so the codec restricts itself
to them.

The device side widens back to the logical dtype inside ONE jitted decode
program per (capacity, spec) -- a few fused casts, so HBM traffic is the
only cost there. The transfer link (PCIe / a tunneled remote device) is the
scarce resource this trades against; reconstruction is bit-exact by
construction, so every engine invariant (zeroed padding, validity masking)
is preserved.

Codec v2 (``spark.rapids.sql.wire.codec``, default ``v2``) extends the
typed transform with three more lossless encodings, chosen per column
from one cheap host stats pass by smallest wire size:

- **RLE** for sorted / low-run-count columns: run values + exclusive run
  end offsets; the device decode is a ``searchsorted`` over the run ends
  plus one gather (float runs are detected on the BIT view, so ``-0.0``
  vs ``0.0`` and distinct NaN payloads never merge).
- **delta** for monotone/smooth integer columns: an int64 base + narrow
  int deltas, decoded by a jitted integer cumsum (two's-complement
  arithmetic is wrap-identical between numpy and XLA, and the encoder
  verifies the round trip before committing).
- **frame-of-reference** for clustered int64/int32 (ids in a dense
  band far from zero): an int64 base + narrow unsigned offsets, decoded
  by one exact integer add.

``v1`` keeps the original dictionary + narrow-int behavior; ``plain``
ships the logical dtypes untransformed (the transport-transparency
baseline the dual-engine parity suite pins).

All of a batch's wire arrays are additionally PACKED into one contiguous
8-byte-aligned staging buffer with a static offset table, so an upload is
ONE ``jax.device_put`` transfer + one jitted unpack-and-decode program --
not one dispatch per buffer. Consecutive tiny batches (below
``spark.rapids.sql.wire.minUploadBytes``) can ride a single transfer via
:func:`upload_packed_group`. The pack half is pure CPU work, so pipeline
prefetch threads stage whole partitions while the device consumes earlier
ones; the ordered consumer only dispatches.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn


# ---------------------------------------------------------------------------
# Integrity framing for serialized batch blobs (spill frames, any future
# inter-process shuffle wire). A 16-byte header: magic | CRC32 | length.
# Deserialize verifies ALL THREE, so a flipped bit / truncated write /
# foreign blob raises WireCorruptionError at the frame boundary instead
# of np.frombuffer silently reinterpreting garbage into wrong rows.
# ---------------------------------------------------------------------------

_FRAME_MAGIC = b"SRTW"
_FRAME_HEADER = struct.Struct("<4sIQ")      # magic, crc32, payload length


class WireCorruptionError(ValueError):
    """A serialized frame failed its integrity check at deserialize."""


def frame_blob(blob: bytes) -> bytes:
    """Wrap ``blob`` in the checksummed wire frame."""
    return _FRAME_HEADER.pack(_FRAME_MAGIC, zlib.crc32(blob) & 0xFFFFFFFF,
                              len(blob)) + blob


def unframe_blob(framed: bytes) -> bytes:
    """Verify + strip the wire frame; raises :class:`WireCorruptionError`
    on any mismatch (magic, length, or CRC32)."""
    if len(framed) < _FRAME_HEADER.size:
        raise WireCorruptionError(
            f"frame truncated: {len(framed)} bytes < header")
    magic, crc, length = _FRAME_HEADER.unpack_from(framed)
    if magic != _FRAME_MAGIC:
        raise WireCorruptionError(f"bad frame magic {magic!r}")
    payload = framed[_FRAME_HEADER.size:]
    if len(payload) != length:
        raise WireCorruptionError(
            f"frame length mismatch: header says {length}, "
            f"payload is {len(payload)}")
    actual = zlib.crc32(payload) & 0xFFFFFFFF
    if actual != crc:
        raise WireCorruptionError(
            f"frame CRC32 mismatch: header {crc:#010x}, "
            f"payload {actual:#010x}")
    return payload

# ---------------------------------------------------------------------------
# Codec mode (spark.rapids.sql.wire.codec / SRT_WIRE_CODEC): process-global,
# like the kernel cache — concurrent sessions with conflicting explicit
# settings race to last-write (documented; the CI matrix uses the env).
# ---------------------------------------------------------------------------

CODEC_MODES = ("plain", "v1", "v2")
_CODEC_OVERRIDE: Optional[str] = None


def codec_mode() -> str:
    if _CODEC_OVERRIDE is not None:
        return _CODEC_OVERRIDE
    env = os.environ.get("SRT_WIRE_CODEC", "").strip().lower()
    return env if env in CODEC_MODES else "v2"


def maybe_configure(conf) -> None:
    """Adopt an explicitly-set ``spark.rapids.sql.wire.codec`` for the
    process (unset clears any prior override back to env/default)."""
    global _CODEC_OVERRIDE
    from spark_rapids_tpu import config as C
    raw = conf.raw.get(C.WIRE_CODEC.key)
    if raw is None:
        _CODEC_OVERRIDE = None
        return
    mode = str(raw).strip().lower()
    if mode not in CODEC_MODES:
        raise ValueError(f"unknown wire codec {raw!r}; "
                         f"expected one of {CODEC_MODES}")
    _CODEC_OVERRIDE = mode


# Process-global transport counters (bench.py's ``wire`` JSON block):
# rawBytes = decoded device footprint the plain codec would have shipped,
# encodedBytes = wire arrays actually produced, stagingBytes = packed
# staging buffers built, uploadTransfers vs uploadedBatches = how many
# device_put calls served how many batches (grouping wins show as
# transfers < batches), codecCols.<kind> = per-codec column counts.
_WIRE_LOCK = threading.Lock()
_WIRE_COUNTERS: Dict[str, float] = {}


def _wrecord(name: str, amount: float = 1) -> None:
    with _WIRE_LOCK:
        _WIRE_COUNTERS[name] = _WIRE_COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    with _WIRE_LOCK:
        out = dict(_WIRE_COUNTERS)
    raw = out.get("rawBytes", 0)
    if raw > 0:
        out["wireCompressionRatio"] = round(
            raw / max(out.get("encodedBytes", raw), 1), 4)
    batches = out.get("uploadedBatches", 0)
    if batches > 0:
        # Fraction of batches that shared a staging transfer with a
        # neighbor (0 = every batch paid its own device_put).
        out["stagingHitRate"] = round(
            1.0 - out.get("uploadTransfers", batches) / batches, 4)
    return out


def reset_counters() -> None:
    with _WIRE_LOCK:
        _WIRE_COUNTERS.clear()


# Column wire spec (static, hashable -- part of the decode jit cache key):
#   numeric: ("num", logical_name, wire_np_name, vmode)
#   string:  ("str", width, lengths_np_name, vmode)
#   dict num: ("dnum", logical_name, code_np_name, dict_cap, vmode)
#   dict str: ("dstr", width, code_np_name, dict_cap, vmode)
#   RLE:      ("rle", logical_name, value_np_name, run_cap, vmode)
#   delta:    ("delta", logical_name, delta_np_name, vmode)
#   frame-of-reference: ("for", logical_name, offset_np_name, vmode)
# vmode: "all" (validity == row mask) | "packed" (bit-packed uint8).
#
# Dictionary encoding is the LZ4-of-this-wire (NvcompLZ4CompressionCodec
# analog): XLA cannot run a byte-serial decompressor, but a gather from a
# small value table is one exact fused kernel — and TPC-shaped data is
# full of low-cardinality columns (flags, modes, quantities, discounts)
# where an 8-byte float or an 8..32-byte string row ships as a 1-2 byte
# code. Exactness: the gathered values ARE the host bit patterns (no
# arithmetic), so emulated-f64 rounding never enters.

_DICT_MAX = 4096            # value-table entries worth a table gather
_DICT_SAMPLE = 1 << 16


def _try_dict(values: np.ndarray, n: int):
    """(codes, uniques) via pandas factorize when cardinality is low
    enough to pay off, else None. Codes are -1-free (values prefiltered
    for NaN; nulls were zeroed upstream)."""
    if n == 0:
        return None
    if values.dtype.kind == "f":
        v = values[:n]
        # factorize hashes -0.0 == 0.0, which would drop the sign bit.
        if not np.isfinite(v).all() or np.any((v == 0) & np.signbit(v)):
            return None
    sample = values[:min(n, _DICT_SAMPLE)]
    if len(np.unique(sample)) > _DICT_MAX // 4:
        return None
    import pandas as pd
    codes, uniques = pd.factorize(values[:n], sort=False)
    if len(uniques) > _DICT_MAX:
        return None
    return codes, uniques

_INT_CANDIDATES = (
    (np.int8, -128, 127),
    (np.int16, -32768, 32767),
    (np.int32, -(2 ** 31), 2 ** 31 - 1),
)

def _narrow_int(values: np.ndarray, itemsize: int):
    """Smallest int dtype whose range covers values (None = keep)."""
    if values.size == 0:
        return np.int8
    mn = values.min()
    mx = values.max()
    for cand, lo, hi in _INT_CANDIDATES:
        if np.dtype(cand).itemsize >= itemsize:
            return None
        if lo <= mn and mx <= hi:
            return cand
    return None


def _encode_float64(values: np.ndarray):
    """Returns (wire_array, wire_np_name) or None. Lossless only, and the
    device decode must be a pure CAST (emulated-f64 arithmetic is not
    correctly rounded — see module docstring): whole numbers in int32
    range ship as narrow ints; exactly-f32-representable ships as f32.
    NaN/inf/-0.0 disqualify the int path (-0.0 would become +0.0)."""
    with np.errstate(invalid="ignore"):
        finite = np.isfinite(values).all() if values.size else True
    if finite and not (values.size
                       and np.any((values == 0) & np.signbit(values))):
        r = np.rint(values)
        if not np.any(np.abs(r) > 2 ** 31 - 1) \
                and np.array_equal(r, values):
            narrow = _narrow_int(r, 8) or np.int32
            return r.astype(narrow), np.dtype(narrow).name
    with np.errstate(over="ignore"):
        f32 = values.astype(np.float32)
    if np.array_equal(f32.astype(np.float64), values):
        return f32, "float32"
    return None


# -- codec v2 candidates ------------------------------------------------------
# Each _try_* returns (wire_arrays, spec_tail, wire_bytes) or None. They
# compete on wire_bytes against the typed/dict encodings; the decode for
# every one of them is gathers + exact integer arithmetic only, never
# emulated-f64 math (see module docstring).

def _bit_view(v: np.ndarray) -> np.ndarray:
    """Float values as their bit patterns (run/equality detection must
    distinguish -0.0 from 0.0 and NaN payloads; int passthrough)."""
    if v.dtype.kind == "f":
        return v.view(np.int32 if v.dtype.itemsize == 4 else np.int64)
    return v


def _try_rle(wire: np.ndarray, n: int, cap: int):
    """Run-length encoding over the (already narrowed) wire values:
    run values + ascending exclusive run-end offsets. Decode is
    searchsorted(run_ends, row) + one table gather — bit patterns move
    untouched. Worth it only when runs are rare (sorted or clustered
    columns)."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    if n < 8:
        return None
    v = wire[:n]
    bits = _bit_view(v)
    starts = np.empty(n, np.bool_)
    starts[0] = True
    np.not_equal(bits[1:], bits[:-1], out=starts[1:])
    runs = int(starts.sum())
    if runs > n // 4:
        return None
    run_cap = bucket_capacity(max(runs, 1))
    sidx = np.flatnonzero(starts)
    run_vals = np.zeros(run_cap, v.dtype)
    run_vals[:runs] = v[sidx]
    # Exclusive end of run i; padding entries sit at cap so padding rows
    # index past the real runs into zeroed table slots.
    ends = np.full(run_cap, cap, np.int32)
    if runs > 1:
        ends[:runs - 1] = sidx[1:]
    ends[runs - 1] = n
    nbytes = run_cap * (v.dtype.itemsize + 4)
    return [run_vals, ends], (v.dtype.name, run_cap), nbytes


_DELTA_CANDIDATES = (np.int8, np.int16, np.int32)


def _smallest_int(lo: int, hi: int, max_itemsize: int):
    """Smallest signed int dtype strictly narrower than ``max_itemsize``
    covering [lo, hi], or None."""
    for cand, clo, chi in _INT_CANDIDATES:
        if np.dtype(cand).itemsize >= max_itemsize:
            return None
        if clo <= lo and hi <= chi:
            return cand
    return None


def _try_delta(wire: np.ndarray, n: int, cap: int):
    """Delta encoding for monotone/smooth integer columns: int64 base +
    narrow int deltas, decoded by a jitted int64 cumsum. Two's-complement
    wrap is identical between numpy and XLA, and the encoder verifies the
    reconstruction before committing, so the decode is exact by
    construction."""
    if n < 8 or wire.dtype.kind != "i" or wire.dtype.itemsize < 4:
        return None
    v64 = wire[:n].astype(np.int64)
    d = np.diff(v64)
    if d.size == 0:
        return None
    narrow = _smallest_int(int(d.min()), int(d.max()), wire.dtype.itemsize)
    if narrow is None:
        return None
    # Round-trip proof (covers any int64 diff wraparound): base +
    # cumsum(deltas) must reproduce the values bit-for-bit.
    if not np.array_equal(
            v64[0] + np.concatenate([np.zeros(1, np.int64),
                                     d]).cumsum(dtype=np.int64), v64):
        return None
    deltas = np.zeros(cap, narrow)
    deltas[1:n] = d.astype(narrow)
    base = np.asarray([v64[0]], np.int64)
    nbytes = 8 + cap * np.dtype(narrow).itemsize
    return [base, deltas], (np.dtype(narrow).name,), nbytes


_FOR_CANDIDATES = ((np.uint8, 0xFF), (np.uint16, 0xFFFF),
                   (np.uint32, 0xFFFFFFFF))


def _try_for(wire: np.ndarray, n: int, cap: int):
    """Frame-of-reference narrowing for clustered integers far from zero
    (dense id bands): int64 base = min + narrow unsigned offsets, decoded
    by one exact integer add."""
    if n == 0 or wire.dtype.kind != "i" or wire.dtype.itemsize < 4:
        return None
    v = wire[:n]
    vmin, vmax = int(v.min()), int(v.max())
    span = vmax - vmin
    narrow = None
    for cand, hi in _FOR_CANDIDATES:
        if np.dtype(cand).itemsize >= wire.dtype.itemsize:
            break
        if span <= hi:
            narrow = cand
            break
    if narrow is None:
        return None
    offsets = np.zeros(cap, narrow)
    offsets[:n] = (v - vmin).astype(narrow)
    base = np.asarray([vmin], np.int64)
    nbytes = 8 + cap * np.dtype(narrow).itemsize
    return [base, offsets], (np.dtype(narrow).name,), nbytes


def encode_column(hc, name: str, n: int, cap: int,
                  string_widths: Optional[dict]) -> Tuple[List[np.ndarray],
                                                          tuple]:
    """Host-side encode of one column -> (wire arrays, static spec),
    under the active codec mode. Counters record the decoded (raw)
    footprint vs the wire bytes and the chosen codec kind."""
    arrs, spec = _encode_column_impl(hc, name, n, cap, string_widths,
                                     codec_mode())
    raw = cap * (hc.dtype.itemsize + 1)
    if hc.dtype.is_string:
        raw = cap * (spec[1] + 4 + 1)      # matrix + lengths + validity
    _wrecord("rawBytes", raw)
    _wrecord("encodedBytes", sum(a.nbytes for a in arrs))
    _wrecord(f"codecCols.{spec[0]}")
    return arrs, spec


def _encode_column_impl(hc, name: str, n: int, cap: int,
                        string_widths: Optional[dict], mode: str
                        ) -> Tuple[List[np.ndarray], tuple]:
    from spark_rapids_tpu.columnar.host import strings_to_matrix
    validity = np.zeros(cap, dtype=np.bool_)
    validity[:n] = hc.validity
    all_valid = bool(validity[:n].all())
    if all_valid:
        vmode, varrs = "all", []
    else:
        vmode = "packed"
        varrs = [np.packbits(validity, bitorder="little")]

    if hc.dtype.is_string:
        # Dictionary path first: a low-cardinality string column (flags,
        # modes, segments) ships 1-2 byte codes + a tiny value table
        # instead of a (rows x width) byte matrix. All probing runs on
        # the dense byte MATRIX (never the lazy per-row object array):
        # rows keyed as (big-endian length | content bytes) void scalars,
        # compared bytewise by np.unique — fully vectorized.
        m0, lens0 = strings_to_matrix(hc)
        lens0 = np.where(hc.validity, lens0, 0).astype(np.int32)
        mw = m0.shape[1]
        d = None
        if n and mode != "plain":
            keyed = np.zeros((n, mw + 4), np.uint8)
            keyed[:, :4] = lens0.astype(">i4").view(np.uint8) \
                .reshape(n, 4)
            if mw:
                keyed[:, 4:] = np.where(hc.validity[:, None],
                                        m0[:n], 0)
            key = np.ascontiguousarray(keyed).view(
                [("k", f"V{mw + 4}")]).ravel()
            if len(np.unique(key[:_DICT_SAMPLE])) <= _DICT_MAX // 4:
                uniq, first_idx, codes = np.unique(
                    key, return_index=True, return_inverse=True)
                if len(uniq) <= _DICT_MAX:
                    d = (codes, first_idx)
        if d is not None:
            codes, first_idx = d
            k = len(first_idx)
            ulens = lens0[first_idx]
            want = dt.string_width_bucket(int(ulens.max()) if k else 0)
            if string_widths and name in string_widths:
                want = max(want, string_widths[name])
            # The all-zero key (empty/invalid rows) is the code padding
            # rows take; add one if the column had no empty strings.
            zeros = np.flatnonzero(ulens == 0)
            dict_rows = list(first_idx)
            if zeros.size:
                zero_code = int(zeros[0])
            else:
                dict_rows.append(None)
                zero_code = k
                k += 1
            dict_cap = 8
            while dict_cap < k:
                dict_cap *= 2
            table = np.zeros((dict_cap, want), dtype=np.uint8)
            len_t = np.int16 if want <= 32767 else np.int32
            len_table = np.zeros(dict_cap, dtype=len_t)
            w = min(want, mw)
            for i, ri in enumerate(dict_rows):
                if ri is None:
                    continue
                if w:
                    table[i, :w] = np.where(hc.validity[ri],
                                            m0[ri, :w], 0)
                len_table[i] = min(int(ulens[i]) if i < len(ulens)
                                   else 0, want)
            code_t = np.int8 if dict_cap <= 128 else np.int16
            codes_arr = np.full(cap, zero_code, dtype=code_t)
            codes_arr[:n] = codes
            return [codes_arr, table, len_table] + varrs, \
                ("dstr", want, np.dtype(code_t).name, dict_cap, vmode)
        m, lens = m0, lens0
        lens = np.where(hc.validity, lens, 0)
        want = dt.string_width_bucket(int(lens.max()) if n else 0)
        if string_widths and name in string_widths:
            want = max(want, string_widths[name])
        data = np.zeros((cap, want), dtype=np.uint8)
        w = min(want, m.shape[1])
        data[:n, :w] = np.where(hc.validity[:, None], m, 0)[:, :w]
        # Lengths are bounded by the column width: int16 only when the
        # width itself fits (a >32767-byte string would otherwise wrap).
        len_t = np.int16 if want <= 32767 else np.int32
        lengths = np.zeros(cap, dtype=len_t)
        lengths[:n] = lens
        return [data, lengths] + varrs, ("str", want,
                                         np.dtype(len_t).name, vmode)

    values = np.where(hc.validity, hc.data,
                      np.zeros(1, hc.dtype.np_dtype)) \
        .astype(hc.dtype.np_dtype, copy=False)
    wire = values
    wire_name = hc.dtype.np_dtype.name
    if mode != "plain":
        if hc.dtype.np_dtype == np.float64:
            enc = _encode_float64(values)
            if enc is not None:
                wire, wire_name = enc
        elif hc.dtype.np_dtype.kind == "i":
            narrow = _narrow_int(values, hc.dtype.itemsize)
            if narrow is not None:
                wire = values.astype(narrow)
                wire_name = np.dtype(narrow).name
    # v2: RLE / frame-of-reference / delta compete with the typed wire
    # (and the dictionary below) on wire bytes. All are gathers + exact
    # int arithmetic on the device side.
    best = None                     # (arrays, spec) of the leader
    best_bytes = cap * wire.dtype.itemsize
    if mode == "v2":
        r = _try_rle(wire, n, cap)
        if r is not None and r[2] < best_bytes:
            arrs, (val_name, run_cap), best_bytes = r
            best = (arrs, ("rle", hc.dtype.name, val_name, run_cap, vmode))
        f = _try_for(wire, n, cap)
        if f is not None and f[2] < best_bytes:
            arrs, (off_name,), best_bytes = f
            best = (arrs, ("for", hc.dtype.name, off_name, vmode))
        dl = _try_delta(wire, n, cap)
        if dl is not None and dl[2] < best_bytes:
            arrs, (d_name,), best_bytes = dl
            best = (arrs, ("delta", hc.dtype.name, d_name, vmode))
    if mode != "plain" and wire.dtype.itemsize > 2:
        # Dictionary beats the typed wire only when codes are narrower
        # than the narrowed values (a 0.00..0.10 f64 discount ships int8).
        d = _try_dict(values, n)
        if d is not None:
            codes, uniques = d
            uniques = list(uniques)
            zero = hc.dtype.np_dtype.type(0)
            zero_code = next((i for i, u in enumerate(uniques)
                              if u == zero and not (
                                  isinstance(u, float)
                                  and np.signbit(u))), None)
            if zero_code is None:
                uniques.append(zero)
                zero_code = len(uniques) - 1
            dict_cap = 8
            while dict_cap < len(uniques):
                dict_cap *= 2
            code_t = np.int8 if dict_cap <= 128 else np.int16
            dict_bytes = cap * np.dtype(code_t).itemsize \
                + dict_cap * hc.dtype.itemsize
            ok = np.dtype(code_t).itemsize < wire.dtype.itemsize \
                if mode == "v1" else dict_bytes < best_bytes
            if ok:
                table = np.zeros(dict_cap, dtype=hc.dtype.np_dtype)
                table[:len(uniques)] = uniques
                codes_arr = np.full(cap, zero_code, dtype=code_t)
                codes_arr[:n] = codes
                return [codes_arr, table] + varrs, \
                    ("dnum", hc.dtype.name, np.dtype(code_t).name,
                     dict_cap, vmode)
    if best is not None:
        return best[0] + varrs, best[1]
    data = np.zeros(cap, dtype=wire.dtype)
    data[:n] = wire
    return [data] + varrs, ("num", hc.dtype.name, wire_name, vmode)


# (capacity, specs) -> jitted widen. Filled from whichever thread
# uploads first (concurrent queries / stage threads under the pipelined
# executor), so insertion is double-checked under a lock — two racing
# uploads must share ONE compiled program.
_DECODE_JIT_CACHE: dict = {}
_DECODE_JIT_LOCK = threading.Lock()


def _unpack_validity(bits: jax.Array, cap: int) -> jax.Array:
    """Inverse of np.packbits(bitorder='little'): (cap/8,) uint8 -> bool."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    opened = (bits[:, None] >> shifts[None, :]) & 1
    return opened.reshape(-1)[:cap].astype(jnp.bool_)


def _decode_fn(cap: int, specs: tuple):
    def decode(arrays, num_rows):
        it = iter(arrays)
        row_mask = None
        cols = []

        def valid_of(vmode):
            nonlocal row_mask
            if vmode == "packed":
                return _unpack_validity(next(it), cap)
            if row_mask is None:
                row_mask = jnp.arange(cap, dtype=jnp.int32) < num_rows
            return row_mask

        for spec in specs:
            if spec[0] == "dnum":
                _, logical_name, _code_name, _dict_cap, vmode = spec
                logical = dt.type_named(logical_name)
                codes = next(it).astype(jnp.int32)
                table = next(it)
                data = jnp.take(table, codes, axis=0, mode="clip")
                cols.append(DeviceColumn(logical, data, valid_of(vmode)))
                continue
            if spec[0] == "dstr":
                _, width, _code_name, _dict_cap, vmode = spec
                codes = next(it).astype(jnp.int32)
                table = next(it)
                len_table = next(it).astype(jnp.int32)
                data = jnp.take(table, codes, axis=0, mode="clip")
                lengths = jnp.take(len_table, codes, axis=0, mode="clip")
                cols.append(DeviceColumn(dt.STRING, data, valid_of(vmode),
                                         lengths))
                continue
            if spec[0] == "rle":
                _, logical_name, _val_name, run_cap, vmode = spec
                logical = dt.type_named(logical_name)
                run_vals = next(it)
                run_ends = next(it)
                from spark_rapids_tpu.ops import native
                if native.kernel_enabled("rleDecode") and \
                        run_cap <= native.rle_max_runs():
                    # Native Pallas interval-membership select over the
                    # run table (ops/native.py): bit planes only, so the
                    # expansion is exact — then the same pure cast.
                    data = native.rle_decode(run_vals, run_ends, cap,
                                             num_rows)
                    if data.dtype != logical.np_dtype:
                        data = data.astype(logical.np_dtype)
                else:
                    rows = jnp.arange(cap, dtype=jnp.int32)
                    ridx = jnp.searchsorted(run_ends, rows,
                                            side="right").astype(jnp.int32)
                    data = jnp.take(run_vals, ridx, axis=0, mode="clip")
                    if data.dtype != logical.np_dtype:
                        data = data.astype(logical.np_dtype)  # pure cast
                    # Zero padding rows (a full run table has no zero
                    # slot).
                    rows_ = jnp.arange(cap, dtype=jnp.int32)
                    data = jnp.where(rows_ < num_rows, data,
                                     jnp.zeros_like(data))
                cols.append(DeviceColumn(logical, data, valid_of(vmode)))
                continue
            if spec[0] in ("delta", "for"):
                kind, logical_name, _nname, vmode = spec
                logical = dt.type_named(logical_name)
                base = next(it)            # (1,) int64
                packed_vals = next(it)
                rows = jnp.arange(cap, dtype=jnp.int32)
                off = packed_vals.astype(jnp.int64)
                if kind == "delta":
                    off = jnp.cumsum(off)  # exact int64 (wrap-identical)
                vals = base[0] + off
                vals = jnp.where(rows < num_rows, vals, jnp.int64(0))
                data = vals.astype(logical.np_dtype)       # exact narrow
                cols.append(DeviceColumn(logical, data, valid_of(vmode)))
                continue
            if spec[0] == "str":
                _, width, _len_name, vmode = spec
                data = next(it)
                lengths = next(it).astype(jnp.int32)
                if vmode == "packed":
                    validity = _unpack_validity(next(it), cap)
                else:
                    if row_mask is None:
                        row_mask = jnp.arange(cap, dtype=jnp.int32) \
                            < num_rows
                    validity = row_mask
                cols.append(DeviceColumn(dt.STRING, data, validity,
                                         lengths))
                continue
            _, logical_name, wire_name, vmode = spec
            logical = dt.type_named(logical_name)
            w = next(it)
            if w.dtype == logical.np_dtype:
                data = w
            else:
                data = w.astype(logical.np_dtype)   # pure cast, exact
            if vmode == "packed":
                validity = _unpack_validity(next(it), cap)
            else:
                if row_mask is None:
                    row_mask = jnp.arange(cap, dtype=jnp.int32) < num_rows
                validity = row_mask
            cols.append(DeviceColumn(logical, data, validity))
        return DeviceBatch(tuple(cols), num_rows)
    return decode


def encode_batch(batch, capacity: Optional[int] = None,
                 string_widths: Optional[dict] = None):
    """Host-side half of the upload: analyze + narrow + pad. CPU-only, so
    scan prefetch threads can run it concurrently with device work.
    Returns (arrays, specs, n, cap)."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    n = batch.num_rows
    cap = capacity if capacity is not None else bucket_capacity(n)
    assert cap >= n, f"capacity {cap} < rows {n}"
    arrays: List[np.ndarray] = []
    specs = []
    for name, hc in zip(batch.names, batch.columns):
        arrs, spec = encode_column(hc, name, n, cap, string_widths)
        arrays.extend(arrs)
        specs.append(spec)
    arrays.append(np.asarray(n, np.int32))
    return arrays, tuple(specs), n, cap


# ---------------------------------------------------------------------------
# Staging buffer: all of a batch's wire arrays packed into ONE contiguous
# uint8 buffer with a static, 8-byte-aligned offset table derived purely
# from (capacity, specs) — so a batch upload is a single device_put
# transfer and the unpack (static slices + bitcasts) fuses into the same
# jitted decode program. The pack half is pure CPU (prefetch threads).
# ---------------------------------------------------------------------------

def _align8(off: int) -> int:
    return (off + 7) & ~7


def _column_layout(spec, cap: int) -> List[Tuple[str, Tuple[int, ...]]]:
    """(np dtype name, shape) of every wire array ``spec`` produces, in
    encode order. MUST mirror encode_column exactly — pack_encoded
    asserts each array against this derivation."""
    kind = spec[0]
    if kind == "num":
        _, _logical, wire_name, vmode = spec
        arrs = [(wire_name, (cap,))]
    elif kind == "dnum":
        _, logical, code_name, dict_cap, vmode = spec
        arrs = [(code_name, (cap,)),
                (dt.type_named(logical).np_dtype.name, (dict_cap,))]
    elif kind == "rle":
        _, _logical, val_name, run_cap, vmode = spec
        arrs = [(val_name, (run_cap,)), ("int32", (run_cap,))]
    elif kind in ("delta", "for"):
        _, _logical, nname, vmode = spec
        arrs = [("int64", (1,)), (nname, (cap,))]
    elif kind == "str":
        _, width, len_name, vmode = spec
        arrs = [("uint8", (cap, width)), (len_name, (cap,))]
    elif kind == "dstr":
        _, width, code_name, dict_cap, vmode = spec
        len_name = "int16" if width <= 32767 else "int32"
        arrs = [(code_name, (cap,)), ("uint8", (dict_cap, width)),
                (len_name, (dict_cap,))]
    else:                               # pragma: no cover - spec typo
        raise AssertionError(f"unknown wire spec kind {kind!r}")
    if vmode == "packed":
        arrs.append(("uint8", ((cap + 7) // 8,)))
    return arrs


def _batch_layout(cap: int, specs: tuple):
    """[(offset, np name, shape, nbytes)] for every wire array plus the
    trailing num_rows scalar, with every offset 8-byte aligned, and the
    aligned total staging size."""
    entries = []
    for spec in specs:
        entries.extend(_column_layout(spec, cap))
    entries.append(("int32", ()))          # num_rows scalar
    out = []
    off = 0
    for name, shape in entries:
        count = 1
        for s in shape:
            count *= s
        nbytes = int(np.dtype(name).itemsize * count)
        out.append((off, name, shape, nbytes))
        off = _align8(off + nbytes)
    return out, off


@dataclasses.dataclass
class EncodedBatch:
    """A batch's wire image, packed and ready for one device_put."""

    staging: np.ndarray         # (total,) uint8, offsets 8-byte aligned
    specs: tuple
    n: int
    cap: int

    @property
    def nbytes(self) -> int:
        return self.staging.nbytes


def pack_encoded(arrays, specs, n: int, cap: int) -> EncodedBatch:
    """Pack a batch's wire arrays into one aligned staging buffer. The
    capacity/spec validation happens HERE, once per batch — the upload
    side only dispatches (the per-column re-checks used to run at
    device_put time on the consumer thread)."""
    entries, total = _batch_layout(cap, specs)
    assert len(arrays) == len(entries), \
        f"wire layout mismatch: {len(arrays)} arrays vs " \
        f"{len(entries)} layout entries for specs {specs!r}"
    buf = np.zeros(total, np.uint8)
    for a, (off, name, shape, nbytes) in zip(arrays, entries):
        a = np.asarray(a)               # tobytes() emits C order below
        adt = "bool" if name == "bool" else name
        assert a.dtype == np.dtype(adt) and a.shape == tuple(shape), \
            f"wire array {a.dtype}{a.shape} != layout {name}{shape}"
        # 8-byte alignment is load-bearing: a misaligned view silently
        # forces a copy on the device side instead of a bitcast.
        assert off % 8 == 0, f"staging offset {off} not 8-byte aligned"
        if nbytes:
            buf[off:off + nbytes] = np.frombuffer(a.tobytes(), np.uint8)
    _wrecord("stagingBytes", total)
    _wrecord("stagingBuffers")
    return EncodedBatch(buf, tuple(specs), n, cap)


def pack_batch(batch, capacity: Optional[int] = None,
               string_widths: Optional[dict] = None) -> EncodedBatch:
    """encode + pack: the complete host half of an upload (what pipeline
    prefetch threads stage ahead of the ordered consumer)."""
    from spark_rapids_tpu import monitoring
    with monitoring.span("wire-pack", "host-prefetch",
                         level=monitoring.LEVEL_KERNEL):
        return pack_encoded(*encode_batch(batch, capacity, string_widths))


def _unpack_array(staged, off: int, name: str, shape, nbytes: int):
    seg = jax.lax.slice(staged, (off,), (off + nbytes,)) if nbytes \
        else staged[:0]
    d = np.dtype(np.bool_) if name == "bool" else np.dtype(name)
    if name == "bool":
        return seg.reshape(shape) != 0
    if name == "uint8":
        return seg.reshape(shape)
    if d.itemsize == 1:
        return jax.lax.bitcast_convert_type(seg, d).reshape(shape)
    return jax.lax.bitcast_convert_type(
        seg.reshape(tuple(shape) + (d.itemsize,)), d)


def _packed_fn(cap: int, specs: tuple):
    """One jitted program: unpack the staging buffer (static slices +
    bitcasts — bit-exact by definition) and widen to the logical
    layout."""
    entries, _total = _batch_layout(cap, specs)
    decode = _decode_fn(cap, specs)

    def run(staged):
        arrays = [_unpack_array(staged, off, name, shape, nbytes)
                  for off, name, shape, nbytes in entries]
        return decode(arrays[:-1], arrays[-1])
    return run


def _packed_jit(cap: int, specs: tuple):
    # The native fingerprint keys the cache like the kernel cache does:
    # toggling a native gate must never serve a decode traced under the
    # other setting (the RLE branch dispatches differently).
    from spark_rapids_tpu.ops import native
    key = ("packed", cap, specs, native.fingerprint())
    fn = _DECODE_JIT_CACHE.get(key)
    if fn is None:
        with _DECODE_JIT_LOCK:
            fn = _DECODE_JIT_CACHE.get(key)
            if fn is None:
                fn = jax.jit(_packed_fn(cap, specs))
                _DECODE_JIT_CACHE[key] = fn
    return fn


def upload_packed(enc: EncodedBatch) -> DeviceBatch:
    """Device half: ONE device_put of the staging buffer + one jitted
    unpack-and-decode dispatch. The largest single allocations in the
    engine happen here, so the dispatch runs under OOM->spill->retry
    (memory/oom.py)."""
    from spark_rapids_tpu.memory.oom import retry_on_oom

    def put_and_decode():
        # Injection site INSIDE the retried dispatch: an injected OOM
        # here exercises the same escalation ladder a real allocation
        # failure would (tests/test_chaos.py).
        faults.fault_point("upload")
        staged = jax.device_put(enc.staging)
        return _packed_jit(enc.cap, enc.specs)(staged)

    from spark_rapids_tpu import monitoring
    with monitoring.span("upload", "upload",
                         args={"bytes": int(enc.nbytes), "rows": enc.n}):
        out = retry_on_oom(put_and_decode)
    out.rows_hint = enc.n
    _wrecord("uploadTransfers")
    _wrecord("uploadedBatches")
    return out


def upload_packed_group(encs: Sequence[EncodedBatch]) -> List[DeviceBatch]:
    """Upload SEVERAL packed batches in one device_put transfer (the
    tiny-batch coalescing path, wire.minUploadBytes): staging buffers
    concatenate (each already 8-aligned), cross the link once, and each
    member decodes off its on-device slice — same bytes, same decode
    program, bit-identical to per-batch uploads."""
    from spark_rapids_tpu.memory.oom import retry_on_oom
    encs = list(encs)
    if not encs:
        return []
    if len(encs) == 1:
        return [upload_packed(encs[0])]
    combined = np.concatenate([e.staging for e in encs])

    def put_all():
        faults.fault_point("upload")
        return jax.device_put(combined)

    from spark_rapids_tpu import monitoring
    with monitoring.span("upload-group", "upload",
                         args={"bytes": int(combined.nbytes),
                               "batches": len(encs)}):
        staged_all = retry_on_oom(put_all)
    _wrecord("uploadTransfers")
    _wrecord("uploadedBatches", len(encs))
    _wrecord("groupedUploads")
    outs: List[DeviceBatch] = []
    off = 0
    for enc in encs:
        seg = jax.lax.slice(staged_all, (off,), (off + enc.nbytes,))
        out = retry_on_oom(_packed_jit(enc.cap, enc.specs), seg)
        out.rows_hint = enc.n
        outs.append(out)
        off += enc.nbytes
    return outs


def plan_upload_groups(sizes: Sequence[int],
                       min_bytes: int) -> List[List[int]]:
    """Group consecutive upload indices so members below ``min_bytes``
    share a transfer: tiny batches accumulate until the group reaches the
    threshold; a batch at/above it always ships alone. Deterministic —
    depends only on the sizes, never on prefetch timing."""
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, s in enumerate(sizes):
        if s >= min_bytes:
            if cur:
                groups.append(cur)
                cur, cur_bytes = [], 0
            groups.append([i])
            continue
        cur.append(i)
        cur_bytes += s
        if cur_bytes >= min_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        groups.append(cur)
    return groups


def upload_encoded(arrays, specs, n: int, cap: int) -> DeviceBatch:
    """Back-compat device half over unpacked wire arrays: pack + single
    transfer. Accepts an :class:`EncodedBatch` in the first position
    too (already-packed prefetch payloads)."""
    if isinstance(arrays, EncodedBatch):
        return upload_packed(arrays)
    return upload_packed(pack_encoded(arrays, specs, n, cap))


def upload(batch, capacity: Optional[int] = None,
           string_widths: Optional[dict] = None) -> DeviceBatch:
    """Encode + pack + single device_put + jitted on-device widen."""
    return upload_packed(pack_batch(batch, capacity, string_widths))
