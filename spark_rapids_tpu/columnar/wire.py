"""Wire codec for host->device uploads: narrow dtypes + packed validity.

TPU-first re-design of the reference's GPU parquet decode
(GpuParquetScan.scala:1144 keeps *compressed pages* on the transfer path and
decodes on-device with cuDF). XLA has no byte-oriented snappy kernel, but the
same bandwidth win comes from a typed transform: before upload each column is
analyzed (vectorized numpy, one pass) and, when lossless, re-encoded to a
narrower wire type --

- integers whose [min, max] fits int8/int16/int32 ship narrow;
- float64 columns that are exactly a scaled decimal (prices with 2dp, whole
  quantities: ``rint(v * scale) / scale == v`` bitwise) ship as scaled ints;
- float64 exactly representable as float32 ships as float32;
- all-valid validity vanishes (reconstructed from the row mask); otherwise
  it ships as packed bits (1/8th);
- string length columns ship int16 (width <= 32k by construction).

The device side widens back to the logical dtype inside ONE jitted decode
program per (capacity, spec) -- a few fused casts, so HBM traffic is the
only cost there. The transfer link (PCIe / a tunneled remote device) is the
scarce resource this trades against; reconstruction is bit-exact by
construction, so every engine invariant (zeroed padding, validity masking)
is preserved.

All buffers of a batch go up in a single ``jax.device_put`` call so the
transfers pipeline instead of paying one round trip per buffer.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn

# Column wire spec (static, hashable -- part of the decode jit cache key):
#   numeric: ("num", logical_name, wire_np_name, scale, vmode)
#   string:  ("str", width, vmode)
# vmode: "all" (validity == row mask) | "packed" (bit-packed uint8).

_INT_CANDIDATES = (
    (np.int8, -128, 127),
    (np.int16, -32768, 32767),
    (np.int32, -(2 ** 31), 2 ** 31 - 1),
)

# Decimal scales tried for exact float64 re-encoding, cheapest-win first:
# whole numbers, then money (2dp), then 1dp.
_FLOAT_SCALES = (1, 100, 10)


def _narrow_int(values: np.ndarray, itemsize: int):
    """Smallest int dtype whose range covers values (None = keep)."""
    if values.size == 0:
        return np.int8
    mn = values.min()
    mx = values.max()
    for cand, lo, hi in _INT_CANDIDATES:
        if np.dtype(cand).itemsize >= itemsize:
            return None
        if lo <= mn and mx <= hi:
            return cand
    return None


def _encode_float64(values: np.ndarray):
    """Returns (wire_array, wire_np_name, scale) or None. Lossless only:
    decode(encode(v)) must equal v bitwise -- NaN/inf/-0.0 all disqualify
    the scaled path (and -0.0 would silently become +0.0)."""
    if values.size and not np.isfinite(values).all():
        return None
    if values.size and np.any((values == 0) & np.signbit(values)):
        return None
    for scale in _FLOAT_SCALES:
        w = values * scale
        r = np.rint(w)
        if np.any(np.abs(r) > 2 ** 31 - 1):
            continue
        if not np.array_equal(r / scale, values):
            continue
        narrow = _narrow_int(r, 8) or np.int32
        return r.astype(narrow), np.dtype(narrow).name, scale
    f32 = values.astype(np.float32)
    if np.array_equal(f32.astype(np.float64), values):
        return f32, "float32", 0
    return None


def encode_column(hc, name: str, n: int, cap: int,
                  string_widths: Optional[dict]) -> Tuple[List[np.ndarray],
                                                          tuple]:
    """Host-side encode of one column -> (wire arrays, static spec)."""
    from spark_rapids_tpu.columnar.host import strings_to_matrix
    validity = np.zeros(cap, dtype=np.bool_)
    validity[:n] = hc.validity
    all_valid = bool(validity[:n].all())
    if all_valid:
        vmode, varrs = "all", []
    else:
        vmode = "packed"
        varrs = [np.packbits(validity, bitorder="little")]

    if hc.dtype.is_string:
        m, lens = strings_to_matrix(hc)
        lens = np.where(hc.validity, lens, 0)
        want = dt.string_width_bucket(int(lens.max()) if n else 0)
        if string_widths and name in string_widths:
            want = max(want, string_widths[name])
        data = np.zeros((cap, want), dtype=np.uint8)
        w = min(want, m.shape[1])
        data[:n, :w] = np.where(hc.validity[:, None], m, 0)[:, :w]
        lengths = np.zeros(cap, dtype=np.int16)
        lengths[:n] = lens
        return [data, lengths] + varrs, ("str", want, vmode)

    values = np.where(hc.validity, hc.data,
                      np.zeros(1, hc.dtype.np_dtype)) \
        .astype(hc.dtype.np_dtype, copy=False)
    wire = values
    wire_name = hc.dtype.np_dtype.name
    scale = 0
    if hc.dtype.np_dtype == np.float64:
        enc = _encode_float64(values)
        if enc is not None:
            wire, wire_name, scale = enc
    elif hc.dtype.np_dtype.kind == "i":
        narrow = _narrow_int(values, hc.dtype.itemsize)
        if narrow is not None:
            wire = values.astype(narrow)
            wire_name = np.dtype(narrow).name
    data = np.zeros(cap, dtype=wire.dtype)
    data[:n] = wire
    # The scale ships as a RUNTIME f64 scalar: a constant denominator lets
    # XLA strength-reduce the divide into a reciprocal multiply, which is
    # not correctly rounded and would break the bit-exact round trip the
    # host-side check guarantees (true IEEE division is exact here).
    sarr = [np.asarray(float(scale), np.float64)] if scale else []
    return [data] + sarr + varrs, ("num", hc.dtype.name, wire_name, scale,
                                   vmode)


_DECODE_JIT_CACHE: dict = {}


def _unpack_validity(bits: jax.Array, cap: int) -> jax.Array:
    """Inverse of np.packbits(bitorder='little'): (cap/8,) uint8 -> bool."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    opened = (bits[:, None] >> shifts[None, :]) & 1
    return opened.reshape(-1)[:cap].astype(jnp.bool_)


def _decode_fn(cap: int, specs: tuple):
    def decode(arrays, num_rows):
        it = iter(arrays)
        row_mask = None
        cols = []
        for spec in specs:
            if spec[0] == "str":
                _, width, vmode = spec
                data = next(it)
                lengths = next(it).astype(jnp.int32)
                if vmode == "packed":
                    validity = _unpack_validity(next(it), cap)
                else:
                    if row_mask is None:
                        row_mask = jnp.arange(cap, dtype=jnp.int32) \
                            < num_rows
                    validity = row_mask
                cols.append(DeviceColumn(dt.STRING, data, validity,
                                         lengths))
                continue
            _, logical_name, wire_name, scale, vmode = spec
            logical = dt.type_named(logical_name)
            w = next(it)
            if scale:
                data = w.astype(logical.np_dtype) / next(it)
            elif w.dtype == logical.np_dtype:
                data = w
            else:
                data = w.astype(logical.np_dtype)
            if vmode == "packed":
                validity = _unpack_validity(next(it), cap)
            else:
                if row_mask is None:
                    row_mask = jnp.arange(cap, dtype=jnp.int32) < num_rows
                validity = row_mask
            cols.append(DeviceColumn(logical, data, validity))
        return DeviceBatch(tuple(cols), num_rows)
    return decode


def encode_batch(batch, capacity: Optional[int] = None,
                 string_widths: Optional[dict] = None):
    """Host-side half of the upload: analyze + narrow + pad. CPU-only, so
    scan prefetch threads can run it concurrently with device work.
    Returns (arrays, specs, n, cap)."""
    from spark_rapids_tpu.columnar.batch import bucket_capacity
    n = batch.num_rows
    cap = capacity if capacity is not None else bucket_capacity(n)
    assert cap >= n, f"capacity {cap} < rows {n}"
    arrays: List[np.ndarray] = []
    specs = []
    for name, hc in zip(batch.names, batch.columns):
        arrs, spec = encode_column(hc, name, n, cap, string_widths)
        arrays.extend(arrs)
        specs.append(spec)
    arrays.append(np.asarray(n, np.int32))
    return arrays, tuple(specs), n, cap


def upload_encoded(arrays, specs, n: int, cap: int) -> DeviceBatch:
    """Device-side half: single device_put + jitted on-device widen."""
    put = jax.device_put(arrays)
    dev_arrays, num_rows = put[:-1], put[-1]
    key = (cap, specs)
    fn = _DECODE_JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_decode_fn(cap, specs))
        _DECODE_JIT_CACHE[key] = fn
    out = fn(dev_arrays, num_rows)
    out.rows_hint = n
    return out


def upload(batch, capacity: Optional[int] = None,
           string_widths: Optional[dict] = None) -> DeviceBatch:
    """Encode + single device_put + jitted on-device widen."""
    return upload_encoded(*encode_batch(batch, capacity, string_widths))
