"""Packed row movement: gather/scatter/concat over class-stacked slabs.

TPU-first redesign of the engine's row-movement primitives (the cuDF
``Table.gather`` / ``contiguous_split`` analogs the reference reaches via
JNI — GpuColumnVector.java from(Table), GpuCoalesceBatches.scala:643).

Motivation (measured on the target device, scripts/microbench.py): XLA-TPU
gather/scatter cost scales with the NUMBER OF ROW OPERATIONS, not bytes —
seven separate 1M-row float64 scatters cost ~920ms while one (1M, 7) 2D
scatter costs ~130ms. So before moving rows, all columns of a batch are
packed into at most three "slabs":

- ``w8``: every value 4 bytes or narrower, bitcast to uint8 bytes and
  concatenated along a width axis — bool/int8/int16/int32/date/float32
  data, string byte matrices, string lengths, and ALL validity vectors;
- ``f64``: float64 columns stacked (N, k) — the TPU's emulated f64 has no
  bitcast, so these stay in the float domain;
- ``i64``: int64/timestamp columns stacked (N, k), same reason.

One gather/scatter per slab then moves every column at once; unpacking is
pure bitcasts/slices that XLA fuses into the consumer.

Null/data discipline: moved rows whose destination is dead are zeroed whole
(one ``where`` per slab), preserving the engine's deterministic-padding
invariant. Values at rows whose validity is False are NOT otherwise
normalized here — consumers must mask by validity (they all do; the
fingerprint kernel normalizes null key data itself).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn


def _to_bytes(arr: jax.Array) -> jax.Array:
    """(N,) array of a ≤4-byte dtype -> (N, itemsize) uint8 view."""
    if arr.dtype == jnp.bool_:
        return arr.astype(jnp.uint8)[:, None]
    if arr.dtype == jnp.uint8:
        return arr[:, None] if arr.ndim == 1 else arr
    out = jax.lax.bitcast_convert_type(arr, jnp.uint8)
    # Same-width bitcasts (int8) add no trailing byte axis.
    return out[:, None] if out.ndim == 1 else out


def _from_bytes(b: jax.Array, np_dtype) -> jax.Array:
    """(N, itemsize) uint8 -> (N,) of np_dtype (inverse of _to_bytes)."""
    np_dtype = np.dtype(np_dtype)
    if np_dtype == np.bool_:
        return b[:, 0] != 0
    if np_dtype == np.uint8:
        return b[:, 0]
    if np_dtype.itemsize == 1:
        return jax.lax.bitcast_convert_type(b[:, 0], jnp.dtype(np_dtype))
    return jax.lax.bitcast_convert_type(b, jnp.dtype(np_dtype))


_W8, _F64, _I64 = "w8", "f64", "i64"


def _col_class(dtype) -> str:
    if dtype.np_dtype == np.float64:
        return _F64
    if dtype.np_dtype == np.int64:
        return _I64
    return _W8


def pack_batch(batch: DeviceBatch) -> Dict[str, jax.Array]:
    """Pack all columns (+ validities, string lengths) into ≤3 slabs."""
    w8: List[jax.Array] = []
    f64: List[jax.Array] = []
    i64: List[jax.Array] = []
    for c in batch.columns:
        if c.dtype.is_string:
            w8.append(c.data)
            w8.append(_to_bytes(c.lengths))
        elif _col_class(c.dtype) == _F64:
            f64.append(c.data)
        elif _col_class(c.dtype) == _I64:
            i64.append(c.data)
        else:
            w8.append(_to_bytes(c.data))
        w8.append(_to_bytes(c.validity))
    slabs: Dict[str, jax.Array] = {}
    if w8:
        slabs[_W8] = w8[0] if len(w8) == 1 else jnp.concatenate(w8, axis=1)
    if f64:
        slabs[_F64] = jnp.stack(f64, axis=1)
    if i64:
        slabs[_I64] = jnp.stack(i64, axis=1)
    return slabs


def unpack_batch(slabs: Dict[str, jax.Array], template: DeviceBatch,
                 num_rows: jax.Array,
                 sel: Optional[jax.Array] = None) -> DeviceBatch:
    """Rebuild a DeviceBatch from slabs, using ``template`` for the schema
    (dtypes + string widths)."""
    w8 = slabs.get(_W8)
    f64 = slabs.get(_F64)
    i64 = slabs.get(_I64)
    w8_off = 0
    f64_i = 0
    i64_i = 0
    cols: List[DeviceColumn] = []
    for c in template.columns:
        if c.dtype.is_string:
            w = c.string_width
            data = w8[:, w8_off:w8_off + w]
            w8_off += w
            lengths = _from_bytes(w8[:, w8_off:w8_off + 4], np.int32)
            w8_off += 4
        elif _col_class(c.dtype) == _F64:
            data = f64[:, f64_i]
            f64_i += 1
            lengths = None
        elif _col_class(c.dtype) == _I64:
            data = i64[:, i64_i].astype(c.dtype.np_dtype)
            i64_i += 1
            lengths = None
        else:
            k = c.dtype.np_dtype.itemsize
            data = _from_bytes(w8[:, w8_off:w8_off + k], c.dtype.np_dtype)
            w8_off += k
            lengths = None
        validity = w8[:, w8_off] != 0
        w8_off += 1
        if c.dtype.is_string:
            cols.append(DeviceColumn(c.dtype, data, validity, lengths))
        else:
            cols.append(DeviceColumn(c.dtype, data, validity))
    return DeviceBatch(tuple(cols), jnp.asarray(num_rows, jnp.int32),
                       sel=sel)


def gather_rows(batch: DeviceBatch, indices: jax.Array,
                new_num_rows: jax.Array,
                valid_dst: Optional[jax.Array] = None) -> DeviceBatch:
    """Take rows at ``indices`` into a dense batch of ``len(indices)``
    capacity. ``valid_dst`` masks live destination slots (defaults to
    ``arange < new_num_rows``); dead slots are zeroed whole."""
    cap = indices.shape[0]
    if valid_dst is None:
        valid_dst = jnp.arange(cap, dtype=jnp.int32) < new_num_rows
    slabs = pack_batch(batch)
    out = {}
    for k, slab in slabs.items():
        g = jnp.take(slab, indices, axis=0, mode="clip")
        mask = valid_dst[:, None] if g.ndim == 2 else valid_dst
        out[k] = jnp.where(mask, g, jnp.zeros_like(g))
    return unpack_batch(out, batch, new_num_rows)


def scatter_rows(batch: DeviceBatch, positions: jax.Array, capacity: int,
                 num_rows: jax.Array) -> DeviceBatch:
    """Write row i to ``positions[i]``; positions >= capacity are dropped.
    Callers route dead rows to ``capacity``."""
    slabs = pack_batch(batch)
    out = {}
    for k, slab in slabs.items():
        shape = (capacity,) + slab.shape[1:]
        out[k] = jnp.zeros(shape, slab.dtype).at[positions].set(
            slab, mode="drop")
    return unpack_batch(out, batch, num_rows)


def compact_batch(batch: DeviceBatch,
                  keep: Optional[jax.Array] = None) -> DeviceBatch:
    """Materialize live rows (optionally ANDed with ``keep``) as a packed
    prefix at the same capacity — the selection-vector discharge point."""
    live = batch.row_mask() if keep is None else (keep & batch.row_mask())
    positions = jnp.cumsum(live.astype(jnp.int32)) - 1
    positions = jnp.where(live, positions, batch.capacity)
    new_rows = jnp.sum(live.astype(jnp.int32))
    return scatter_rows(batch, positions, batch.capacity, new_rows)


def compact_to(batch: DeviceBatch, capacity: int,
               live_count: jax.Array) -> DeviceBatch:
    """Compact live rows into a batch of (smaller) static ``capacity``.

    One cheap int32 scatter builds the live-row index list, then a packed
    gather at the target capacity moves the data — cost scales with the
    OUTPUT rows, so shrinking a mostly-dead batch is nearly free."""
    live = batch.row_mask()
    rank = jnp.cumsum(live.astype(jnp.int32)) - 1
    idx = jnp.zeros((capacity,), jnp.int32).at[
        jnp.where(live, rank, capacity)].set(
        jnp.arange(batch.capacity, dtype=jnp.int32), mode="drop")
    return gather_rows(batch, idx, jnp.asarray(live_count, jnp.int32))


def concat_compact(batches: Sequence[DeviceBatch],
                   capacity: int) -> DeviceBatch:
    """Concatenate the LIVE rows of ``batches`` into one dense batch.

    Selection-vector aware: each member's live rows are packed by a
    per-member cumsum, offset by the running live total (device scalars).
    One packed scatter per member; every destination written once."""
    assert batches, "concat of zero batches"
    out_slabs: Dict[str, jax.Array] = {}
    template = max(batches, key=lambda b: b.capacity)
    # Widen string columns to the widest member so slabs line up.
    from spark_rapids_tpu.columnar.batch import string_repad
    widths = []
    for ci in range(batches[0].num_columns):
        if batches[0].columns[ci].dtype.is_string:
            widths.append(max(b.columns[ci].string_width for b in batches))
        else:
            widths.append(None)

    def widen(b: DeviceBatch) -> DeviceBatch:
        cols = tuple(string_repad(c, w) if w is not None else c
                     for c, w in zip(b.columns, widths))
        return DeviceBatch(cols, b.num_rows, sel=b.sel)

    template = widen(template)
    off = jnp.asarray(0, jnp.int32)
    total = jnp.asarray(0, jnp.int32)
    for b in batches:
        b = widen(b)
        live = b.row_mask()
        pos = jnp.cumsum(live.astype(jnp.int32)) - 1 + off
        pos = jnp.where(live, pos, capacity)
        cnt = jnp.sum(live.astype(jnp.int32))
        slabs = pack_batch(b)
        for k, slab in slabs.items():
            acc = out_slabs.get(k)
            if acc is None:
                shape = (capacity,) + slab.shape[1:]
                acc = jnp.zeros(shape, slab.dtype)
            out_slabs[k] = acc.at[pos].set(slab, mode="drop")
        off = off + cnt
        total = total + cnt
    return unpack_batch(out_slabs, template, total)
