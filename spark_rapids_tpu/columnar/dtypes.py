"""SQL type system for the TPU columnar engine.

Mirrors the supported-type gate of the reference (GpuOverrides.scala:442-455:
bool/byte/short/int/long/float/double/date/timestamp-UTC/string only), mapped
onto jnp dtypes. DATE is days-since-epoch int32 and TIMESTAMP is
microseconds-since-epoch int64 (UTC), matching Spark's internal Catalyst
representation so results can be compared bit-for-bit.

Strings are stored TPU-first as a fixed-width padded byte matrix
``(capacity, width) uint8`` plus an int32 length column (see
columnar/batch.py) — vector-friendly for the VPU — rather than cuDF's
offsets+chars layout; width is bucketed per column to bound recompilation.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    """A SQL-level column type.

    ``np_dtype`` is the physical element dtype of the backing device array.
    For STRING the backing array is uint8 with an extra width axis.
    """

    name: str
    np_dtype: np.dtype
    # Byte width of one element (strings: per byte; see Column for width axis).
    itemsize: int

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.name

    @property
    def is_string(self) -> bool:
        return self.name == "string"

    @property
    def is_integral(self) -> bool:
        return self.name in ("int8", "int16", "int32", "int64")

    @property
    def is_floating(self) -> bool:
        return self.name in ("float32", "float64")

    @property
    def is_numeric(self) -> bool:
        return self.is_integral or self.is_floating

    @property
    def is_datetime(self) -> bool:
        return self.name in ("date", "timestamp")

    @property
    def is_boolean(self) -> bool:
        return self.name == "bool"


BOOL = DataType("bool", np.dtype(np.bool_), 1)
INT8 = DataType("int8", np.dtype(np.int8), 1)
INT16 = DataType("int16", np.dtype(np.int16), 2)
INT32 = DataType("int32", np.dtype(np.int32), 4)
INT64 = DataType("int64", np.dtype(np.int64), 8)
FLOAT32 = DataType("float32", np.dtype(np.float32), 4)
FLOAT64 = DataType("float64", np.dtype(np.float64), 8)
# Spark DateType: days since unix epoch, int32.
DATE = DataType("date", np.dtype(np.int32), 4)
# Spark TimestampType: microseconds since unix epoch UTC, int64.
TIMESTAMP = DataType("timestamp", np.dtype(np.int64), 8)
STRING = DataType("string", np.dtype(np.uint8), 1)

ALL_TYPES = (BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE,
             TIMESTAMP, STRING)

_BY_NAME = {t.name: t for t in ALL_TYPES}
# Convenience aliases matching Spark SQL names.
_BY_NAME.update({
    "boolean": BOOL, "byte": INT8, "short": INT16, "int": INT32,
    "integer": INT32, "long": INT64, "bigint": INT64, "float": FLOAT32,
    "double": FLOAT64,
})


def type_named(name: str) -> DataType:
    return _BY_NAME[name]


def common_numeric_type(a: DataType, b: DataType) -> DataType:
    """Spark's numeric widening for binary arithmetic operands."""
    if a == b:
        return a
    if not (a.is_numeric and b.is_numeric):
        raise TypeError(f"no common numeric type for {a} and {b}")
    if FLOAT64 in (a, b):
        return FLOAT64
    if FLOAT32 in (a, b):
        # Spark promotes (float, long) -> float? No: (float, long) -> float.
        return FLOAT32
    order = [INT8, INT16, INT32, INT64]
    return order[max(order.index(a), order.index(b))]


def from_numpy_dtype(dt: np.dtype) -> DataType:
    dt = np.dtype(dt)
    for t in (BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64):
        if t.np_dtype == dt:
            return t
    if dt.kind in ("U", "S", "O"):
        return STRING
    if dt.kind == "M":
        return TIMESTAMP
    raise TypeError(f"unsupported numpy dtype {dt}")


# Default string width bucket ladder (bytes). Width is static under jit, so
# we bucket it like capacities to bound the number of compiled programs.
STRING_WIDTH_BUCKETS = (8, 16, 32, 64, 128, 256)


def string_width_bucket(max_len: int) -> int:
    for w in STRING_WIDTH_BUCKETS:
        if max_len <= w:
            return w
    # Very long strings fall back to the exact next multiple of 128.
    return ((max_len + 127) // 128) * 128
