"""Host-resident columnar batches and host<->device transitions.

The analog of the reference's RapidsHostColumnVector + GpuRowToColumnarExec /
GpuColumnarToRowExec / HostColumnarToGpu trio (SURVEY.md §2.3): host data is
numpy (fixed width) or numpy object arrays of bytes (strings); transitions
pad to the capacity bucket and upload, or download and trim to num_rows.

Host batches are also the currency of the CPU-fallback engine
(plan/physical.py) — the numpy analog of rows staying on CPU Spark.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity)


class HostColumn:
    """One host column: values + validity. Strings are ``object`` arrays of
    python ``bytes`` (None entries are allowed and mean null).

    String columns may instead carry the dense device layout directly
    (``str_matrix`` (n, w) uint8 + ``str_lengths`` int32) — the vectorized
    fast path used by the arrow bridge and the host<->device transitions so
    scans never loop per row; the object array is materialized lazily only
    when a host-oracle kernel asks for ``.data``."""

    def __init__(self, dtype: DataType, data: Optional[np.ndarray],
                 validity: np.ndarray,
                 str_matrix: Optional[np.ndarray] = None,
                 str_lengths: Optional[np.ndarray] = None):
        self.dtype = dtype
        self._data = data
        self.validity = validity
        self.str_matrix = str_matrix
        self.str_lengths = str_lengths

    @property
    def data(self) -> np.ndarray:
        if self._data is None:
            m, lens, val = self.str_matrix, self.str_lengths, self.validity
            out = np.empty(m.shape[0], dtype=object)
            for i in range(m.shape[0]):
                out[i] = m[i, :lens[i]].tobytes() if val[i] else b""
            self._data = out
        return self._data

    @data.setter
    def data(self, v):
        self._data = v

    @property
    def num_rows(self) -> int:
        return len(self.validity)

    @classmethod
    def from_values(cls, dtype: DataType, values: Sequence) -> "HostColumn":
        """Build from a python sequence; None means null."""
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        if dtype.is_string:
            data = np.empty(n, dtype=object)
            for i, v in enumerate(values):
                if v is None:
                    data[i] = b""
                else:
                    data[i] = v.encode("utf-8") if isinstance(v, str) else bytes(v)
        else:
            data = np.zeros(n, dtype=dtype.np_dtype)
            idx = np.nonzero(validity)[0]
            if len(idx):
                data[idx] = np.asarray([values[i] for i in idx],
                                       dtype=dtype.np_dtype)
        return cls(dtype, data, validity)

    def to_list(self) -> list:
        """Python values with None for nulls (test/compare currency).

        Vectorized: one ``ndarray.tolist()`` converts the whole column
        to native python scalars at C speed, then nulls patch in via the
        (usually tiny) invalid index set — the per-row python loop with
        its per-element dtype branches used to dominate ``collect``'s
        pure-CPU tail (scripts/bench_rows.py measures the difference).
        Strings slice one contiguous ``tobytes()`` buffer per column
        instead of materializing the lazy per-row object array."""
        val = np.asarray(self.validity, dtype=np.bool_)
        n = len(val)
        if self.dtype.is_string:
            if self._data is None:
                # Dense matrix layout: decode straight off one buffer.
                m, lens = self.str_matrix, self.str_lengths
                w = m.shape[1]
                buf = m.tobytes()
                lens_l = lens.tolist()
                val_l = val.tolist()
                out = [buf[i * w:i * w + lens_l[i]]
                       .decode("utf-8", "replace") if val_l[i] else None
                       for i in range(n)]
                return out
            out = [bytes(b).decode("utf-8", "replace") if v else None
                   for b, v in zip(self.data, val.tolist())]
            return out
        # tolist() yields native bool/int/float for every numpy dtype
        # this engine carries — identical values to the per-row casts.
        out = np.asarray(self.data)[:n].tolist()
        if not val.all():
            for i in np.flatnonzero(~val).tolist():
                out[i] = None
        return out


@dataclasses.dataclass
class HostBatch:
    names: Tuple[str, ...]
    columns: List[HostColumn]

    @property
    def num_rows(self) -> int:
        return self.columns[0].num_rows if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> HostColumn:
        return self.columns[self.names.index(name)]

    def to_pylist(self) -> List[tuple]:
        cols = [c.to_list() for c in self.columns]
        return list(zip(*cols)) if cols else []

    @classmethod
    def from_pydict(cls, schema: Sequence[Tuple[str, DataType]],
                    data: dict) -> "HostBatch":
        names = tuple(n for n, _ in schema)
        cols = [HostColumn.from_values(t, data[n]) for n, t in schema]
        return cls(names, cols)


def strings_to_matrix(col: "HostColumn") -> Tuple[np.ndarray, np.ndarray]:
    """Host string column -> ((n, w) uint8 byte matrix, (n,) int32 lengths).

    The single shared bridge between host object-array strings and the dense
    device layout; used by every host-path string kernel and by the
    host->device transition. ``None`` entries (permitted null encoding per
    HostColumn's contract) become empty strings.
    """
    if col.str_matrix is not None:
        return col.str_matrix, col.str_lengths
    n = col.num_rows
    vals = [b"" if b is None else bytes(b) for b in col.data]
    w = max([len(b) for b in vals] + [1])
    m = np.zeros((n, w), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    for i, b in enumerate(vals):
        m[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    return m, lens


def matrix_to_strings(data: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray) -> "HostColumn":
    """Inverse of strings_to_matrix (nulls become empty bytes). The object
    array stays lazy: the matrix IS the column until a host kernel asks."""
    from spark_rapids_tpu.columnar import dtypes as _dt
    validity = np.asarray(validity, np.bool_)
    return HostColumn(_dt.STRING, None, validity,
                      str_matrix=np.asarray(data),
                      str_lengths=np.asarray(lengths, np.int32))


@dataclasses.dataclass
class StringMatrixView:
    """A host string column viewed in the dense device layout: byte matrix +
    lengths + validity, carrying its dtype so kernels that branch on
    ``dtype.is_string`` (blend/repad) work on it. The one shared adapter for
    every host-path string kernel."""

    dtype: "DataType"
    data: np.ndarray          # (n, w) uint8
    lengths: np.ndarray       # (n,) int32
    validity: np.ndarray      # (n,) bool

    @classmethod
    def of(cls, col: "HostColumn") -> "StringMatrixView":
        m, lens = strings_to_matrix(col)
        return cls(col.dtype, m, lens, col.validity)


def concat_host_batches(hbs: Sequence["HostBatch"]) -> "HostBatch":
    """Row-concatenate host batches (vectorized; string columns merge at
    the byte-matrix level so no object arrays materialize)."""
    assert hbs, "concat of zero host batches"
    if len(hbs) == 1:
        return hbs[0]
    cols = []
    for ci, c0 in enumerate(hbs[0].columns):
        members = [hb.columns[ci] for hb in hbs]
        val = np.concatenate([m.validity for m in members])
        if c0.dtype.is_string:
            mats = [strings_to_matrix(m) for m in members]
            w = max(mm.shape[1] for mm, _ in mats)
            mat = np.zeros((len(val), w), np.uint8)
            lens = np.concatenate([l for _, l in mats]).astype(np.int32)
            off = 0
            for mm, _ in mats:
                mat[off:off + mm.shape[0], :mm.shape[1]] = mm
                off += mm.shape[0]
            cols.append(HostColumn(c0.dtype, None, val,
                                   str_matrix=mat, str_lengths=lens))
        else:
            cols.append(HostColumn(
                c0.dtype, np.concatenate([m.data for m in members]), val))
    return HostBatch(hbs[0].names, cols)


# ---------------------------------------------------------------------------
# Transitions (host -> device -> host)
# ---------------------------------------------------------------------------

def host_to_device(batch: HostBatch, capacity: Optional[int] = None,
                   string_widths: Optional[dict] = None) -> DeviceBatch:
    """Upload a host batch into a fresh fixed-capacity device batch.

    Ref: HostColumnarToGpu.scala / GpuRowToColumnarExec.scala. The upload
    goes through the wire codec (columnar/wire.py): narrow lossless wire
    dtypes + packed/absent validity, one device_put, and a jitted
    on-device widen back to the logical layout — the transfer link, not
    HBM, is the scarce resource.
    """
    from spark_rapids_tpu.columnar import wire
    return wire.upload(batch, capacity, string_widths)


# Batches whose device footprint exceeds MIN_SHRINK_BYTES get shrunk to
# their live-row bucket before download: the row-count sync (~1 round
# trip) buys back the padding bytes, which dominate on a slow link.


def download_batches(batches: Sequence[DeviceBatch],
                     names: Optional[Sequence[str]] = None
                     ) -> List[HostBatch]:
    """Download many device batches with the minimum number of round trips.

    Ref: GpuColumnarToRowExec.scala — the single place results leave HBM.
    Two-phase on purpose (SURVEY §7 sizes-then-data): phase 1 pulls every
    unknown row count in ONE ``jax.device_get`` and shrinks padded batches
    to their live bucket; phase 2 fetches all remaining buffers in ONE
    more ``device_get`` so the transfers pipeline. On a tunneled device
    each extra sync is a full network round trip, so per-batch/per-buffer
    loops cost O(batches*columns) round trips while this costs two.
    """
    import jax
    from spark_rapids_tpu.columnar.batch import shrink_all
    # LARGE batches shrink first (the row-count sync buys back padding
    # bytes on the link); small ones — selection vectors included — ship
    # as-is with their row mask and filter on the HOST, which costs no
    # device round trip and no compaction gather at all.
    from spark_rapids_tpu.columnar.batch import MIN_SHRINK_BYTES
    batches, _ = shrink_all(batches, min_bytes=MIN_SHRINK_BYTES)
    leaves: List = []
    for b in batches:
        leaves.append(b.num_rows)
        leaves.append(b.sel if b.sel is not None else None)
        for c in b.columns:
            leaves.append(c.data)
            leaves.append(c.validity)
            if c.dtype.is_string:
                leaves.append(c.lengths)
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory.oom import retry_on_oom

    def _fetch():
        # Named injection site + OOM ladder around the one batched
        # device_get every result takes (the download dispatch funnel).
        faults.fault_point("download")
        return jax.device_get([x for x in leaves if x is not None])

    fetched = retry_on_oom(_fetch)
    it = iter(fetched)
    out = []
    for b in batches:
        n = int(next(it))
        keep = None
        if b.sel is not None:
            keep = np.asarray(next(it))[:n]
        cols = []
        for c in b.columns:
            data_h = np.asarray(next(it))[:n]
            validity = np.asarray(next(it))[:n]
            lengths = None
            if c.dtype.is_string:
                lengths = np.asarray(next(it))[:n]
            if keep is not None:
                data_h, validity = data_h[keep], validity[keep]
                if lengths is not None:
                    lengths = lengths[keep]
            if c.dtype.is_string:
                cols.append(matrix_to_strings(data_h, lengths, validity))
            else:
                data = data_h.copy()
                data[~validity] = np.zeros(1, c.dtype.np_dtype)
                cols.append(HostColumn(c.dtype, data, validity))
        if names is None:
            batch_names = tuple(f"c{i}" for i in range(b.num_columns))
        else:
            batch_names = tuple(names)
        out.append(HostBatch(batch_names, cols))
    return out


def device_to_host(batch: DeviceBatch,
                   names: Optional[Sequence[str]] = None) -> HostBatch:
    """Download one device batch, trimming padding rows."""
    return download_batches([batch], names)[0]
