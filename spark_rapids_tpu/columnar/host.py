"""Host-resident columnar batches and host<->device transitions.

The analog of the reference's RapidsHostColumnVector + GpuRowToColumnarExec /
GpuColumnarToRowExec / HostColumnarToGpu trio (SURVEY.md §2.3): host data is
numpy (fixed width) or numpy object arrays of bytes (strings); transitions
pad to the capacity bucket and upload, or download and trim to num_rows.

Host batches are also the currency of the CPU-fallback engine
(plan/physical.py) — the numpy analog of rows staying on CPU Spark.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity)


# The shared all-valid mask (satellite of the vectorized host engine):
# ``eval_host`` sites used to allocate ``np.full(n, True)`` per call. One
# read-only buffer grows monotonically and every caller slices a view.
_ALL_VALID = np.ones(0, dtype=np.bool_)


def all_valid(n: int) -> np.ndarray:
    """A read-only all-True validity mask of length ``n`` (shared buffer).

    Callers that need to flip bits must copy — the read-only flag turns a
    silent shared-mask corruption into an immediate ValueError."""
    global _ALL_VALID
    if n > _ALL_VALID.shape[0]:
        _ALL_VALID = np.ones(max(n, 2 * _ALL_VALID.shape[0], 1024),
                             dtype=np.bool_)
        _ALL_VALID.setflags(write=False)
    return _ALL_VALID[:n]


class HostColumn:
    """One host column: values + validity. Strings are ``object`` arrays of
    python ``bytes`` (None entries are allowed and mean null).

    String columns may instead carry the dense device layout directly
    (``str_matrix`` (n, w) uint8 + ``str_lengths`` int32) — the vectorized
    fast path used by the arrow bridge and the host<->device transitions so
    scans never loop per row; the object array is materialized lazily only
    when a host-oracle kernel asks for ``.data``."""

    def __init__(self, dtype: DataType, data: Optional[np.ndarray],
                 validity: np.ndarray,
                 str_matrix: Optional[np.ndarray] = None,
                 str_lengths: Optional[np.ndarray] = None):
        self.dtype = dtype
        self._data = data
        self.validity = validity
        self.str_matrix = str_matrix
        self.str_lengths = str_lengths
        # encode_key memo: grouping sets / sort / window re-encode the
        # SAME column instance (rollup encodes a shared key once per
        # set otherwise — the dominant host span on TPC-DS q67).
        # ``_key_uniq`` identifies the string coding SPACE: the sorted
        # unique byte records the rank codes index into. take()/filter()
        # propagate (codes, space) to derived columns, so a post-shuffle
        # consumer merges tiny per-space dictionaries instead of
        # re-ranking every row.
        self._key_codes: Optional[np.ndarray] = None
        self._key_uniq: Optional[np.ndarray] = None
        # Deferred gather provenance ``(parent, selection, validity)``
        # recorded by take()/filter() on string columns whose parent has
        # no codes YET: when this column is later asked for codes,
        # encode_key ranks the (usually much smaller) parent once and
        # gathers — a broadcast dimension table taken into every probe
        # partition is ranked once per query, not once per partition.
        self._key_src = None

    @property
    def data(self) -> np.ndarray:
        if self._data is None:
            m, lens, val = self.str_matrix, self.str_lengths, self.validity
            n = m.shape[0]
            w = m.shape[1]
            out = np.empty(n, dtype=object)
            # One contiguous buffer + C-level bytes slicing beats per-row
            # ndarray indexing + tobytes by ~20× (same trick as to_list).
            buf = m.tobytes()
            lens_l = lens.tolist()
            val_l = np.asarray(val, np.bool_).tolist()
            out[:] = [buf[i * w:i * w + lens_l[i]] if val_l[i] else b""
                      for i in range(n)]
            self._data = out
        return self._data

    @data.setter
    def data(self, v):
        self._data = v
        self._key_codes = None
        self._key_uniq = None
        self._key_src = None

    @property
    def num_rows(self) -> int:
        return len(self.validity)

    def take(self, indices: np.ndarray,
             null_on_negative: bool = False) -> "HostColumn":
        """Row gather preserving the dense string layout (no object arrays).

        With ``null_on_negative`` a negative index yields a null row — the
        currency of vectorized outer-join null extension."""
        idx = np.asarray(indices, dtype=np.int64)
        if null_on_negative:
            if self.num_rows == 0:
                # Every index is a null extension of an empty side.
                n = len(idx)
                if self.dtype.is_string:
                    return HostColumn(self.dtype, None,
                                      np.zeros(n, np.bool_),
                                      str_matrix=np.zeros((n, 1), np.uint8),
                                      str_lengths=np.zeros(n, np.int32))
                return HostColumn(self.dtype,
                                  np.zeros(n, self.dtype.np_dtype),
                                  np.zeros(n, np.bool_))
            neg = idx < 0
            safe = np.where(neg, 0, idx)
            val = self.validity[safe] & ~neg
        else:
            safe = idx
            val = self.validity[safe]
        if self.dtype.is_string and self._data is None:
            m = self.str_matrix[safe]
            lens = np.where(val, self.str_lengths[safe], 0).astype(np.int32)
            out = HostColumn(self.dtype, None, np.asarray(val, np.bool_),
                             str_matrix=m, str_lengths=lens)
            return self._propagate_key_codes(out, safe, val)
        # Fancy indexing always yields a fresh array, so in-place null
        # canonicalization below never aliases the source column.
        d = self.data[safe]
        if self.dtype.is_string:
            if not val.all():
                for i in np.flatnonzero(~val):
                    d[i] = b""
        else:
            if not val.all():
                d[~val] = np.zeros(1, self.dtype.np_dtype)
        out = HostColumn(self.dtype, d, np.asarray(val, np.bool_))
        return self._propagate_key_codes(out, safe, val)

    def _propagate_key_codes(self, out: "HostColumn", safe: np.ndarray,
                             val: np.ndarray) -> "HostColumn":
        """Carry (rank codes, coding space) through a gather: parent
        ranks stay order-preserving and equality-exact over any row
        subset. Rows nulled by the gather drop to code 0 (the null
        code), matching what a fresh encoding would produce."""
        if self._key_codes is not None:
            kc = self._key_codes[safe]
            if not val.all():
                kc = np.where(val, kc, np.int64(0))
            out._key_codes = kc
            out._key_uniq = self._key_uniq
        elif self.dtype.is_string:
            out._key_src = (self, safe, val)
        return out

    def filter(self, keep: np.ndarray) -> "HostColumn":
        """Boolean-mask row filter, matrix-preserving like ``take``."""
        keep = np.asarray(keep, np.bool_)
        if self.dtype.is_string and self._data is None:
            out = HostColumn(self.dtype, None, self.validity[keep],
                             str_matrix=self.str_matrix[keep],
                             str_lengths=self.str_lengths[keep])
        else:
            out = HostColumn(self.dtype, self.data[keep],
                             self.validity[keep])
        if self._key_codes is not None:
            out._key_codes = self._key_codes[keep]
            out._key_uniq = self._key_uniq
        elif self.dtype.is_string:
            out._key_src = (self, keep, None)
        return out

    @classmethod
    def from_values(cls, dtype: DataType, values: Sequence) -> "HostColumn":
        """Build from a python sequence; None means null."""
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        if dtype.is_string:
            data = np.empty(n, dtype=object)
            data[:] = [b"" if v is None else
                       (v.encode("utf-8") if isinstance(v, str) else bytes(v))
                       for v in values]
        else:
            data = np.zeros(n, dtype=dtype.np_dtype)
            idx = np.nonzero(validity)[0]
            if len(idx):
                data[idx] = np.asarray([values[i] for i in idx],
                                       dtype=dtype.np_dtype)
        return cls(dtype, data, validity)

    def to_list(self) -> list:
        """Python values with None for nulls (test/compare currency).

        Vectorized: one ``ndarray.tolist()`` converts the whole column
        to native python scalars at C speed, then nulls patch in via the
        (usually tiny) invalid index set — the per-row python loop with
        its per-element dtype branches used to dominate ``collect``'s
        pure-CPU tail (scripts/bench_rows.py measures the difference).
        Strings slice one contiguous ``tobytes()`` buffer per column
        instead of materializing the lazy per-row object array."""
        val = np.asarray(self.validity, dtype=np.bool_)
        n = len(val)
        if self.dtype.is_string:
            if self._data is None:
                # Dense matrix layout: decode straight off one buffer.
                m, lens = self.str_matrix, self.str_lengths
                w = m.shape[1]
                buf = m.tobytes()
                lens_l = lens.tolist()
                val_l = val.tolist()
                out = [buf[i * w:i * w + lens_l[i]]
                       .decode("utf-8", "replace") if val_l[i] else None
                       for i in range(n)]
                return out
            out = [bytes(b).decode("utf-8", "replace") if v else None
                   for b, v in zip(self.data, val.tolist())]
            return out
        # tolist() yields native bool/int/float for every numpy dtype
        # this engine carries — identical values to the per-row casts.
        out = np.asarray(self.data)[:n].tolist()
        if not val.all():
            for i in np.flatnonzero(~val).tolist():
                out[i] = None
        return out


@dataclasses.dataclass
class HostBatch:
    names: Tuple[str, ...]
    columns: List[HostColumn]

    @property
    def num_rows(self) -> int:
        return self.columns[0].num_rows if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> HostColumn:
        return self.columns[self.names.index(name)]

    def to_pylist(self) -> List[tuple]:
        cols = [c.to_list() for c in self.columns]
        return list(zip(*cols)) if cols else []

    @classmethod
    def from_pydict(cls, schema: Sequence[Tuple[str, DataType]],
                    data: dict) -> "HostBatch":
        names = tuple(n for n, _ in schema)
        cols = [HostColumn.from_values(t, data[n]) for n, t in schema]
        return cls(names, cols)

    def take(self, indices: np.ndarray,
             null_on_negative: bool = False) -> "HostBatch":
        return HostBatch(self.names, [c.take(indices, null_on_negative)
                                      for c in self.columns])

    def filter(self, keep: np.ndarray) -> "HostBatch":
        # One mask scan for the whole batch: convert the boolean mask to
        # a gather index once instead of a count-and-copy mask pass per
        # column.
        idx = np.flatnonzero(np.asarray(keep, np.bool_))
        return HostBatch(self.names, [c.take(idx) for c in self.columns])


# ---------------------------------------------------------------------------
# Type-aware key encoding (shared by host sort / group-by / window / join)
# ---------------------------------------------------------------------------
#
# Every vectorized host op that orders or matches rows reduces each key
# column to ONE int64 code array with the invariants:
#   * order-preserving: code(a) < code(b)  iff  a sorts before b under the
#     engine's type-aware total order (floats: -inf..inf, every NaN equal
#     and greatest-of-negatives canonical bit pattern; -0.0 == +0.0),
#   * equality-exact: code(a) == code(b)  iff  a == b under group/join
#     semantics (NaN matches NaN, -0.0 matches +0.0),
#   * null-blind: invalid rows get code 0 — callers carry validity
#     alongside and order nulls by lexsorting the validity plane.

_NAN_CANON = np.int64(0x7FF8000000000000)


def encode_key(col: "HostColumn") -> np.ndarray:
    """Order-preserving int64 codes for one column (see invariants above).

    String codes are ranks drawn from THIS column only — comparable within
    the column (sort/group) but not across tables; joins use
    :func:`encode_key_pair` for a shared code space.

    Codes are memoized on the column instance (columns are immutable
    after construction; the ``data`` setter drops the memo)."""
    if col._key_codes is not None:
        return col._key_codes
    if col.dtype.is_string:
        src = col._key_src
        if src is not None:
            # Deferred gather: rank the parent (once, memoized there)
            # and pull this column's codes through the recorded
            # selection instead of re-ranking these rows from bytes.
            parent, sel, val = src
            kc = encode_key(parent)[sel]
            if val is not None and not val.all():
                kc = np.where(val, kc, np.int64(0))
            col._key_codes = kc
            col._key_uniq = parent._key_uniq
            col._key_src = None
            return kc
        codes_l, uniq = _string_codes([col])
        codes = codes_l[0]
        col._key_uniq = uniq
    else:
        codes = _fixed_codes(col)
    col._key_codes = codes
    return codes


def encode_key_concat(cols: Sequence["HostColumn"]
                      ) -> Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
    """``(codes, validity, space)`` for the row-concatenation of
    ``cols``, WITHOUT ranking the materialized concat. ``space`` is the
    unique-records matrix the string codes index into (None for
    fixed-width keys) — callers stamping the concat column's memo pass
    it along so downstream consumers keep merging dictionaries.

    Fixed-width codes are value-derived, so per-piece codes already
    share one space. String pieces are coded per instance (memoized —
    grouping-set expansion hands the same key instance back once per
    set, and shuffle slices arrive pre-coded via take()-propagation),
    then their coding spaces are merged over DISTINCT VALUES ONLY:
    ranking a few thousand dictionary entries instead of every row."""
    if len(cols) == 1:
        c = cols[0]
        return (encode_key(c), np.asarray(c.validity, np.bool_),
                c._key_uniq)
    validity = np.concatenate(
        [np.asarray(c.validity, np.bool_) for c in cols])
    if not cols[0].dtype.is_string:
        return (np.concatenate([encode_key(c) for c in cols]), validity,
                None)
    distinct: List["HostColumn"] = []
    seen = {}
    for c in cols:
        if id(c) not in seen:
            seen[id(c)] = c
            distinct.append(c)
    live = [c for c in distinct if bool(np.any(c.validity))]
    space: Optional[np.ndarray] = None
    if len(live) == 1:
        percodes = {id(live[0]): encode_key(live[0])}
        space = live[0]._key_uniq
    elif live:
        for c in live:
            encode_key(c)
        spaces: List[np.ndarray] = []
        space_idx = {}
        for c in live:
            u = c._key_uniq
            if u is not None and id(u) not in space_idx:
                space_idx[id(u)] = len(spaces)
                spaces.append(u)
        if any(c._key_uniq is None for c in live):
            codes_l, space = _string_codes(live)
            percodes = {id(c): k for c, k in zip(live, codes_l)}
        elif len(spaces) == 1:
            percodes = {id(c): c._key_codes for c in live}
            space = spaces[0]
        else:
            remaps, space = _merge_string_spaces(spaces)
            percodes = {
                id(c): remaps[space_idx[id(c._key_uniq)]][c._key_codes]
                for c in live}
    else:
        percodes = {}
    codes = np.concatenate([
        percodes.get(id(c), np.zeros(c.num_rows, np.int64)) for c in cols])
    return codes, validity, space


def encode_key_pair(a: "HostColumn",
                    b: "HostColumn") -> Tuple[np.ndarray, np.ndarray]:
    """Codes for two same-type columns drawn from ONE shared code space —
    the join-key currency (left probe codes comparable to right build)."""
    if a.dtype.is_string or b.dtype.is_string:
        ca, cb = encode_key(a), encode_key(b)
        ua, ub = a._key_uniq, b._key_uniq
        if ua is not None and ua is ub:
            # Same coding space (broadcast build reused across probe
            # partitions, self-join): codes compare directly.
            return ca, cb
        if ua is not None and ub is not None:
            remaps, _ = _merge_string_spaces([ua, ub])
            return remaps[0][ca], remaps[1][cb]
        codes_l, _ = _string_codes([a, b])
        return codes_l[0], codes_l[1]
    # Mixed int/float key pair: python equality said 1 == 1.0, so both
    # sides encode through the float domain.
    ff = a.dtype.is_floating != b.dtype.is_floating
    if not ff:
        # Fixed-width codes are value-derived (globally comparable), so
        # the per-column memo already holds the shared-space answer.
        return encode_key(a), encode_key(b)
    return _fixed_codes(a, force_float=ff), _fixed_codes(b, force_float=ff)


def encode_sort_key(col: "HostColumn") -> np.ndarray:
    """Codes matching the DEVICE sort order exactly. encode_key is the
    join/group EQUALITY currency, so it canonicalizes ``-0.0`` to
    ``0.0``; SQL ordering (kernels._orderable_u32_words) keeps the IEEE
    total order's distinct zeros (``-0.0 < 0.0``). Everything else —
    NaN canonical and greatest, nulls code 0 — is shared."""
    if col.dtype.is_floating:
        arr = np.asarray(col.data)
        val = np.asarray(col.validity, np.bool_)
        f = arr.astype(np.float64)
        bits = f.view(np.int64)
        bits = np.where(np.isnan(f), _NAN_CANON, bits)
        bits = np.where(bits >= 0, bits,
                        bits ^ np.int64(0x7FFFFFFFFFFFFFFF))
        return np.where(val, bits, np.int64(0))
    return encode_key(col)


def stable_code_argsort(codes: np.ndarray) -> np.ndarray:
    """Stable argsort of int64 key codes. NumPy's stable sort on ints is
    a full 8-pass LSD radix regardless of value range; when range*n fits
    below 2**62, compositing the row index into the key makes every key
    distinct, so the default introsort returns the *identical* stable
    order ~4x faster on bounded codes (join keys, group codes)."""
    n = len(codes)
    if n > 1:
        cmin = int(codes.min())
        crange = int(codes.max()) - cmin + 1
        if crange * n < (1 << 62):
            comp = ((codes - np.int64(cmin)) * np.int64(n)
                    + np.arange(n, dtype=np.int64))
            return np.argsort(comp)
    return np.argsort(codes, kind="stable")


def _merge_string_spaces(uniqs: Sequence[np.ndarray]
                         ) -> Tuple[List[np.ndarray], np.ndarray]:
    """Merge string coding spaces over their DISTINCT records.

    Each space is a sorted (d, w) matrix of unique byte records (payload
    zero-padded to w-4, then a 4-byte big-endian length suffix). Returns
    ``(remaps, merged)``: ``remaps[i][old_code]`` is the merged-space
    code (index 0 stays 0, the null code), and ``merged`` is the joint
    unique-records matrix for further propagation."""
    pw = max(u.shape[1] for u in uniqs) - 4
    recs = []
    for u in uniqs:
        w = u.shape[1] - 4
        if w < pw:
            u = np.concatenate(
                [u[:, :w], np.zeros((len(u), pw - w), np.uint8),
                 u[:, w:]], axis=1)
        recs.append(u)
    allu = np.ascontiguousarray(np.concatenate(recs, axis=0))
    if not len(allu):
        return [np.zeros(1, np.int64) for _ in uniqs], allu
    inv, merged = _rank_byte_rows(allu)
    remaps, off = [], 0
    for u in uniqs:
        r = np.zeros(len(u) + 1, np.int64)
        r[1:] = inv[off:off + len(u)] + 1
        remaps.append(r)
        off += len(u)
    return remaps, merged


def _fixed_codes(col: "HostColumn",
                 force_float: bool = False) -> np.ndarray:
    arr = np.asarray(col.data)
    val = np.asarray(col.validity, np.bool_)
    if arr.dtype.kind == "f" or force_float:
        f = arr.astype(np.float64) + 0.0          # kill -0.0
        bits = f.view(np.int64)
        bits = np.where(np.isnan(f), _NAN_CANON, bits)
        # Sign-flip encode: total order over the reals with NaN greatest.
        bits = np.where(bits >= 0, bits,
                        bits ^ np.int64(0x7FFFFFFFFFFFFFFF))
        return np.where(val, bits, np.int64(0))
    codes = arr.astype(np.int64, copy=False)
    return np.where(val, codes, np.int64(0))


def _string_codes(cols: Sequence["HostColumn"]
                  ) -> Tuple[List[np.ndarray], np.ndarray]:
    """Jointly factorize string columns into lexicographic rank codes;
    returns ``(codes_per_col, unique_records)`` — the second element is
    the coding space the ranks index into (see _merge_string_spaces).

    Rows become zero-padded byte records with a big-endian length suffix
    (so a string with trailing NULs cannot collide with its shorter
    prefix), viewed as void scalars and ranked by one np.unique — the
    padded-byte order with length tiebreak IS bytewise lexicographic
    order, so the ranks are both order-preserving and equality-exact."""
    mats, lens_l, vals_l = [], [], []
    for c in cols:
        m, lens = strings_to_matrix(c)
        val = np.asarray(c.validity, np.bool_)
        # Zero payload past each length and under nulls: only the first
        # ``len`` bytes are contractual, the rest may be device garbage.
        live = (np.arange(m.shape[1]) < lens[:, None]) & val[:, None]
        mats.append(np.where(live, m, np.uint8(0)))
        lens_l.append(np.where(val, lens, 0).astype(np.int64))
        vals_l.append(val)
    w = max((m.shape[1] for m in mats), default=1)
    recs = []
    for m, lens in zip(mats, lens_l):
        if m.shape[1] < w:
            m = np.pad(m, ((0, 0), (0, w - m.shape[1])))
        rec = np.concatenate(
            [m, lens.astype(">u4").view(np.uint8).reshape(len(lens), 4)],
            axis=1)
        recs.append(rec)
    allm = np.ascontiguousarray(np.concatenate(recs, axis=0))
    uniq = np.zeros((0, allm.shape[1] if allm.ndim == 2 else w + 4),
                    np.uint8)
    if not allm.shape[0]:
        return [np.zeros(0, np.int64) for _ in cols], uniq
    # Rank only the VALID rows: null rows take code 0 regardless, and
    # grouping-set expansion feeds whole null-projected key planes here —
    # keeping them out of the unique sort is up to a set-count-fold win.
    validall = np.concatenate(vals_l) if len(vals_l) > 1 else \
        np.asarray(vals_l[0], np.bool_)
    inv = np.zeros(allm.shape[0], np.int64)
    sel = np.flatnonzero(validall)
    if len(sel):
        sub = allm[sel] if len(sel) < allm.shape[0] else allm
        ranks, uniq = _rank_byte_rows(sub)
        inv[sel] = ranks + 1                      # reserve 0 for nulls
    out, off = [], 0
    for c, val in zip(cols, vals_l):
        n = c.num_rows
        out.append(inv[off:off + n])
        off += n
    return out, uniq


def _rank_byte_rows(rows: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Dense lexicographic rank of (n, w) uint8 rows; returns
    ``(ranks, unique_rows)`` with unique_rows sorted (so ranks index
    into it).

    Narrow keys pack into a couple of big-endian uint64 words and rank
    through native-integer lexsort passes (the zero padding added by the
    caller makes word order agree with byte order); wide keys stay on
    ``np.unique`` over a void view — one memcmp quicksort beats six
    full stable argsort passes."""
    n, w = rows.shape
    w8 = -(-w // 8) * 8
    if w8 > 24:
        voided = np.ascontiguousarray(rows) \
            .view(np.dtype((np.void, w))).ravel()
        u, inv = np.unique(voided, return_inverse=True)
        return (inv.astype(np.int64),
                np.ascontiguousarray(u).view(np.uint8).reshape(-1, w))
    orig = rows
    if w8 != w:
        rows = np.pad(rows, ((0, 0), (0, w8 - w)))
    words = np.ascontiguousarray(rows).view(">u8").astype(np.uint64)
    planes = tuple(words[:, j] for j in range(words.shape[1] - 1, -1, -1))
    order = planes[-1].argsort(kind="stable") if len(planes) == 1 \
        else np.lexsort(planes)
    sw = words[order]
    newg = np.empty(n, np.bool_)
    newg[0] = True
    np.any(sw[1:] != sw[:-1], axis=1, out=newg[1:])
    inv = np.empty(n, np.int64)
    inv[order] = np.cumsum(newg) - 1
    return inv, np.ascontiguousarray(orig[order[newg]])


def strings_to_matrix(col: "HostColumn") -> Tuple[np.ndarray, np.ndarray]:
    """Host string column -> ((n, w) uint8 byte matrix, (n,) int32 lengths).

    The single shared bridge between host object-array strings and the dense
    device layout; used by every host-path string kernel and by the
    host->device transition. ``None`` entries (permitted null encoding per
    HostColumn's contract) become empty strings.
    """
    if col.str_matrix is not None:
        return col.str_matrix, col.str_lengths
    n = col.num_rows
    vals = [b"" if b is None else bytes(b) for b in col.data]
    if not n:
        return np.zeros((0, 1), np.uint8), np.zeros(0, np.int32)
    # Vectorized: one b"".join + frombuffer, then a single fancy scatter
    # into the (n, w) matrix — the per-row frombuffer loop this replaces
    # was a top-3 host-span in the forced-host q3 profile.
    lens = np.fromiter(map(len, vals), dtype=np.int64, count=n)
    w = max(int(lens.max()), 1)
    m = np.zeros((n, w), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        flat = np.frombuffer(b"".join(vals), dtype=np.uint8)
        rows = np.repeat(np.arange(n, dtype=np.int64), lens)
        starts = np.cumsum(lens) - lens
        pos = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
        m[rows, pos] = flat
    return m, lens.astype(np.int32)


def matrix_to_strings(data: np.ndarray, lengths: np.ndarray,
                      validity: np.ndarray) -> "HostColumn":
    """Inverse of strings_to_matrix (nulls become empty bytes). The object
    array stays lazy: the matrix IS the column until a host kernel asks."""
    from spark_rapids_tpu.columnar import dtypes as _dt
    validity = np.asarray(validity, np.bool_)
    return HostColumn(_dt.STRING, None, validity,
                      str_matrix=np.asarray(data),
                      str_lengths=np.asarray(lengths, np.int32))


@dataclasses.dataclass
class StringMatrixView:
    """A host string column viewed in the dense device layout: byte matrix +
    lengths + validity, carrying its dtype so kernels that branch on
    ``dtype.is_string`` (blend/repad) work on it. The one shared adapter for
    every host-path string kernel."""

    dtype: "DataType"
    data: np.ndarray          # (n, w) uint8
    lengths: np.ndarray       # (n,) int32
    validity: np.ndarray      # (n,) bool

    @classmethod
    def of(cls, col: "HostColumn") -> "StringMatrixView":
        m, lens = strings_to_matrix(col)
        return cls(col.dtype, m, lens, col.validity)


def concat_host_batches(hbs: Sequence["HostBatch"]) -> "HostBatch":
    """Row-concatenate host batches (vectorized; string columns merge at
    the byte-matrix level so no object arrays materialize)."""
    assert hbs, "concat of zero host batches"
    if len(hbs) == 1:
        return hbs[0]
    cols = []
    for ci, c0 in enumerate(hbs[0].columns):
        members = [hb.columns[ci] for hb in hbs]
        val = np.concatenate([m.validity for m in members])
        if c0.dtype.is_string:
            mats = [strings_to_matrix(m) for m in members]
            w = max(mm.shape[1] for mm, _ in mats)
            mat = np.zeros((len(val), w), np.uint8)
            lens = np.concatenate([l for _, l in mats]).astype(np.int32)
            off = 0
            for mm, _ in mats:
                mat[off:off + mm.shape[0], :mm.shape[1]] = mm
                off += mm.shape[0]
            out = HostColumn(c0.dtype, None, val,
                             str_matrix=mat, str_lengths=lens)
            # Key-code propagation: pieces already coded in ONE shared
            # space concatenate codes too (sort/window over shuffle
            # output re-encode nothing).
            if all(m._key_codes is not None for m in members) and \
                    len({id(m._key_uniq) for m in members}) == 1 and \
                    members[0]._key_uniq is not None:
                out._key_codes = np.concatenate(
                    [m._key_codes for m in members])
                out._key_uniq = members[0]._key_uniq
            cols.append(out)
        else:
            cols.append(HostColumn(
                c0.dtype, np.concatenate([m.data for m in members]), val))
    return HostBatch(hbs[0].names, cols)


# ---------------------------------------------------------------------------
# Transitions (host -> device -> host)
# ---------------------------------------------------------------------------

def host_to_device(batch: HostBatch, capacity: Optional[int] = None,
                   string_widths: Optional[dict] = None) -> DeviceBatch:
    """Upload a host batch into a fresh fixed-capacity device batch.

    Ref: HostColumnarToGpu.scala / GpuRowToColumnarExec.scala. The upload
    goes through the wire codec (columnar/wire.py): narrow lossless wire
    dtypes + packed/absent validity, one device_put, and a jitted
    on-device widen back to the logical layout — the transfer link, not
    HBM, is the scarce resource.
    """
    from spark_rapids_tpu.columnar import wire
    return wire.upload(batch, capacity, string_widths)


# Batches whose device footprint exceeds MIN_SHRINK_BYTES get shrunk to
# their live-row bucket before download: the row-count sync (~1 round
# trip) buys back the padding bytes, which dominate on a slow link.


def download_batches(batches: Sequence[DeviceBatch],
                     names: Optional[Sequence[str]] = None
                     ) -> List[HostBatch]:
    """Download many device batches with the minimum number of round trips.

    Ref: GpuColumnarToRowExec.scala — the single place results leave HBM.
    Two-phase on purpose (SURVEY §7 sizes-then-data): phase 1 pulls every
    unknown row count in ONE ``jax.device_get`` and shrinks padded batches
    to their live bucket; phase 2 fetches all remaining buffers in ONE
    more ``device_get`` so the transfers pipeline. On a tunneled device
    each extra sync is a full network round trip, so per-batch/per-buffer
    loops cost O(batches*columns) round trips while this costs two.
    """
    import jax
    from spark_rapids_tpu.columnar.batch import shrink_all
    # LARGE batches shrink first (the row-count sync buys back padding
    # bytes on the link); small ones — selection vectors included — ship
    # as-is with their row mask and filter on the HOST, which costs no
    # device round trip and no compaction gather at all.
    from spark_rapids_tpu.columnar.batch import MIN_SHRINK_BYTES
    batches, _ = shrink_all(batches, min_bytes=MIN_SHRINK_BYTES)
    leaves: List = []
    for b in batches:
        leaves.append(b.num_rows)
        leaves.append(b.sel if b.sel is not None else None)
        for c in b.columns:
            leaves.append(c.data)
            leaves.append(c.validity)
            if c.dtype.is_string:
                leaves.append(c.lengths)
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory.oom import retry_on_oom

    def _fetch():
        # Named injection site + OOM ladder around the one batched
        # device_get every result takes (the download dispatch funnel).
        faults.fault_point("download")
        return jax.device_get([x for x in leaves if x is not None])

    fetched = retry_on_oom(_fetch)
    it = iter(fetched)
    out = []
    for b in batches:
        n = int(next(it))
        keep = None
        if b.sel is not None:
            keep = np.asarray(next(it))[:n]
        cols = []
        for c in b.columns:
            data_h = np.asarray(next(it))[:n]
            validity = np.asarray(next(it))[:n]
            lengths = None
            if c.dtype.is_string:
                lengths = np.asarray(next(it))[:n]
            if keep is not None:
                data_h, validity = data_h[keep], validity[keep]
                if lengths is not None:
                    lengths = lengths[keep]
            if c.dtype.is_string:
                cols.append(matrix_to_strings(data_h, lengths, validity))
            else:
                data = data_h.copy()
                data[~validity] = np.zeros(1, c.dtype.np_dtype)
                cols.append(HostColumn(c.dtype, data, validity))
        if names is None:
            batch_names = tuple(f"c{i}" for i in range(b.num_columns))
        else:
            batch_names = tuple(names)
        out.append(HostBatch(batch_names, cols))
    return out


def device_to_host(batch: DeviceBatch,
                   names: Optional[Sequence[str]] = None) -> HostBatch:
    """Download one device batch, trimming padding rows."""
    return download_batches([batch], names)[0]
