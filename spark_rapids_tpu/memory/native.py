"""ctypes bindings for the native spill store (native/spill_store.cpp).

Compiles the C++ on first use (g++ is in the image; pybind11 is not, so
the binding is a plain C ABI over ctypes). Falls back to a pure-python
file-backed store when no compiler is available, keeping the engine
functional everywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "spill_store.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libspillstore.so")

_lock = threading.Lock()
_lib = None
_tried = False


def compile_and_load(src_path: str, so_name: str):
    """Shared compile-on-first-use loader for the repo's native sources
    (g++ is in the image; pybind11 is not, so bindings are a plain C ABI
    over ctypes). Rebuilds when the source is newer than the .so; returns
    the CDLL or None when no toolchain is available."""
    so_path = os.path.join(_BUILD_DIR, so_name)
    try:
        if not os.path.exists(so_path) or \
                os.path.getmtime(so_path) < os.path.getmtime(src_path):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", so_path, src_path],
                check=True, capture_output=True, timeout=120)
        return ctypes.CDLL(so_path)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None


def load():
    """Load (compiling if needed) the spill-store library; None on
    failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lib = compile_and_load(_SRC, "libspillstore.so")
        if lib is None:
            return None
        lib.spill_store_create.restype = ctypes.c_void_p
        lib.spill_store_create.argtypes = [ctypes.c_char_p]
        lib.spill_store_write.restype = ctypes.c_int64
        lib.spill_store_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.spill_store_read.restype = ctypes.c_int64
        lib.spill_store_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.spill_store_block_size.restype = ctypes.c_int64
        lib.spill_store_block_size.argtypes = [
            ctypes.c_void_p, ctypes.c_int64]
        lib.spill_store_free.restype = ctypes.c_int
        lib.spill_store_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.spill_store_allocated_bytes.restype = ctypes.c_uint64
        lib.spill_store_allocated_bytes.argtypes = [ctypes.c_void_p]
        lib.spill_store_file_bytes.restype = ctypes.c_uint64
        lib.spill_store_file_bytes.argtypes = [ctypes.c_void_p]
        lib.spill_store_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeSpillFile:
    """One spill file with block ids (native path)."""

    def __init__(self, directory: str):
        lib = load()
        if lib is None:
            raise RuntimeError("native spill store unavailable")
        self._lib = lib
        os.makedirs(directory, exist_ok=True)
        self._h = lib.spill_store_create(directory.encode())
        if not self._h:
            raise OSError(f"cannot create spill file in {directory}")

    def write(self, data: bytes) -> int:
        bid = self._lib.spill_store_write(self._h, data, len(data))
        if bid < 0:
            raise OSError(f"spill write failed: errno {-bid}")
        return bid

    def read(self, block_id: int) -> bytes:
        size = self._lib.spill_store_block_size(self._h, block_id)
        if size < 0:
            raise KeyError(block_id)
        buf = ctypes.create_string_buffer(size)
        n = self._lib.spill_store_read(self._h, block_id, buf, size)
        if n < 0:
            raise OSError(f"spill read failed: errno {-n}")
        return buf.raw[:n]

    def free(self, block_id: int):
        self._lib.spill_store_free(self._h, block_id)

    @property
    def allocated_bytes(self) -> int:
        return self._lib.spill_store_allocated_bytes(self._h)

    @property
    def file_bytes(self) -> int:
        return self._lib.spill_store_file_bytes(self._h)

    def close(self):
        if self._h:
            self._lib.spill_store_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class PySpillFile:
    """Pure-python fallback with the same block-id interface."""

    def __init__(self, directory: str):
        import tempfile
        os.makedirs(directory, exist_ok=True)
        self._f = tempfile.TemporaryFile(dir=directory)
        self._blocks = {}
        self._next = 0
        self._end = 0
        self._live = 0
        self._lock = threading.Lock()

    def write(self, data: bytes) -> int:
        with self._lock:
            off = self._end
            self._f.seek(off)
            self._f.write(data)
            self._end += len(data)
            bid = self._next
            self._next += 1
            self._blocks[bid] = (off, len(data))
            self._live += len(data)
            return bid

    def read(self, block_id: int) -> bytes:
        with self._lock:
            off, size = self._blocks[block_id]
            self._f.seek(off)
            return self._f.read(size)

    def free(self, block_id: int):
        with self._lock:
            blk = self._blocks.pop(block_id, None)
            if blk:
                self._live -= blk[1]

    @property
    def allocated_bytes(self) -> int:
        return self._live

    @property
    def file_bytes(self) -> int:
        return self._end

    def close(self):
        self._f.close()


def open_spill_file(directory: str):
    """Native store when compilable, python fallback otherwise."""
    try:
        return NativeSpillFile(directory)
    except (RuntimeError, OSError):
        return PySpillFile(directory)
