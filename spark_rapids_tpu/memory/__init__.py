"""Memory management: tiered device/host/disk stores, spillable batches,
task semaphore (SURVEY.md §2.2)."""

from spark_rapids_tpu.memory.stores import (    # noqa: F401
    PRIORITY_ACTIVE_INPUT, PRIORITY_DEFAULT, PRIORITY_SHUFFLE_OUTPUT,
    BufferCatalog, SpillableBatch, StorageTier, TpuSemaphore)
