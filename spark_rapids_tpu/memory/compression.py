"""Compression codec SPI for spilled blobs and shuffle payloads.

TPU-native analog of the reference's ``TableCompressionCodec`` SPI
(sql-plugin/.../TableCompressionCodec.scala:41,107-128 — codec registry +
``spark.rapids.shuffle.compression.codec``; its GPU implementation is
nvcomp LZ4, NvcompLZ4CompressionCodec.scala). The TPU has no byte-oriented
decompressor kernel, so the codec runs where the bytes actually live: on
the host, in native code (native/compress.cpp, a self-contained LZ4
block-format implementation), applied by the spill framework's host->disk
writes and available to any serialized payload path.

Codecs:
- ``lz4``  — native LZ4 block format (ctypes). When no toolchain is
  available a python ``zlib`` level-1 stand-in is returned instead; it
  identifies itself via ``codec.name == "lz4-zlib-fallback"``.
- ``copy`` — framing without byte transform (the reference's test codec)
- ``none`` — disable compression entirely
"""

from __future__ import annotations

import ctypes
import os
import threading
import zlib
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "compress.cpp")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        from spark_rapids_tpu.memory.native import compile_and_load
        lib = compile_and_load(_SRC, "libsrtcompress.so")
        if lib is None:
            return None
        lib.lz4_compress_bound.restype = ctypes.c_int64
        lib.lz4_compress_bound.argtypes = [ctypes.c_int64]
        lib.lz4_compress.restype = ctypes.c_int64
        lib.lz4_compress.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_char_p, ctypes.c_int64]
        lib.lz4_decompress.restype = ctypes.c_int64
        lib.lz4_decompress.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
        return _lib


class CompressionCodec:
    """One codec: name + compress/decompress over byte blobs."""

    name: str = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        raise NotImplementedError


class CopyCodec(CompressionCodec):
    """Framing without a byte transform (the reference's copy codec used
    by tests, TableCompressionCodec.scala:107)."""

    name = "copy"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        if len(data) != uncompressed_size:
            raise OSError(
                f"copy codec blob is {len(data)} of "
                f"{uncompressed_size} bytes")
        return data


class Lz4Codec(CompressionCodec):
    name = "lz4"

    def __init__(self, lib):
        self._lib = lib

    def compress(self, data: bytes) -> bytes:
        n = len(data)
        bound = self._lib.lz4_compress_bound(n)
        out = ctypes.create_string_buffer(bound)
        sz = self._lib.lz4_compress(data, n, out, bound)
        if sz < 0:
            raise OSError("lz4 compression failed")
        return out.raw[:sz]

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        out = ctypes.create_string_buffer(max(uncompressed_size, 1))
        sz = self._lib.lz4_decompress(data, len(data), out,
                                      uncompressed_size)
        if sz != uncompressed_size:
            raise OSError(
                f"lz4 decompression produced {sz} of "
                f"{uncompressed_size} bytes")
        return out.raw[:uncompressed_size]


class ZlibFallbackCodec(CompressionCodec):
    """Pure-python stand-in when the native library can't build; level 1
    keeps the CPU cost near LZ4's class."""

    name = "lz4-zlib-fallback"

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, 1)

    def decompress(self, data: bytes, uncompressed_size: int) -> bytes:
        out = zlib.decompress(data)
        if len(out) != uncompressed_size:
            raise OSError(
                f"zlib fallback produced {len(out)} of "
                f"{uncompressed_size} bytes")
        return out


def get_codec(name: str) -> Optional[CompressionCodec]:
    """Codec registry (TableCompressionCodec.getCodec analog).
    Returns None for 'none'/'' (compression disabled)."""
    name = (name or "none").lower()
    if name in ("none", ""):
        return None
    if name == "copy":
        return CopyCodec()
    if name == "lz4":
        lib = _load()
        if lib is not None:
            return Lz4Codec(lib)
        return ZlibFallbackCodec()
    raise ValueError(f"unknown compression codec {name!r}")
