"""OOM -> spill -> retry at dispatch boundaries
(DeviceMemoryEventHandler.scala:42-69 re-imagined for XLA).

The reference installs a cuDF alloc-failure callback that spills the
RapidsBufferCatalog and lets RMM retry the SAME allocation. XLA exposes no
allocator hook, so the equivalent lives at the dispatch sites instead:
the handful of funnels that issue large device allocations (uploads,
concats/shrinks, downloads) run through :func:`retry_on_oom`, which
catches the backend's RESOURCE_EXHAUSTED, spills every spillable catalog
buffer to the host tier, and retries the dispatch exactly once. The
wrapped operations are pure batch->batch (no consumed iterator state), so
the retry is safe.

The active catalog is registered per-collect (ops/base.py) — dispatch
sites deep in the kernel layer never thread an ExecContext through.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

T = TypeVar("T")

_local = threading.local()


def set_active_catalog(catalog) -> None:
    _local.catalog = catalog


def get_active_catalog():
    return getattr(_local, "catalog", None)


def is_oom_error(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    # Deliberately narrow: a spurious match triggers a full
    # spill-everything pass plus a duplicate dispatch of the failing op.
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s)


def retry_on_oom(fn: Callable[..., T], *args, **kwargs) -> T:
    """Run ``fn``; on a device OOM, spill the active catalog and retry
    once. Anything else (or OOM with nothing spillable) propagates."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:                  # jaxlib.XlaRuntimeError etc.
        if not is_oom_error(e):
            raise
        catalog = get_active_catalog()
        if catalog is None or catalog.handle_oom() == 0:
            raise
        return fn(*args, **kwargs)


def is_transient_error(e: BaseException) -> bool:
    """Backend/tunnel failures worth one whole-query retry (SURVEY §5.3
    failure detection: the reference leans on Spark task retry; this
    engine owns the retry itself). Deliberately narrow — deterministic
    errors must not run twice."""
    s = f"{type(e).__name__}: {e}"
    return any(marker in s for marker in (
        "UNAVAILABLE", "DEADLINE_EXCEEDED", "connection reset",
        "Connection reset", "Socket closed", "ABORTED",
        "failed to connect", "stream terminated"))
