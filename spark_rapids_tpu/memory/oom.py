"""OOM -> tiered recovery at dispatch boundaries
(DeviceMemoryEventHandler.scala:42-69 re-imagined for XLA).

The reference installs a cuDF alloc-failure callback that spills the
RapidsBufferCatalog and lets RMM retry the SAME allocation. XLA exposes no
allocator hook, so the equivalent lives at the dispatch sites instead:
the funnels that issue large device allocations (uploads, concats/shrinks,
cached-kernel dispatches, downloads) run through :func:`retry_on_oom`.

Recovery is a bounded ESCALATION LADDER, not a single retry — each rung
frees (or will free) more memory than the last, and the dispatch retries
after every rung:

1. ``spill-some``: spill lowest-priority catalog buffers until about half
   the registered device bytes are freed (the cheap rung — most OOMs are
   transient headroom misses).
2. ``spill-all``: spill EVERY spillable device buffer (the reference's
   alloc-failure callback behavior).
3. ``evict-neighbors``: under the multi-query scheduler
   (parallel/scheduler.py) with concurrent queries in flight, spill the
   OTHER active queries' device buffers to host — the offending query's
   own buffers always go first (rungs 1-2), so isolation holds until
   the device is truly full (``crossQueryEvictions``).
4. ``shrink``: halve the process-wide degraded batch target
   (:func:`effective_batch_target`) so every SUBSEQUENT coalesce/serve
   dispatch issues smaller batches, then retry once more.

If the ladder is exhausted the dispatch raises :class:`OomRetryExhausted`
— whose message deliberately does NOT carry the OOM markers, so nested
``retry_on_oom`` frames propagate it instead of re-running the ladder.
The operator layer (ops/base.py ``execute_device_recovering``) catches it
and demotes in two further rungs: first the operator's ON-DEVICE degraded
mode (``Exec._grace_retry`` — the hash join's spill-partitioned grace
path, ops/join.py, which keeps a build side far past the device budget on
the accelerator), and only then host-engine degradation of the operator
subtree — mirroring the reference's always-available CPU fallback, but
one rung later than it.

The wrapped operations are pure batch->batch (no consumed iterator
state), so every retry is safe. The active catalog is registered
per-collect (ops/base.py) — dispatch sites deep in the kernel layer never
thread an ExecContext through. Every rung records through
spark_rapids_tpu.faults' recovery counters (``retriesAttempted``,
``spillEscalations``...), which is also how tests/test_chaos.py proves
the ladder actually fires.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Callable, List, Optional, TypeVar

from spark_rapids_tpu import faults

_LOG = logging.getLogger("spark_rapids_tpu.memory")

T = TypeVar("T")

_local = threading.local()


def set_active_catalog(catalog) -> None:
    _local.catalog = catalog


def get_active_catalog():
    return getattr(_local, "catalog", None)


class OomRetryExhausted(RuntimeError):
    """Device OOM persisted through the whole escalation ladder. The
    message carries NO OOM marker on purpose: an enclosing retry_on_oom
    must propagate this (its own ladder would just repeat the failed
    rungs), and the operator layer host-degrades on it instead."""

    def __init__(self, original: BaseException, rungs: List[str]):
        super().__init__(
            f"device memory exhausted after escalation ladder "
            f"{rungs!r}; original: {type(original).__name__}")
        self.original = original
        self.rungs = rungs


def is_oom_error(e: BaseException) -> bool:
    if isinstance(e, OomRetryExhausted):
        return False
    s = f"{type(e).__name__}: {e}"
    # Deliberately narrow: a spurious match triggers a full
    # spill-everything pass plus a duplicate dispatch of the failing op.
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s)


# -- degraded batch target (rung 3) -----------------------------------------

_MAX_DEGRADE_FACTOR = 8
_MIN_TARGET_ROWS = 1 << 12
_degrade_lock = threading.Lock()
_degrade_factor = 1

RUNG_SPILL_SOME = "spill-some"
RUNG_SPILL_ALL = "spill-all"
RUNG_EVICT_NEIGHBORS = "evict-neighbors"
RUNG_SHRINK = "shrink"

# Rung names of the LAST completed ladder, in firing order (introspection
# for tests proving the escalation discipline).
last_ladder: List[str] = []


def degrade_factor() -> int:
    return _degrade_factor


def effective_batch_target(target_rows: int) -> int:
    """The batchSizeRows target after OOM degradation: once the shrink
    rung has fired, every consumer that coalesces toward the target
    (aggregate input coalescing, exchange reduce-side serving) dispatches
    proportionally smaller batches until :func:`reset_degradation`."""
    return max(int(target_rows) // _degrade_factor, _MIN_TARGET_ROWS)


def shrink_batch_target() -> bool:
    """Halve the process-wide batch target (bounded). True if the factor
    actually moved."""
    global _degrade_factor
    with _degrade_lock:
        if _degrade_factor >= _MAX_DEGRADE_FACTOR:
            return False
        _degrade_factor *= 2
        _LOG.warning("OOM escalation: batch target degraded to 1/%d",
                     _degrade_factor)
        return True


def reset_degradation() -> None:
    global _degrade_factor
    with _degrade_lock:
        _degrade_factor = 1


# -- the ladder ---------------------------------------------------------------

def _evict_neighbor_queries() -> int:
    """Cross-query eviction rung: after the offending query has spilled
    everything IT owns (the first two rungs walk its own catalog), ask
    the QueryManager to spill the other active queries' buffers to host
    — isolation means the offender pays first, not that neighbors are
    untouchable while the device is truly full. No-op (0 bytes) outside
    a managed query or with no concurrent neighbors."""
    tok = faults.get_query_token()
    if tok is None:
        return 0
    from spark_rapids_tpu.parallel import scheduler
    mgr = scheduler.get_query_manager()
    return mgr.evict_neighbors(tok.query_id)


def retry_on_oom(fn: Callable[..., T], *args, **kwargs) -> T:
    """Run ``fn``; on a device OOM walk the spill-some -> spill-all ->
    evict-neighbors -> shrink escalation ladder, retrying the dispatch
    after each rung (neighbor eviction only under the QueryManager with
    concurrent queries — the offender's own buffers always go first).
    Anything else propagates; a ladder that never frees or changes
    anything re-raises immediately (the retry would just fail again)."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:                  # jaxlib.XlaRuntimeError etc.
        if not is_oom_error(e):
            raise
        first = e
    catalog = get_active_catalog()
    rungs: List[str] = []
    last = first

    def attempt():
        faults.record("retriesAttempted")
        return fn(*args, **kwargs)

    for rung in (RUNG_SPILL_SOME, RUNG_SPILL_ALL, RUNG_EVICT_NEIGHBORS,
                 RUNG_SHRINK):
        if rung == RUNG_SPILL_SOME:
            acted = catalog is not None and catalog.spill_some() > 0
        elif rung == RUNG_SPILL_ALL:
            acted = catalog is not None and catalog.handle_oom() > 0
        elif rung == RUNG_EVICT_NEIGHBORS:
            acted = _evict_neighbor_queries() > 0
        else:
            acted = shrink_batch_target()
        if not acted:
            # Nothing changed at this rung; the identical dispatch would
            # fail the same way — escalate without burning a retry.
            continue
        rungs.append(rung)
        last_ladder[:] = rungs
        faults.record("spillEscalations")
        from spark_rapids_tpu import monitoring
        monitoring.instant("oom-rung", "recovery", args={"rung": rung})
        _LOG.warning("device OOM: escalation rung %r (of %r), retrying "
                     "dispatch: %s", rung, rungs, last)
        try:
            return attempt()
        except Exception as e2:
            if not is_oom_error(e2):
                raise
            last = e2
    last_ladder[:] = rungs
    if not rungs:
        # No catalog / nothing spillable / already fully degraded:
        # preserve the original error verbatim (historical contract).
        raise last
    raise OomRetryExhausted(last, rungs)


# -- transient failures -------------------------------------------------------

def is_transient_error(e: BaseException) -> bool:
    """Backend/tunnel failures worth retrying the whole query (SURVEY
    §5.3 failure detection: the reference leans on Spark task retry; this
    engine owns the retry itself — with exponential backoff and a
    per-query budget, plan/planner.py). Deliberately narrow —
    deterministic errors must not run twice."""
    s = f"{type(e).__name__}: {e}"
    return any(marker in s for marker in (
        "UNAVAILABLE", "DEADLINE_EXCEEDED", "connection reset",
        "Connection reset", "Socket closed", "ABORTED",
        "failed to connect", "stream terminated"))


def backoff_delay_ms(attempt: int, base_ms: int, max_ms: int,
                     seed: int = 0) -> float:
    """Exponential backoff with DETERMINISTIC jitter: attempt ``i``
    sleeps ``min(base * 2^i, max) * U(0.5, 1.0)`` where U comes from a
    PRNG seeded by (seed, attempt) — reproducible chaos runs stay
    reproducible down to their sleep schedule."""
    d = min(float(base_ms) * (2 ** int(attempt)), float(max_ms))
    jitter = random.Random(f"{seed}:backoff:{attempt}").uniform(0.5, 1.0)
    return d * jitter
