"""Tiered buffer stores: HBM -> host RAM -> disk (SURVEY.md §2.2 — the
RapidsBufferCatalog / Rapids{Device,Host,Disk}Store chain re-designed for
XLA's memory model).

RMM calls back on allocation failure (DeviceMemoryEventHandler.scala:42);
XLA will not, so the device tier is governed by a **watermark budget**: the
catalog tracks the bytes of every registered device batch against a budget
(HBM fraction config) and synchronously spills lowest-priority buffers when
an admission would cross it (the same synchronousSpill(targetSize) loop as
RapidsBufferStore.scala:39, driven by admission instead of a callback).

Spill priorities follow SpillPriorities.scala: shuffle outputs spill first,
actively-read input buffers last.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

_LOG = logging.getLogger("spark_rapids_tpu.memory")

import numpy as np

from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.columnar.host import (
    HostBatch, device_to_host, host_to_device)

# SpillPriorities.scala analogs: lower spills first.
PRIORITY_SHUFFLE_OUTPUT = 0
PRIORITY_DEFAULT = 50
# Broadcast singles are re-read by every probe partition: spill them
# after shuffle buckets and scratch, before actively-read inputs.
PRIORITY_BROADCAST = 75
PRIORITY_ACTIVE_INPUT = 100


class StorageTier:
    DEVICE = "device"
    HOST = "host"
    DISK = "disk"


def _batch_to_numpy(batch: DeviceBatch) -> Tuple[dict, list]:
    """Device batch -> (meta, list of numpy buffers) without trimming
    padding (exact image, so re-upload restores identical capacities)."""
    bufs = []
    cols_meta = []
    for c in batch.columns:
        entry = {"dtype": c.dtype.name, "string": c.dtype.is_string}
        bufs.append(np.asarray(c.data))
        bufs.append(np.asarray(c.validity))
        if c.lengths is not None:
            bufs.append(np.asarray(c.lengths))
            entry["has_lengths"] = True
        cols_meta.append(entry)
    meta = {"cols": cols_meta, "num_rows": int(batch.num_rows),
            "rows_hint": batch.rows_hint}
    if batch.sel is not None:
        bufs.append(np.asarray(batch.sel))
        meta["has_sel"] = True
    return meta, bufs


def _numpy_to_batch(meta: dict, bufs: list) -> DeviceBatch:
    import jax.numpy as jnp
    cols = []
    bi = 0
    for entry in meta["cols"]:
        t = dt.type_named(entry["dtype"])
        data = jnp.asarray(bufs[bi]); bi += 1
        validity = jnp.asarray(bufs[bi]); bi += 1
        lengths = None
        if entry.get("has_lengths"):
            lengths = jnp.asarray(bufs[bi]); bi += 1
        cols.append(DeviceColumn(t, data, validity, lengths))
    sel = jnp.asarray(bufs[bi]) if meta.get("has_sel") else None
    out = DeviceBatch(tuple(cols),
                      jnp.asarray(meta["num_rows"], jnp.int32), sel=sel)
    out.rows_hint = meta.get("rows_hint")
    return out


def _serialize_bufs(bufs: list) -> Tuple[bytes, list]:
    """Buffers -> one contiguous byte blob + shape/dtype directory."""
    directory = []
    parts = []
    for a in bufs:
        a = np.ascontiguousarray(a)
        raw = a.tobytes()
        directory.append({"dtype": a.dtype.str, "shape": a.shape,
                          "nbytes": len(raw)})
        parts.append(raw)
    return b"".join(parts), directory


def _deserialize_bufs(blob: bytes, directory: list) -> list:
    out = []
    off = 0
    for d in directory:
        n = d["nbytes"]
        arr = np.frombuffer(blob[off:off + n],
                            dtype=np.dtype(d["dtype"])).reshape(d["shape"])
        out.append(arr)
        off += n
    return out


# ---------------------------------------------------------------------------
# Self-describing shard wire format (the shuffle-transport SPI's at-rest
# representation, parallel/transport/): ONE CRC-framed blob per shard,
# meta + buffer directory as a JSON header followed by the contiguous
# buffer bytes. The numpy round trip is bit-exact, so any transport that
# moves these blobs (spool files today, a real DCN wire tomorrow)
# preserves bit-identical query results by construction.
# ---------------------------------------------------------------------------

def batch_to_shard_blob(batch: DeviceBatch) -> bytes:
    """DeviceBatch -> one CRC-framed, self-describing byte blob
    (``wire.frame_blob`` on the outside, so fetch detects corruption at
    the frame boundary)."""
    import json
    import struct

    from spark_rapids_tpu.columnar.wire import frame_blob
    meta, bufs = _batch_to_numpy(batch)
    blob, directory = _serialize_bufs(bufs)
    header = json.dumps(
        {"meta": meta,
         "directory": [{"dtype": d["dtype"],
                        "shape": list(d["shape"]),
                        "nbytes": d["nbytes"]} for d in directory]},
    ).encode("utf-8")
    return frame_blob(struct.pack("<I", len(header)) + header + blob)


def shard_blob_to_batch(framed: bytes) -> DeviceBatch:
    """Inverse of :func:`batch_to_shard_blob`. Raises
    ``WireCorruptionError`` on any frame/CRC mismatch — wrong bytes must
    never deserialize into wrong rows."""
    import json
    import struct

    from spark_rapids_tpu.columnar.wire import unframe_blob
    payload = unframe_blob(framed)
    (hlen,) = struct.unpack_from("<I", payload)
    header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    bufs = _deserialize_bufs(payload[4 + hlen:], header["directory"])
    return _numpy_to_batch(header["meta"], bufs)


@dataclasses.dataclass
class BufferEntry:
    buffer_id: int
    tier: str
    size_bytes: int
    priority: int
    # Owning query id (RapidsBufferCatalog's owner tagging): per-query
    # accounting, the leak report's attribution, and the proof that a
    # cancelled query's teardown freed everything it owned. None =
    # unmanaged (unit tests, direct Exec.collect).
    owner: Optional[int] = None
    # Exactly one of these is set, per tier:
    device_batch: Optional[DeviceBatch] = None
    host_meta: Optional[dict] = None
    host_bufs: Optional[list] = None
    disk_meta: Optional[dict] = None
    disk_directory: Optional[list] = None
    disk_block: Optional[int] = None


class BufferCatalog:
    """id -> buffer across tiers, with the device->host->disk spill chain
    (RapidsBufferCatalog.scala:128-142's singleton wiring)."""

    def __init__(self, device_budget_bytes: int = 1 << 34,
                 host_budget_bytes: int = 1 << 30,
                 spill_dir: str = "/tmp/spark_rapids_tpu_spill",
                 compression_codec: str = "none",
                 debug: bool = False,
                 owner: Optional[int] = None):
        from spark_rapids_tpu.memory.compression import get_codec
        from spark_rapids_tpu.memory.native import open_spill_file
        self.device_budget = device_budget_bytes
        self.host_budget = host_budget_bytes
        # Default owner tag for every buffer this catalog registers —
        # the admitting QueryManager's query id (catalogs are per-query,
        # so catalog owner == buffer owner unless a caller overrides).
        self.owner = owner
        self._entries: Dict[int, BufferEntry] = {}
        self._next_id = itertools.count()
        self._device_bytes = 0
        self._host_bytes = 0
        self._lock = threading.RLock()
        self._spill_file = open_spill_file(spill_dir)
        # Alloc/leak debug (spark.rapids.memory.gpu.debug analog): log
        # every buffer event and keep creation stacks for the close-time
        # leak report.
        self.debug = debug
        self._stacks: Dict[int, str] = {}
        # Disk-tier blobs compress through the codec SPI
        # (spark.rapids.shuffle.compression.codec; TableCompressionCodec
        # analog — see memory/compression.py).
        self._codec = get_codec(compression_codec)
        self.metrics = {"spill_to_host": 0, "spill_to_disk": 0,
                        "restore_from_host": 0, "restore_from_disk": 0,
                        "disk_bytes_raw": 0, "disk_bytes_stored": 0}

    # -- registration --------------------------------------------------------
    def add_batch(self, batch: DeviceBatch,
                  priority: int = PRIORITY_DEFAULT,
                  owner: Optional[int] = None) -> int:
        size = batch.device_size_bytes()
        with self._lock:
            self._ensure_device_room(size)
            bid = next(self._next_id)
            self._entries[bid] = BufferEntry(
                bid, StorageTier.DEVICE, size, priority,
                owner=owner if owner is not None else self.owner,
                device_batch=batch)
            self._device_bytes += size
            if self.debug:
                import traceback
                self._stacks[bid] = "".join(
                    traceback.format_stack(limit=8)[:-1])
                _LOG.info("catalog add id=%d size=%d device_bytes=%d",
                          bid, size, self._device_bytes)
            return bid

    def acquire_batch(self, buffer_id: int) -> DeviceBatch:
        """Materialize back on device (from whatever tier), re-admitting it
        under the budget (SpillableColumnarBatch.getColumnarBatch)."""
        with self._lock:
            e = self._entries[buffer_id]
            if e.tier == StorageTier.DEVICE:
                e.priority = PRIORITY_ACTIVE_INPUT
                return e.device_batch
            # Mark in-flight and detach the source tier's state BEFORE
            # _ensure_device_room: the cascaded device->host spill it can
            # trigger must never pick this entry as a host->disk victim
            # (double-decrement of _host_bytes + leaked disk block).
            e.priority = PRIORITY_ACTIVE_INPUT
            if e.tier == StorageTier.HOST:
                self.metrics["restore_from_host"] += 1
                meta, bufs = e.host_meta, e.host_bufs
                e.host_meta = e.host_bufs = None
                self._host_bytes -= e.size_bytes
                batch = _numpy_to_batch(meta, bufs)
            else:
                self.metrics["restore_from_disk"] += 1
                blob = self._read_disk_frame(e)
                if self._codec is not None:
                    blob = self._codec.decompress(
                        blob, e.disk_meta["raw_len"])
                bufs = _deserialize_bufs(blob, e.disk_directory)
                batch = _numpy_to_batch(e.disk_meta, bufs)
                self._spill_file.free(e.disk_block)
                e.disk_meta = e.disk_directory = e.disk_block = None
            self._ensure_device_room(e.size_bytes)
            e.tier = StorageTier.DEVICE
            e.device_batch = batch
            self._device_bytes += e.size_bytes
            return batch

    def release(self, buffer_id: int,
                priority: int = PRIORITY_DEFAULT):
        """Done reading: buffer becomes spillable again."""
        with self._lock:
            e = self._entries.get(buffer_id)
            if e is not None:
                e.priority = priority

    def remove(self, buffer_id: int):
        with self._lock:
            e = self._entries.pop(buffer_id, None)
            if e is None:
                return
            if self.debug:
                self._stacks.pop(buffer_id, None)
                _LOG.info("catalog remove id=%d size=%d", buffer_id,
                          e.size_bytes)
            if e.tier == StorageTier.DEVICE:
                self._device_bytes -= e.size_bytes
            elif e.tier == StorageTier.HOST:
                self._host_bytes -= e.size_bytes
            elif e.disk_block is not None:
                self._spill_file.free(e.disk_block)

    def _read_disk_frame(self, e: "BufferEntry") -> bytes:
        """Read + CRC-verify a spilled frame. A checksum mismatch (bit
        rot, torn read, injected corruption) re-reads ONCE — wrong data
        must never deserialize into wrong rows; persistent corruption
        fails loudly instead."""
        from spark_rapids_tpu.columnar.wire import (
            WireCorruptionError, unframe_blob)
        last: Optional[WireCorruptionError] = None
        for _ in range(2):
            faults.fault_point("spill.read")
            framed = self._spill_file.read(e.disk_block)
            framed = faults.corrupt_blob("wire", framed)
            try:
                return unframe_blob(framed)
            except WireCorruptionError as err:
                last = err
                faults.record("corruptionsDetected")
                self.metrics["corruption_detected"] = \
                    self.metrics.get("corruption_detected", 0) + 1
                _LOG.warning("spill frame checksum mismatch (buffer %d), "
                             "re-reading: %s", e.buffer_id, err)
        raise last

    # -- OOM recovery --------------------------------------------------------
    def spill_some(self, target_bytes: Optional[int] = None) -> int:
        """First escalation rung: spill lowest-priority device buffers
        until ~``target_bytes`` are freed (default: half the registered
        device bytes). Returns bytes freed (0 = nothing spillable)."""
        freed = 0
        with self._lock:
            if target_bytes is None:
                target_bytes = max(self._device_bytes // 2, 1)
            while freed < target_bytes:
                victim = self._pick_victim(StorageTier.DEVICE)
                if victim is None:
                    break
                freed += victim.size_bytes
                self._spill_device_to_host(victim)
        if freed:
            self.metrics["oom_spills"] = \
                self.metrics.get("oom_spills", 0) + 1
        return freed

    def handle_oom(self) -> int:
        """Real HBM allocation failure (not a budget watermark): spill
        EVERY spillable device buffer to host and report bytes freed
        (DeviceMemoryEventHandler.scala:42-69's alloc-failure callback,
        driven from the dispatch site instead of a cuDF hook). Returns 0
        when nothing was spillable — the caller's retry would just fail
        again, so it should re-raise."""
        freed = 0
        with self._lock:
            while True:
                victim = self._pick_victim(StorageTier.DEVICE)
                if victim is None:
                    break
                freed += victim.size_bytes
                self._spill_device_to_host(victim)
        if freed:
            self.metrics["oom_spills"] = \
                self.metrics.get("oom_spills", 0) + 1
        return freed

    # -- spilling ------------------------------------------------------------
    def _ensure_device_room(self, incoming: int):
        """synchronousSpill loop: evict lowest-priority device buffers until
        the incoming batch fits the budget."""
        while self._device_bytes + incoming > self.device_budget:
            victim = self._pick_victim(StorageTier.DEVICE)
            if victim is None:
                break   # nothing spillable; admit anyway (XLA may OOM)
            self._spill_device_to_host(victim)

    def _pick_victim(self, tier: str) -> Optional[BufferEntry]:
        best = None
        for e in self._entries.values():
            if e.tier != tier or e.priority >= PRIORITY_ACTIVE_INPUT:
                continue
            if best is None or e.priority < best.priority or \
                    (e.priority == best.priority and
                     e.buffer_id < best.buffer_id):
                best = e
        return best

    def _spill_device_to_host(self, e: BufferEntry):
        meta, bufs = _batch_to_numpy(e.device_batch)
        e.device_batch = None
        e.tier = StorageTier.HOST
        e.host_meta, e.host_bufs = meta, bufs
        self._device_bytes -= e.size_bytes
        self._host_bytes += e.size_bytes
        self.metrics["spill_to_host"] += 1
        # Cascade: host over budget -> push host victims to disk.
        while self._host_bytes > self.host_budget:
            victim = self._pick_victim(StorageTier.HOST)
            if victim is None:
                break
            self._spill_host_to_disk(victim)

    def _spill_host_to_disk(self, e: BufferEntry):
        from spark_rapids_tpu.columnar.wire import frame_blob
        faults.fault_point("spill.write")
        blob, directory = _serialize_bufs(e.host_bufs)
        raw_len = len(blob)
        if self._codec is not None:
            blob = self._codec.compress(blob)
        # CRC32-framed on disk: deserialize verifies the frame, so real
        # or injected corruption is DETECTED instead of decoding into
        # silently wrong rows (ISSUE 2 wire-integrity contract).
        block = self._spill_file.write(frame_blob(blob))
        e.disk_meta = dict(e.host_meta)
        e.disk_meta["raw_len"] = raw_len
        e.disk_directory = directory
        e.disk_block = block
        e.host_meta = e.host_bufs = None
        e.tier = StorageTier.DISK
        self._host_bytes -= e.size_bytes
        self.metrics["spill_to_disk"] += 1
        self.metrics["disk_bytes_raw"] += raw_len
        self.metrics["disk_bytes_stored"] += len(blob)

    # -- introspection -------------------------------------------------------
    def tier_of(self, buffer_id: int) -> str:
        with self._lock:
            return self._entries[buffer_id].tier

    def has(self, buffer_id: int) -> bool:
        """Whether the buffer is still registered (durable-stage-output
        liveness probe for the lineage recovery layer and tests)."""
        with self._lock:
            return buffer_id in self._entries

    @property
    def registered_count(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def device_bytes(self) -> int:
        return self._device_bytes

    @property
    def host_bytes(self) -> int:
        return self._host_bytes

    @property
    def disk_bytes(self) -> int:
        return self._spill_file.allocated_bytes

    def owned_bytes(self) -> Dict[Optional[int], int]:
        """Registered bytes per owner tag (any tier) — the per-query
        accounting view the scheduler's isolation tests assert on."""
        out: Dict[Optional[int], int] = {}
        with self._lock:
            for e in self._entries.values():
                out[e.owner] = out.get(e.owner, 0) + e.size_bytes
        return out

    def leak_report(self) -> List[Tuple[int, int, str]]:
        """Buffers still registered: (id, bytes, creation stack) — the
        MemoryCleaner leak-callstack analog. Stacks are recorded only in
        debug mode."""
        with self._lock:
            return [(e.buffer_id, e.size_bytes,
                     self._stacks.get(e.buffer_id, "<enable "
                                      "spark.rapids.memory.tpu.debug for "
                                      "the allocation stack>"))
                    for e in self._entries.values()]

    def close(self):
        leaks = self.leak_report()
        if leaks and self.debug:
            total = sum(b for _, b, _ in leaks)
            _LOG.warning("catalog closing with %d leaked buffers "
                         "(%d bytes):", len(leaks), total)
            for bid, size, stack in leaks:
                _LOG.warning("  leaked id=%d size=%d\n%s", bid, size,
                             stack)
        self._spill_file.close()


class SpillableBatch:
    """Operator-facing handle that re-materializes from whatever tier the
    batch is on (SpillableColumnarBatch.scala:27)."""

    def __init__(self, catalog: BufferCatalog, batch: DeviceBatch,
                 priority: int = PRIORITY_DEFAULT):
        self._catalog = catalog
        self._id = catalog.add_batch(batch, priority)
        self._closed = False
        # Host-known row capacity and registered byte size (static
        # shapes) — let consumers (out-of-core bucketing, grace joins)
        # group handles by size without any device sync.
        self.capacity = batch.capacity
        self.size_bytes = batch.device_size_bytes()

    def get(self) -> DeviceBatch:
        return self._catalog.acquire_batch(self._id)

    def release(self, priority: int = PRIORITY_DEFAULT):
        self._catalog.release(self._id, priority)

    def close(self):
        if not self._closed:
            self._catalog.remove(self._id)
            self._closed = True

    def __enter__(self):
        return self.get()

    def __exit__(self, *exc):
        self.release()
        return False


_GLOBAL_SEM: Optional["TpuSemaphore"] = None
_GLOBAL_SEM_LOCK = threading.Lock()

# Class-aware device preemption gate
# (spark.rapids.sql.scheduler.preemption.enabled): process-global like
# the metrics/wire regimes — last collect's conf wins. False keeps the
# acquire path byte-for-byte the flat class-blind semaphore.
_PREEMPT_ENABLED = False


def preemption_configure(conf) -> None:
    """Adopt this query's preemption gate setting (called from the
    dispatch funnel before the semaphore is touched)."""
    global _PREEMPT_ENABLED
    from spark_rapids_tpu import config as C
    _PREEMPT_ENABLED = bool(conf.get(C.PREEMPTION_ENABLED))


def preemption_enabled() -> bool:
    return _PREEMPT_ENABLED


def _class_rank(token) -> int:
    """The token's priority rank for the device gate (lower = better).
    Untagged/FIFO queries rank as the default class, so preemption
    only ever engages when somebody actually declared a class."""
    from spark_rapids_tpu.parallel.qos.policy import (CLASS_RANK,
                                                      DEFAULT_CLASS)
    cls = getattr(token, "qos_class", None) or DEFAULT_CLASS
    return CLASS_RANK.get(cls, CLASS_RANK[DEFAULT_CLASS])


def pressure_score(catalog: Optional["BufferCatalog"]) -> float:
    """Memory-pressure score of one catalog: the device watermark
    fraction dominates (it is what OOMs), host and disk occupancy add
    smaller terms so a ladder already spilling reads hotter than one
    merely full. Range [0, ~1.35]; each tier fraction clamps at 1."""
    if catalog is None:
        return 0.0
    dev = min(catalog.device_bytes / max(catalog.device_budget, 1), 1.0)
    host = min(catalog.host_bytes / max(catalog.host_budget, 1), 1.0)
    disk = min(catalog.disk_bytes / max(catalog.host_budget, 1), 1.0)
    return round(dev + 0.25 * host + 0.1 * disk, 4)


def get_tpu_semaphore(permits: int) -> "TpuSemaphore":
    """THE process-wide admission semaphore, sized by the FIRST
    ``spark.rapids.sql.concurrentTpuTasks`` value seen (the reference
    sizes one GpuSemaphore per executor once at startup —
    GpuSemaphore.scala:63; later conf changes are likewise ignored so the
    device bound stays global across sessions). Exec.collect acquires it
    around device work."""
    global _GLOBAL_SEM
    with _GLOBAL_SEM_LOCK:
        if _GLOBAL_SEM is None:
            _GLOBAL_SEM = TpuSemaphore(permits)
        return _GLOBAL_SEM


class TpuSemaphore:
    """Task-admission semaphore (GpuSemaphore.scala:101):
    ``spark.rapids.sql.concurrentTpuTasks`` tasks may issue device work at
    once; auto-release via context manager replaces the task-completion
    listener.

    With ``scheduler.preemption.enabled`` the same permits become a
    CLASS-RANKED gate: tokened acquisitions queue in (class rank,
    arrival) order, only the head waiter takes a permit, and a head
    waiter that outranks a running holder asks the WORST-ranked holder
    to yield at its next partition boundary
    (``QueryToken.request_preempt`` — cooperative, so live device state
    is always catalog-registered data at rest when the permit comes
    back). Victims re-enter through :meth:`wait_resume`, which queues
    at their own rank — a preempted background query resumes exactly
    when the interactive burst above it has drained. Disabled (the
    default), every acquire takes the flat-semaphore path unchanged."""

    def __init__(self, permits: int = 2):
        self._sem = threading.Semaphore(permits)
        self.permits = permits
        # Classed-gate state (only touched when preemption is enabled):
        self._gate_lock = threading.Lock()
        self._seq = 0
        self._waiters: List[list] = []        # [rank, seq, token]
        self._holders: Dict[int, list] = {}   # id(token) -> [tok, rank, n]
        self.preempt_requests = 0

    def __enter__(self):
        # Cancellation-aware: a query cancelled/deadlined while QUEUED
        # for the device must unwind instead of eventually grabbing a
        # permit it will never use (its neighbors keep the device busy).
        # The acquire records as a "queued" span — device-semaphore
        # contention is one of the three queueing stories the flight
        # recorder separates (admission queue, semaphore, pipeline wait).
        from spark_rapids_tpu import faults, monitoring
        with monitoring.span("tpu-semaphore-acquire", "queued",
                             level=monitoring.LEVEL_QUERY):
            tok = faults.get_query_token()
            if tok is None:
                self._sem.acquire()
                return self
            if _PREEMPT_ENABLED:
                self._acquire_classed(tok)
                return self
            while not self._sem.acquire(timeout=0.05):
                if tok.cancelled():
                    raise tok.error()
            return self

    # -- class-ranked gate (preemption.enabled only) -------------------------
    def _enqueue(self, tok) -> list:
        with self._gate_lock:
            self._seq += 1
            w = [_class_rank(tok), self._seq, tok]
            self._waiters.append(w)
            return w

    def _head(self, w: list) -> bool:
        """Whether ``w`` is the best-ranked waiter (class rank first,
        arrival order within a class) — only the head takes a permit, so
        grants happen in priority order."""
        return min(self._waiters, key=lambda x: (x[0], x[1])) is w

    def _request_preempt_locked(self, rank: int) -> None:
        """A head waiter of rank ``rank`` found every permit held: ask
        the WORST strictly-lower-class holder (highest rank number) to
        yield. Idempotent per victim — the event is level-triggered."""
        victim = None
        for tok, hrank, _n in self._holders.values():
            if hrank > rank and tok.preempt_enabled \
                    and not tok.preempt.is_set():
                if victim is None or hrank > victim[1]:
                    victim = (tok, hrank)
        if victim is not None:
            from spark_rapids_tpu.parallel.qos.policy import CLASSES
            self.preempt_requests += 1
            victim[0].request_preempt(CLASSES[rank]
                                      if 0 <= rank < len(CLASSES)
                                      else None)

    def _acquire_classed(self, tok) -> None:
        w = self._enqueue(tok)
        rank = w[0]
        try:
            while True:
                if tok.cancelled():
                    raise tok.error()
                with self._gate_lock:
                    if self._head(w):
                        if self._sem.acquire(blocking=False):
                            self._waiters.remove(w)
                            h = self._holders.get(id(tok))
                            if h is None:
                                self._holders[id(tok)] = [tok, rank, 1]
                            else:
                                h[2] += 1
                            return
                        # Head of the line, no permit: preempt the
                        # worst-ranked running holder (if any is
                        # strictly below this class).
                        self._request_preempt_locked(rank)
                time.sleep(0.005)
        except BaseException:
            with self._gate_lock:
                if w in self._waiters:
                    self._waiters.remove(w)
            raise

    def wait_resume(self, tok, cancel=None) -> None:
        """Block a preempted query until the gate would grant its class
        a permit again (the preemptor — and every other higher-ranked
        waiter — has drained), WITHOUT taking the permit: the caller's
        re-collect re-acquires normally. Acquire-then-release keeps the
        resume ordered through the same ranked queue."""
        if not _PREEMPT_ENABLED:
            return
        self._acquire_classed(tok)
        self.release_classed(tok)

    def release_classed(self, tok) -> None:
        with self._gate_lock:
            h = self._holders.get(id(tok))
            if h is not None:
                h[2] -= 1
                if h[2] <= 0:
                    self._holders.pop(id(tok), None)
        self._sem.release()

    def __exit__(self, *exc):
        from spark_rapids_tpu import faults
        tok = faults.get_query_token()
        if tok is not None and _PREEMPT_ENABLED:
            self.release_classed(tok)
            return False
        with self._gate_lock:
            # A holder registered under the classed gate may release
            # after a mid-flight regime flip (mixed confs): keep the
            # holder table honest either way.
            if tok is not None:
                self._holders.pop(id(tok), None)
        self._sem.release()
        return False

    def acquire(self):
        self._sem.acquire()

    def release(self):
        self._sem.release()

    @property
    def holders(self) -> List[tuple]:
        """(query_id, class rank) of current classed-gate holders
        (tests/diagnostics)."""
        with self._gate_lock:
            return [(t.query_id, r) for t, r, _n in
                    self._holders.values()]
