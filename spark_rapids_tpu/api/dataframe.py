"""DataFrame API frontend (SURVEY.md §7 "accept ... a direct DataFrame API
for standalone benchmarking"; shapes mirror pyspark.sql).

``TpuSession`` is the SparkSession analog: holds the conf, builds
DataFrames from memory/files/range, and plans queries through the
tag->convert rewrite (plan/planner.py). ``DataFrame.collect`` executes on
the device engine with host islands where the planner tagged fallbacks;
``DataFrame.explain`` prints the will/will-not-run-on-TPU report.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import Column, col, lit_col
from spark_rapids_tpu.plan.planner import Planner


class TpuSession:
    """Session: conf + DataFrame builders (SparkSession analog)."""

    def __init__(self, conf: Optional[Dict] = None):
        self.conf = C.TpuConf(conf)

    # -- conf ----------------------------------------------------------------
    def set(self, key: str, value) -> "TpuSession":
        self.conf.set(key, value)
        return self

    # -- builders ------------------------------------------------------------
    def create_dataframe(self, data: Union[Dict, List[tuple]],
                         schema: Sequence[Tuple[str, dt.DataType]],
                         num_partitions: int = 1) -> "DataFrame":
        schema = tuple(schema)
        if isinstance(data, dict):
            rows = list(zip(*[data[n] for n, _ in schema])) \
                if data else []
        else:
            rows = list(data)
        per = max(1, -(-len(rows) // num_partitions)) if rows else 1
        parts = []
        for i in range(num_partitions):
            chunk = rows[i * per:(i + 1) * per]
            cols = {n: [r[ci] for r in chunk]
                    for ci, (n, _) in enumerate(schema)}
            parts.append([HostBatch.from_pydict(schema, cols)])
        return DataFrame(self, L.InMemoryScan(schema, parts))

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.LogicalRange(start, end, step,
                                              num_partitions))

    def ingest_spark_plan(self, plan_text: str, table_paths):
        """Plugin mode: parse a CAPTURED Spark physical plan (the text of
        ``df.explain()`` from a real cluster) and run it on this engine.
        ``table_paths`` maps table names (matched against the captured
        scan locations) to local data paths. See plan/spark_ingest.py."""
        from spark_rapids_tpu.plan.spark_ingest import ingest_spark_plan
        return ingest_spark_plan(plan_text, self, table_paths)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self._session = session
        self._options: Dict = {}

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def _scan(self, fmt: str, paths) -> "DataFrame":
        from spark_rapids_tpu.io import infer_schema
        if isinstance(paths, str):
            paths = [paths]
        schema = infer_schema(fmt, paths, self._options)
        return DataFrame(self._session,
                         L.FileScan(fmt, list(paths), schema,
                                    dict(self._options)))

    def parquet(self, *paths) -> "DataFrame":
        return self._scan("parquet", list(paths))

    def csv(self, *paths) -> "DataFrame":
        return self._scan("csv", list(paths))

    def orc(self, *paths) -> "DataFrame":
        return self._scan("orc", list(paths))


class GroupedData:
    def __init__(self, df: "DataFrame", keys: Sequence[Union[str, Column]],
                 grouping: Optional[str] = None):
        self._df = df
        self._keys = [(k, col(k)) if isinstance(k, str)
                      else (k.name_hint, k) for k in keys]
        self._grouping = grouping

    def agg(self, *aggs: Column, **named: Column) -> "DataFrame":
        specs = []
        for a in aggs:
            specs.append((self._agg_name(a), a))
        for name, a in named.items():
            specs.append((name, a))
        plan = L.LogicalAggregate(self._df._plan, self._keys, specs,
                                  grouping=self._grouping)
        return DataFrame(self._df._session, plan)

    @staticmethod
    def _agg_name(a: Column) -> str:
        node = a.node
        if node[0] == "alias":
            return node[2]
        if node[0] == "agg":
            kind = node[1]
            child = node[2]
            base = child.name_hint if child is not None else "1"
            return f"{kind}({base})"
        return node[0]

    def count(self) -> "DataFrame":
        from spark_rapids_tpu.plan.logical import agg_count
        return self.agg(agg_count().alias("count"))

    # -- pandas-UDF flavors (GpuFlatMapGroupsInPandasExec family) ---------
    def _key_names(self) -> List[str]:
        names = []
        for hint, c in self._keys:
            if c.node[0] != "ref":
                raise ValueError(
                    "pandas group flavors need plain column-name keys")
            names.append(c.node[1])
        return names

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(group: pandas.DataFrame) -> pandas.DataFrame, one call per
        group (Spark applyInPandas; GpuFlatMapGroupsInPandasExec)."""
        plan = L.LogicalGroupedMapInPandas(
            self._df._plan, self._key_names(), fn, tuple(schema))
        return DataFrame(self._df._session, plan)

    applyInPandas = apply_in_pandas

    def agg_in_pandas(self, **named) -> "DataFrame":
        """GROUPED_AGG pandas UDFs: each kwarg is
        ``out_name=(input_column, series_fn, result_type)`` where
        series_fn(pandas.Series) -> scalar (GpuAggregateInPandasExec)."""
        aggs = [(out, colname, fn, t)
                for out, (colname, fn, t) in named.items()]
        plan = L.LogicalAggInPandas(self._df._plan, self._key_names(),
                                    aggs)
        return DataFrame(self._df._session, plan)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)


class CoGroupedData:
    """Pair of grouped frames for cogrouped pandas application
    (Spark's PandasCogroupedOps; GpuCoGroupedMapInPandasExec)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(left_group: pdf, right_group: pdf) -> pdf per key in the
        union of both sides' key sets (absent side = empty frame)."""
        plan = L.LogicalCoGroupedMapInPandas(
            self._left._df._plan, self._right._df._plan,
            self._left._key_names(), self._right._key_names(),
            fn, tuple(schema))
        return DataFrame(self._left._df._session, plan)

    applyInPandas = apply_in_pandas


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self._session = session
        self._plan = plan

    # -- schema ---------------------------------------------------------------
    @property
    def schema(self):
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return [n for n, _ in self.schema]

    # -- transformations ------------------------------------------------------
    def filter(self, condition: Column) -> "DataFrame":
        return DataFrame(self._session,
                         L.LogicalFilter(self._plan, condition))

    where = filter

    def _project(self, projections) -> "DataFrame":
        """Build a projection, extracting window expressions into a chain
        of LogicalWindow nodes first (ExtractWindowExpressions analog)."""
        plan = self._plan
        out = []
        for i, (name, c) in enumerate(projections):
            if L.is_window_column(c):
                node = c.node
                while node[0] == "alias":
                    node = node[1].node
                _, fn_col, windef = node
                tmp = f"__window_{i}_{name}"
                plan = L.LogicalWindow(plan, [(tmp, fn_col)], windef)
                out.append((name, col(tmp)))
            elif L.is_generate_column(c):
                node = c.node
                while node[0] == "alias":
                    node = node[1].node
                _, elements, position, outer = node
                plan = L.LogicalGenerate(plan, name, list(elements),
                                         position, outer)
                if position:
                    out.append((f"{name}__pos", col(f"{name}__pos")))
                out.append((name, col(name)))
            else:
                out.append((name, c))
        return DataFrame(self._session, L.LogicalProject(plan, out))

    def select(self, *cols_: Union[str, Column]) -> "DataFrame":
        projections = []
        for c in cols_:
            if isinstance(c, str):
                projections.append((c, col(c)))
            else:
                projections.append((c.name_hint, c))
        return self._project(projections)

    def with_column(self, name: str, c: Column) -> "DataFrame":
        # Replace in place like pyspark's withColumn; append when new.
        if name in self.columns:
            projections = [(n, c if n == name else col(n))
                           for n in self.columns]
        else:
            projections = [(n, col(n)) for n in self.columns]
            projections.append((name, c))
        return self._project(projections)

    withColumn = with_column

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """fn(iterator of pandas DataFrames) -> iterator of DataFrames
        (Spark mapInPandas; GpuMapInPandasExec analog)."""
        plan = L.LogicalMapInPandas(self._plan, fn, tuple(schema))
        return DataFrame(self._session, plan)

    mapInPandas = map_in_pandas

    def group_by(self, *keys: Union[str, Column]) -> GroupedData:
        return GroupedData(self, keys)

    groupBy = group_by

    def rollup(self, *keys: Union[str, Column]) -> GroupedData:
        """GROUP BY ROLLUP: hierarchical subtotals via ExpandExec
        (GpuExpandExec.scala)."""
        return GroupedData(self, keys, grouping="rollup")

    def cube(self, *keys: Union[str, Column]) -> GroupedData:
        """GROUP BY CUBE: all key-subset subtotals via ExpandExec."""
        return GroupedData(self, keys, grouping="cube")

    def agg(self, *aggs: Column, **named: Column) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs, **named)

    def order_by(self, *orders: Union[str, Column]) -> "DataFrame":
        os_ = [col(o) if isinstance(o, str) else o for o in orders]
        return DataFrame(self._session, L.LogicalSort(self._plan, os_))

    orderBy = order_by
    sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._session, L.LogicalLimit(self._plan, n))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._session,
                         L.LogicalUnion(self._plan, other._plan))

    unionAll = union

    def repartition(self, n: int, *keys: Union[str, Column]) -> "DataFrame":
        ks = [col(k) if isinstance(k, str) else k for k in keys] or None
        return DataFrame(self._session,
                         L.LogicalRepartition(self._plan, n, ks))

    def join(self, other: "DataFrame", on: Union[str, Sequence[str], tuple],
             how: str = "inner", condition: Optional[Column] = None,
             strategy: str = "auto") -> "DataFrame":
        if isinstance(on, str):
            on = [on]
        lkeys = [col(k) if isinstance(k, str) else k for k in on]
        rkeys = list(lkeys)
        plan = L.LogicalJoin(self._plan, other._plan, lkeys, rkeys,
                             how, condition, strategy)
        return DataFrame(self._session, plan)

    def join_on(self, other: "DataFrame",
                left_on: Sequence[Union[str, Column]],
                right_on: Sequence[Union[str, Column]],
                how: str = "inner", condition: Optional[Column] = None,
                strategy: str = "auto") -> "DataFrame":
        lkeys = [col(k) if isinstance(k, str) else k for k in left_on]
        rkeys = [col(k) if isinstance(k, str) else k for k in right_on]
        plan = L.LogicalJoin(self._plan, other._plan, lkeys, rkeys,
                             how, condition, strategy)
        return DataFrame(self._session, plan)

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        plan = L.LogicalJoin(self._plan, other._plan, [], [], "cross")
        return DataFrame(self._session, plan)

    crossJoin = cross_join

    # -- actions --------------------------------------------------------------
    def _physical(self):
        # Plan once per (DataFrame, conf version); the process-global
        # parameterized plan cache (plan/plan_cache.py) additionally
        # shares fully planned templates ACROSS DataFrames of the same
        # shape — a repeat query with new literals binds against the
        # cached template instead of re-planning and re-tracing.
        key = self._session.conf.version
        cached = getattr(self, "_phys_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from spark_rapids_tpu.plan.plan_cache import plan_or_bind
        phys = plan_or_bind(self._session.conf, self._plan)
        self._phys_cache = (key, phys)
        return phys

    def prepare(self):
        """Explicit prepared-statement handle: plan NOW (or bind
        against the process-global plan cache) and return the bound
        plan — its ``collect()``/``explain()`` skip all planning work,
        and ``cache_hit``/``bind_values`` expose the plan-cache
        provenance. Useful for warming a serving tier's shapes before
        traffic arrives (scripts/warmup.py drives this)."""
        return self._physical()

    def collect(self, timeout_ms: Optional[float] = None,
                priority: Optional[str] = None,
                tenant: Optional[str] = None) -> List[tuple]:
        """Run the query through the multi-query scheduler
        (parallel/scheduler.py). ``timeout_ms`` arms a deadline: a query
        still running when it expires unwinds cooperatively at its next
        dispatch checkpoint with ``QueryCancelledError`` (reason
        "deadline exceeded"), releasing the TPU semaphore and every
        owned buffer. Raises ``QueryRejectedError`` when the scheduler's
        run queue is full (load shed) or admission times out.

        With the QoS subsystem enabled (scheduler.qos.enabled),
        ``priority`` picks the query's class ("interactive" / "batch" /
        "background"), ``tenant`` tags it for per-tenant quotas, and
        ``timeout_ms`` additionally acts as a deadline tested against
        the cost estimate at admit time (kind "deadline-unmeetable").
        Both default from conf (qos.priorityClass / qos.tenant)."""
        return self._physical().collect(timeout_ms=timeout_ms,
                                        priority=priority, tenant=tenant)

    def collect_with_retry(self, timeout_ms: Optional[float] = None,
                           priority: Optional[str] = None,
                           tenant: Optional[str] = None,
                           max_attempts: Optional[int] = None,
                           max_backoff_ms: Optional[float] = None,
                           seed: int = 0) -> List[tuple]:
        """:meth:`collect` behind the obedient-client backpressure loop
        (parallel/scheduler.collect_with_retry): a
        ``QueryRejectedError`` carrying a ``retry_after_ms`` hint backs
        off for the hinted interval (deterministic per-``seed`` jitter,
        capped at ``client.retry.maxBackoffMs``) and resubmits, up to
        ``client.retry.maxAttempts`` attempts; hintless rejections
        re-raise immediately. This is the call a sustained serving
        client should make — a herd of them converges onto the
        scheduler's observed service rate instead of hammering a full
        queue (bench.py's sustained probe does exactly this)."""
        from spark_rapids_tpu.parallel import scheduler as SC
        return SC.collect_with_retry(
            lambda: self.collect(timeout_ms=timeout_ms,
                                 priority=priority, tenant=tenant),
            conf=self._session.conf, max_attempts=max_attempts,
            max_backoff_ms=max_backoff_ms, seed=seed)

    def submit(self, timeout_ms: Optional[float] = None,
               priority: Optional[str] = None,
               tenant: Optional[str] = None):
        """Async collect: returns a ``QueryHandle`` whose ``cancel()``
        stops the query cooperatively — while it is still queued for
        admission or mid-flight — and whose ``result()`` returns the
        rows or re-raises the query's error. ``priority``/``tenant``
        feed QoS scheduling exactly as in :meth:`collect`."""
        from spark_rapids_tpu.parallel.scheduler import QueryHandle
        phys = self._physical()

        def run(cancel_event, tmo):
            return phys.collect(timeout_ms=tmo, cancel_event=cancel_event,
                                priority=priority, tenant=tenant)

        return QueryHandle(run, timeout_ms)

    def _host_physical(self):
        """Re-plan with sql.enabled off (the host fallback engine — no
        device bridges). Shared by collect_host and gated writes."""
        import spark_rapids_tpu.config as C
        host_conf = C.TpuConf(dict(self._session.conf.raw))
        host_conf.set("spark.rapids.sql.enabled", False)
        return Planner(host_conf).plan(self._plan)

    def collect_host(self) -> List[tuple]:
        """Run entirely on the host oracle engine (CPU-Spark stand-in)."""
        phys = self._host_physical()
        from spark_rapids_tpu.ops.base import ExecContext
        return phys.root.collect(ExecContext(phys.conf), device=False)

    def count_rows(self) -> int:
        return len(self.collect())

    def explain(self, mode: str = "ALL") -> str:
        report = self._physical().explain(mode)
        print(report)
        return report

    def explain_analyze(self) -> str:
        """The plan tree annotated with OBSERVED per-operator
        rows/bytes/wall-ms next to the cost model's per-node estimates
        and the estimate error — the estimate-vs-actual feedback the
        cost calibration needs (monitoring/analyze.py). Reads the LAST
        collect() on this DataFrame; collects once if none ran yet."""
        phys = self._physical()
        if getattr(phys, "last_ctx", None) is None:
            self.collect()
        from spark_rapids_tpu.monitoring.analyze import render
        report = render(phys, getattr(phys, "last_ctx", None))
        # Plan provenance: a cache-hit (bind-only) execution must not
        # silently look identical to a freshly planned one.
        prov = getattr(phys, "provenance", None)
        if prov:
            report = f"[{prov}]\n{report}"
        print(report)
        return report

    def trace_export(self, path: Optional[str] = None) -> dict:
        """Export the flight recorder's Chrome trace-event JSON (loads
        in Perfetto / chrome://tracing): one track per query — this
        DataFrame's last collect AND whatever ran concurrently — and
        one per worker thread. Requires ``spark.rapids.sql.trace.enabled``
        (or SRT_TRACE=1) during the collect; returns the trace document
        and writes it to ``path`` when given.

        After a cluster collect, the workers' trace rings (shipped back
        on stage completion) merge into this SAME document under their
        own per-worker process tracks — one file shows the driver's
        dispatch wait next to each worker's stage execution."""
        from spark_rapids_tpu import monitoring
        phys = self._physical()
        ctx = getattr(phys, "last_ctx", None)
        workers = ctx.cache.get("cluster_worker_events") \
            if ctx is not None else None
        if not workers:
            return monitoring.export_chrome(path)
        from spark_rapids_tpu.monitoring.chrome import to_chrome_cluster
        doc = to_chrome_cluster(monitoring.events(),
                                monitoring.thread_names(), workers,
                                monitoring.process_tag())
        if path:
            import json
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc

    def to_pandas(self):
        import pandas as pd
        rows = self.collect()
        return pd.DataFrame(rows, columns=self.columns)

    def to_jax(self):
        """ML hand-off: run the plan on the device engine and return the
        result as ``{column_name: jax.Array}`` WITHOUT a host round trip
        (ColumnarRdd.scala:41-49 / InternalColumnarRddConverter analog —
        the reference exports cuDF device tables to GPU ML; here the
        arrays stay resident in HBM for jax models to consume).

        Numeric/bool/date columns come back as 1-D arrays of exactly the
        live row count; strings as (rows, width) uint8 byte matrices
        under ``name`` plus ``name + '__len'`` length vectors. Nulls are
        not representable in a raw array — columns with any null raise
        (fill or drop them in the query first)."""
        import jax as _jax
        import jax.numpy as jnp
        import spark_rapids_tpu.config as C
        from spark_rapids_tpu.columnar.batch import (
            bucket_capacity, concat_batches)
        from spark_rapids_tpu.memory.oom import set_active_catalog
        from spark_rapids_tpu.memory.stores import get_tpu_semaphore
        from spark_rapids_tpu.ops.base import ExecContext
        phys = self._physical()
        assert phys.root_on_device, \
            "to_jax needs a device plan (sql.enabled off?)"
        ctx = ExecContext(phys.conf)
        ctx.cache.setdefault("engine", "device")
        install = getattr(phys, "install", None)
        if install is not None:     # bound plan: thread the literals in
            install(ctx)
        root = phys.root
        # Same device-admission + OOM-recovery regime as collect():
        # the semaphore bounds concurrent device users, the registered
        # catalog lets dispatch sites spill-and-retry.
        sem = get_tpu_semaphore(
            max(int(phys.conf.get(C.CONCURRENT_TPU_TASKS)), 1))
        try:
            with sem:
                set_active_catalog(ctx.catalog)
                try:
                    batches = []
                    for p in range(root.num_partitions(ctx)):
                        batches.extend(
                            root.execute_device_recovering(ctx, p))
                    if not batches:
                        return self._empty_jax(root.schema)
                    single = batches[0] if len(batches) == 1 else \
                        concat_batches(
                            batches, bucket_capacity(
                                sum(b.capacity for b in batches)))
                    from spark_rapids_tpu.columnar.rowmove import \
                        compact_batch
                    from spark_rapids_tpu.ops import kernel_cache as kc
                    fn = kc.lookup("compact-batch", (),
                                   lambda: _jax.jit(compact_batch))
                    single = fn(single)
                    n = int(single.live_count())
                finally:
                    set_active_catalog(None)
        finally:
            phys.last_ctx = ctx
            ctx.close()
        out = {}
        for (name, t), c in zip(root.schema, single.columns):
            if not bool(jnp.all(c.validity[:n])):
                raise ValueError(
                    f"to_jax: column {name!r} contains nulls; fill or "
                    f"filter them before exporting")
            if t.is_string:
                out[name] = c.data[:n]
                out[name + "__len"] = c.lengths[:n]
            else:
                out[name] = c.data[:n]
        return out

    @staticmethod
    def _empty_jax(schema):
        """Typed empty export: dtypes and the string matrix/length layout
        must match the non-empty contract."""
        import jax.numpy as jnp
        out = {}
        for name, t in schema:
            if t.is_string:
                out[name] = jnp.zeros((0, 8), jnp.uint8)
                out[name + "__len"] = jnp.zeros((0,), jnp.int32)
            else:
                out[name] = jnp.zeros((0,), t.np_dtype)
        return out

    _METRIC_LEVELS = {
        "ESSENTIAL": {"numOutputRows", "totalTime"},
        "MODERATE": {"numOutputRows", "totalTime", "numOutputBatches",
                     "shuffleTime", "bufferTime"},
    }

    def metrics(self):
        """Per-operator metrics of the LAST collect() on this DataFrame
        (GpuExec.scala:27-56 registry; empty before any action).
        ``spark.rapids.sql.metrics.level`` filters verbosity."""
        import spark_rapids_tpu.config as C
        phys = self._physical()
        ctx = getattr(phys, "last_ctx", None)
        if ctx is None:
            return {}
        level = str(self._session.conf.get(C.METRICS_LEVEL)).upper()
        keep = self._METRIC_LEVELS.get(level)
        # Audit-group entries (Recovery/Pipeline/Scheduler/Transport/
        # Cost @query — stageRecomputes, overlapRatio, queuedMs,
        # remoteShardRefetches, joinDemotions...) are audit trails,
        # never filtered by verbosity level. The exemption set lives in
        # ONE registry (ops/base.py audit_metric_groups) that every
        # subsystem's query_metrics_entry() feeds — not in per-call-site
        # tuples here.
        from spark_rapids_tpu.ops.base import audit_metric_groups
        exempt = audit_metric_groups()
        return {k: {name: v for name, v in m.values.items()
                    if keep is None or name in keep
                    or m.owner in exempt}
                for k, m in ctx.metrics.items()}

    # -- writes ---------------------------------------------------------------
    @property
    def write(self):
        from spark_rapids_tpu.io.writer import DataFrameWriter
        return DataFrameWriter(self)
