"""Public DataFrame API (SparkSession/DataFrame analogs)."""

from spark_rapids_tpu.api.dataframe import (    # noqa: F401
    DataFrame, DataFrameReader, GroupedData, TpuSession)
from spark_rapids_tpu.plan.logical import (     # noqa: F401
    agg_avg, agg_count, agg_first, agg_last, agg_max, agg_min, agg_sum,
    col, concat, input_file_name, lit_col, lower, monotonically_increasing_id,
    rand, spark_partition_id, upper, when)
