"""Public DataFrame API (SparkSession/DataFrame analogs)."""

from spark_rapids_tpu.api.dataframe import (    # noqa: F401
    DataFrame, DataFrameReader, GroupedData, TpuSession)
from spark_rapids_tpu.plan.logical import (     # noqa: F401
    add_months, agg_avg, agg_avg_distinct, agg_count, agg_count_distinct,
    agg_first, agg_last, agg_max, agg_min, agg_sum, agg_sum_distinct,
    bround_col, ceil_col, col, concat, concat_ws, date_add, date_sub,
    datediff, dayofmonth, dayofweek, dayofyear, exp_col, floor_col,
    from_unixtime, greatest, hour, initcap, input_file_name, instr,
    isnan_col, last_day, least, length, lit_col, locate, log10_col, log_col,
    log2_col, lower, lpad, ltrim, minute, monotonically_increasing_id,
    month, nanvl, pmod, pow_col, quarter, rand, regexp_extract, repeat,
    replace_str, reverse, round_col, rpad, rtrim, second, signum_col,
    spark_partition_id, sqrt_col, to_unix_timestamp, translate, trim,
    trunc, upper, weekday, when, year)
