"""UDF acceleration tier.

The reference decompiles JVM lambda bytecode into Catalyst expression
trees (udf-compiler/.../Instruction.scala:1, 830 LoC +
CatalystExpressionBuilder.scala) so UDFs run as native GPU expressions,
and falls back to Arrow-fed Python workers for the rest
(sql-plugin/.../python/GpuArrowEvalPythonExec.scala:494,
python/rapids/worker.py:22). This package is the TPU build's analog with
Python as the host language: ``udf(f)`` walks the function's AST
(udf/compiler.py) and translates a restricted subset — arithmetic,
comparisons, boolean logic, conditionals, math/string builtins — into the
engine's Column DSL, so a compiled UDF is indistinguishable from native
expressions (full device execution, jit fusion, predicate pushdown).

When compilation fails, the call still works: it produces a ``pyudf``
expression that evaluates the original Python function over host-side
column values with a device roundtrip (the GpuArrowEvalPythonExec
pattern), and the planner's explain output carries the compile-failure
reason (willNotWorkOnGpu-style visibility).
"""

from spark_rapids_tpu.udf.compiler import (
    UdfCompileError, compile_udf, udf)

__all__ = ["udf", "compile_udf", "UdfCompileError"]
