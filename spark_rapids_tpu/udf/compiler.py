"""Python-AST -> Column-DSL UDF compiler (CatalystExpressionBuilder
analog; the reference's equivalent walks JVM bytecode,
udf-compiler/.../Instruction.scala:1).

``compile_udf(f)`` returns a builder ``(*arg_columns) -> Column`` or
raises ``UdfCompileError`` naming the unsupported construct. ``udf(f)``
wraps that into a callable usable anywhere a Column is: compiled UDFs
become native expressions; uncompilable ones degrade to a host-evaluated
``pyudf`` expression with the failure reason attached (surfaced by
explain, the willNotWorkOnGpu discipline)."""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Callable, List, Optional

from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import Column, _as_col


class UdfCompileError(ValueError):
    """The function uses constructs outside the compilable subset."""


_BINOPS = {
    ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul", ast.Div: "div",
    ast.Mod: "mod",
}
_CMPOPS = {
    ast.Lt: "lt", ast.LtE: "le", ast.Gt: "gt", ast.GtE: "ge",
    ast.Eq: "eq",
}

# Supported calls: name -> (arity, Column builder).
_CALLS = {
    "abs": (1, L.abs_col),
    "min": (2, lambda a, b: L.least(a, b)),
    "max": (2, lambda a, b: L.greatest(a, b)),
    "round": (1, L.round_col),
    "len": (1, L.length),
}
# Supported method calls on string-ish values.
_METHODS = {
    "upper": L.upper,
    "lower": L.lower,
    "strip": L.trim,
    "lstrip": L.ltrim,
    "rstrip": L.rtrim,
}


def _function_ast(f: Callable) -> ast.AST:
    try:
        src = textwrap.dedent(inspect.getsource(f))
    except (OSError, TypeError) as e:
        raise UdfCompileError(f"source unavailable: {e}")
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # A lambda in the middle of a call expression: isolate it.
        start = src.index("lambda")
        depth = 0
        end = len(src)
        for i, ch in enumerate(src[start:], start):
            if ch in "([{":
                depth += 1
            elif ch in ")]}":
                if depth == 0:
                    end = i
                    break
                depth -= 1
            elif ch == "," and depth == 0:
                end = i
                break
        tree = ast.parse(src[start:end].strip(), mode="eval")
    for node in ast.walk(tree):
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return node
    raise UdfCompileError("no function definition found in source")


class _Compiler(ast.NodeVisitor):
    def __init__(self, params: List[str], env: dict):
        self.params = params
        self.env = env

    def compile(self, node: ast.AST) -> Column:
        return self.visit(node)

    # -- structure ------------------------------------------------------
    def visit_Return(self, node: ast.Return) -> Column:
        if node.value is None:
            raise UdfCompileError("bare return")
        return self.visit(node.value)

    def visit_IfExp(self, node: ast.IfExp) -> Column:
        cond = self.visit(node.test)
        return L.when(cond, self.visit(node.body)) \
            .otherwise(self.visit(node.orelse))

    # -- leaves ---------------------------------------------------------
    def visit_Name(self, node: ast.Name) -> Column:
        if node.id in self.env:
            return self.env[node.id]
        raise UdfCompileError(f"unresolved name {node.id!r}")

    def visit_Constant(self, node: ast.Constant) -> Column:
        if node.value is None:
            raise UdfCompileError("None literal (use SQL null semantics "
                                  "via engine functions)")
        if isinstance(node.value, (bool, int, float, str)):
            return L.lit_col(node.value)
        raise UdfCompileError(
            f"unsupported constant {type(node.value).__name__}")

    # -- operators ------------------------------------------------------
    def visit_BinOp(self, node: ast.BinOp) -> Column:
        if isinstance(node.op, ast.Pow):
            return L.pow_col(self.visit(node.left),
                             self.visit(node.right))
        kind = _BINOPS.get(type(node.op))
        if kind is None:
            raise UdfCompileError(
                f"operator {type(node.op).__name__}")
        return Column((kind, self.visit(node.left),
                       self.visit(node.right)))

    def visit_UnaryOp(self, node: ast.UnaryOp) -> Column:
        v = self.visit(node.operand)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return v
        if isinstance(node.op, ast.Not):
            return ~v
        raise UdfCompileError(f"operator {type(node.op).__name__}")

    def visit_Compare(self, node: ast.Compare) -> Column:
        parts = []
        left = node.left
        for op, right in zip(node.ops, node.comparators):
            lcol = self.visit(left)
            rcol = self.visit(right)
            if isinstance(op, ast.NotEq):
                parts.append(~(lcol == rcol))
            else:
                kind = _CMPOPS.get(type(op))
                if kind is None:
                    raise UdfCompileError(
                        f"comparison {type(op).__name__}")
                parts.append(Column((kind, lcol, rcol)))
            left = right
        out = parts[0]
        for p in parts[1:]:
            out = out & p
        return out

    def visit_BoolOp(self, node: ast.BoolOp) -> Column:
        vals = [self.visit(v) for v in node.values]
        out = vals[0]
        for v in vals[1:]:
            out = (out & v) if isinstance(node.op, ast.And) else (out | v)
        return out

    # -- calls ----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> Column:
        if node.keywords:
            raise UdfCompileError("keyword arguments in call")
        if isinstance(node.func, ast.Name):
            spec = _CALLS.get(node.func.id)
            if spec is None:
                raise UdfCompileError(f"call to {node.func.id!r}")
            arity, builder = spec
            if len(node.args) != arity:
                raise UdfCompileError(
                    f"{node.func.id}() expects {arity} args")
            return builder(*[self.visit(a) for a in node.args])
        if isinstance(node.func, ast.Attribute):
            builder = _METHODS.get(node.func.attr)
            if builder is None or node.args:
                raise UdfCompileError(
                    f"method .{node.func.attr}()")
            return builder(self.visit(node.func.value))
        raise UdfCompileError("computed call target")

    def generic_visit(self, node):
        raise UdfCompileError(f"syntax {type(node).__name__}")


def compile_udf(f: Callable) -> Callable[..., Column]:
    """Compile ``f`` into a Column-builder or raise UdfCompileError."""
    fn_node = _function_ast(f)
    args = fn_node.args
    if args.vararg or args.kwarg or args.kwonlyargs or args.defaults:
        raise UdfCompileError("only plain positional parameters")
    params = [a.arg for a in args.args]
    # Captured variables inline as constants FROZEN AT COMPILE TIME (the
    # reference's bytecode compiler does the same for lambda captures);
    # anything non-literal is rejected up front.
    captured = {}
    try:
        cv = inspect.getclosurevars(f)
        free = dict(cv.nonlocals)
        free.update({k: v for k, v in cv.globals.items()})
        unbound = set(cv.unbound)
    except TypeError:
        free, unbound = {}, set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                node.id not in params and node.id not in _CALLS:
            if node.id in free:
                v = free[node.id]
                if isinstance(v, (bool, int, float, str)):
                    captured[node.id] = L.lit_col(v)
                else:
                    raise UdfCompileError(
                        f"captured variable {node.id!r} is not a "
                        "literal constant")
            elif node.id in unbound:
                raise UdfCompileError(f"free variable {node.id!r}")
    if isinstance(fn_node, ast.Lambda):
        body: ast.AST = fn_node.body
    else:
        stmts = [s for s in fn_node.body
                 if not isinstance(s, ast.Expr)     # skip docstring
                 or not isinstance(s.value, ast.Constant)]
        if len(stmts) != 1 or not isinstance(stmts[0], ast.Return):
            raise UdfCompileError(
                "function body must be a single return statement")
        body = stmts[0]

    def build(*cols) -> Column:
        if len(cols) != len(params):
            raise TypeError(
                f"udf takes {len(params)} args, got {len(cols)}")
        env = dict(captured)
        env.update({p: _as_col(c) for p, c in zip(params, cols)})
        return _Compiler(params, env).compile(body)

    build.__name__ = getattr(f, "__name__", "udf")
    return build


def udf(f: Optional[Callable] = None, return_type=None):
    """pyspark-style ``udf``: compiled to native expressions when the AST
    subset allows, host-evaluated ``pyudf`` expression otherwise (with the
    compile failure surfaced in explain)."""
    if f is None:
        return lambda g: udf(g, return_type)
    try:
        build = compile_udf(f)
        reason = None
    except UdfCompileError as e:
        build = None
        reason = str(e)

    def call(*cols) -> Column:
        if build is not None:
            return build(*cols)
        from spark_rapids_tpu.columnar import dtypes as dt
        rt = return_type or dt.FLOAT64
        rt = dt.type_named(rt) if isinstance(rt, str) else rt
        return Column(("pyudf", f, rt,
                       tuple(_as_col(c) for c in cols), reason))

    call.compiled = build is not None
    call.compile_error = reason
    call.func = f
    return call
