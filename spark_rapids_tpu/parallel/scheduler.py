"""Multi-query admission control, isolation, and cancellation (ISSUE 5).

Every robustness layer before this one (fault/recovery ladder, lineage
recovery, watchdog, pipelined executor) assumed exactly one query in
flight: the TPU semaphore serializes device *partitions*, not *queries*,
each query's buffer catalog believes it owns the whole device budget,
and nothing could cancel, deadline, or shed a query. The reference gets
cross-task isolation for free from Spark's scheduler plus GpuSemaphore
and the owner-tagged RapidsBufferCatalog (SURVEY §2.2); this module is
the single-process re-design of that layer. Four pieces:

1. **Admission control** — :class:`QueryManager` holds a bounded run
   queue (``spark.rapids.sql.scheduler.{maxConcurrentQueries,queueDepth,
   admissionTimeoutMs}``). At most ``maxConcurrentQueries`` collects run
   at once; excess queries wait FIFO in a queue of ``queueDepth``; a
   query arriving with the queue full — or waiting past the admission
   timeout — is SHED with :class:`QueryRejectedError` instead of letting
   unbounded concurrency OOM the device (the reference leans on Spark's
   task scheduler for the same bound).

2. **Per-query resource isolation** — every admitted query gets a
   monotonically increasing query id; its catalog (and every buffer,
   stage output, and kernel-cache reservation it creates) is owner-tagged
   with that id, its device budget is scaled by the fair share
   (``scheduler.queryMemoryFraction``), and the OOM ladder spills the
   *offending* query's buffers (its own catalog) through two rungs
   before :func:`evict_neighbors` touches anyone else's
   (``crossQueryEvictions``). Teardown — success, failure, or cancel —
   closes every owned handle and records the catalog leak report as the
   proof (``ExecContext.last_leak_report``).

3. **Cooperative cancellation + deadlines** — admission issues a
   :class:`faults.QueryToken`; ``DataFrame.collect(timeout_ms=...)``
   arms a deadline timer on it and :meth:`QueryHandle.cancel` sets it
   directly. Every dispatch funnel's ``fault_point`` doubles as a
   cancellation checkpoint, the TPU semaphore acquire and the pipeline's
   ordered wait poll the token, and the watchdog/prefetch/stage worker
   threads inherit it — so a cancelled query unwinds mid-flight with
   :class:`faults.QueryCancelledError`, releasing the semaphore and all
   owned buffers on the way out.

4. **Cross-query fault containment** — faults.py's query-scoped arming
   (``kind@site/query=N``) matches the token's fault tag, so chaos
   tests inject an OOM/stall/lostoutput into query A and assert query
   B's results and recovery counters are bit-identical to a solo run
   (tests/test_scheduler.py).

Counters (process-global here + the per-query ``Scheduler@query``
metrics entry): ``queuedMs``, ``admitted``, ``rejected``, ``cancelled``,
``deadlineKills``, ``crossQueryEvictions``.

``SRT_SCHEDULER_MAX_CONCURRENT=1`` (env) degenerates to strictly serial
queries — byte-identical to the pre-scheduler engine (the CI matrix
proves it over the whole suite).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu import faults

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def _record(name: str, amount: float = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    """Process-global scheduler counters (bench.py's ``scheduler`` JSON
    block)."""
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _COUNTERS.clear()


def metrics_entry(ctx):
    """The per-query Scheduler metrics entry (next to Recovery@query;
    registered level-filter exempt through the ops/base.py audit
    registry)."""
    from spark_rapids_tpu.ops.base import query_metrics_entry
    return query_metrics_entry(ctx, "Scheduler")


def _telemetry_reject(kind: str, depth: int, hint, tenant=None,
                      qcls=None) -> None:
    """Structured shed-load telemetry (monitoring/telemetry.py): every
    QueryRejectedError's fields — kind, run-queue depth at rejection,
    the retry-after EWMA hint — as labeled scrape series next to the
    bare ``rejected`` funnel counter."""
    from spark_rapids_tpu.monitoring import telemetry
    if not telemetry.enabled():
        return
    telemetry.inc("srt_queries_rejected", kind=kind,
                  tenant=str(tenant or "-"), **{"class": str(qcls or "-")})
    telemetry.set_gauge("srt_reject_queue_depth", depth, kind=kind)
    if hint is not None:
        telemetry.set_gauge("srt_reject_retry_after_ms", hint, kind=kind)


def record_plan_cache(ctx, hit: bool) -> None:
    """Per-tenant plan-cache outcome (plan/plan_cache.py) on the query's
    Scheduler@query entry plus the process counters bench.py's
    ``scheduler`` block reports: ``planCacheBindOnly`` executions
    skipped planning entirely (plan once, bind literals, dispatch);
    ``planCacheMiss`` executions paid a template plan this tenant's
    later calls amortize. Tenant-tagged queries (the ``tenant=`` kwarg
    or ``scheduler.qos.tenant``) additionally land in the per-tenant
    QoS counters bench.py's ``qos``/``sustained`` blocks report."""
    name = "planCacheBindOnly" if hit else "planCacheMiss"
    metrics_entry(ctx).add(name, 1)
    _record(name)
    tenant = getattr(getattr(ctx, "query", None), "tenant", None)
    if tenant:
        from spark_rapids_tpu.parallel import qos as Q
        Q._record(f"planCache{'Hit' if hit else 'Miss'}.{tenant}")


class QueryRejectedError(RuntimeError):
    """Load shed or policy rejection. Deliberately NOT a transient
    error (no retry marker): the caller — a serving tier, a test, or
    :func:`collect_with_retry` — decides whether to resubmit, guided by
    the structured fields:

    - ``kind``: ``queue-full`` | ``admission-timeout`` |
      ``tenant-quota`` | ``deadline-unmeetable`` | ``brownout``
    - ``queue_depth``: run-queue occupancy snapshot at rejection
    - ``retry_after_ms``: when resubmitting could plausibly succeed
      (observed-service-time estimate scaled by live queue depth —
      every load-type rejection carries it); None only when retrying
      as-is can never help (a deadline the raw cost estimate already
      exceeds)."""

    def __init__(self, reason: str, kind: str = "rejected",
                 queue_depth: Optional[int] = None,
                 retry_after_ms: Optional[float] = None):
        super().__init__(
            f"REJECTED: {reason} (spark.rapids.sql.scheduler.*)")
        self.reason = reason
        self.kind = kind
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms


class QueryTicket:
    """One admitted query: its token (cancellation handle + owner id),
    admission bookkeeping, and the context registration cross-query
    eviction walks. QoS admissions (parallel/qos/) additionally carry
    the priority class, tenant, and the cost estimate that ordered the
    queue; FIFO admissions leave them None (tenant may still be set —
    it is pure attribution, never a scheduling input there)."""

    __slots__ = ("token", "queued_ms", "ctx", "deadline_timer",
                 "qos_class", "tenant", "cost_ms", "admitted_at")

    def __init__(self, token: faults.QueryToken, queued_ms: float,
                 qos_class: Optional[str] = None,
                 tenant: Optional[str] = None,
                 cost_ms: Optional[float] = None):
        self.token = token
        self.queued_ms = queued_ms
        self.ctx = None                 # registered by PhysicalPlan.collect
        self.deadline_timer: Optional[threading.Timer] = None
        self.qos_class = qos_class
        self.tenant = tenant
        self.cost_ms = cost_ms
        self.admitted_at = time.perf_counter()

    @property
    def query_id(self) -> int:
        return self.token.query_id

    def arm_deadline(self, timeout_ms: Optional[float]) -> None:
        """Deadline -> the SAME cancel event cancellation uses, so every
        checkpoint/wait tests one flag. The timer thread only sets an
        event — the query unwinds cooperatively at its next checkpoint."""
        if timeout_ms is None or timeout_ms <= 0:
            return
        t = threading.Timer(
            timeout_ms / 1000.0,
            lambda: self.token.request_cancel("deadline exceeded"))
        t.daemon = True
        t.start()
        self.deadline_timer = t

    def cancel(self, reason: str = "cancelled") -> None:
        self.token.request_cancel(reason)


class QueryManager:
    """THE process-wide query scheduler (GpuSemaphore's missing other
    half: admission at QUERY granularity). One instance per process
    (:func:`get_query_manager`); resizable only while idle so tests can
    reconfigure without racing in-flight queries."""

    def __init__(self, max_concurrent: int = 2, queue_depth: int = 16,
                 admission_timeout_ms: int = 60000, qos=None):
        self.max_concurrent = max(int(max_concurrent), 1)
        self.queue_depth = max(int(queue_depth), 0)
        self.admission_timeout_ms = max(int(admission_timeout_ms), 1)
        self._lock = threading.Lock()
        self._slots_free = self.max_concurrent
        self._waiters: List[threading.Event] = []   # FIFO run queue
        self._active: Dict[int, QueryTicket] = {}
        self._next_id = 0
        # Serving QoS (parallel/qos/, default None = the FIFO queue
        # above, byte-for-byte the pre-QoS scheduler): a QosPolicy
        # carrying the WFQ run queue + tenant quota tracker.
        self._qos = qos
        # Observed query service time EWMA (both modes; feeds the
        # retry_after_ms hint on rejections — attribution only, never
        # a scheduling input on the FIFO path).
        self._service_ewma_ms: Optional[float] = None
        # Brownout state (scheduler.pressure.*): driven by
        # note_pressure() observations from the dispatch funnel.
        self._pressure_score = 0.0
        self._pressure_high_since: Optional[float] = None
        self.brownout_active = False
        # Set (under this manager's lock) when a conf-change resize
        # replaced this manager: late calls on a stale reference follow
        # the chain so a ticket can never land in a retired manager.
        self._successor: Optional["QueryManager"] = None

    def _current(self) -> "QueryManager":
        m = self
        while m._successor is not None:
            m = m._successor
        return m

    # -- admission -----------------------------------------------------------
    def admit(self, conf=None,
              cancel: Optional[threading.Event] = None,
              priority: Optional[str] = None,
              tenant: Optional[str] = None,
              cost_ms: Optional[float] = None,
              deadline_ms: Optional[float] = None) -> QueryTicket:
        """Block until a run slot frees (FIFO, or WFQ order when the
        QoS subsystem is enabled), up to the admission timeout; raise
        :class:`QueryRejectedError` immediately when the queue is full
        (load shed), a QoS policy check fails (tenant quota, unmeetable
        deadline), or on timeout. ``cancel`` (the eventual query's
        cancel event, when the caller pre-creates it for a handle)
        aborts the wait too — a queued query is cancellable before it
        ever runs. ``priority``/``tenant``/``cost_ms``/``deadline_ms``
        feed the QoS policy; on the FIFO path only ``tenant`` is kept
        (as pure attribution for per-tenant stats)."""
        if self._successor is not None:
            # A conf-change resize retired this manager while the
            # caller still held its reference: every new ticket lands
            # in the live manager, never a retired one.
            return self._current().admit(
                conf, cancel=cancel, priority=priority, tenant=tenant,
                cost_ms=cost_ms, deadline_ms=deadline_ms)
        if self._qos is not None:
            return self._admit_qos(conf, cancel, priority, tenant,
                                   cost_ms, deadline_ms)
        from spark_rapids_tpu import config as C
        tag = None
        tnt = tenant
        if conf is not None:
            t = int(conf.get(C.TEST_FAULTS_QUERY_TAG))
            if t >= 0:
                tag = t
            if tnt is None:
                v = str(conf.get(C.QOS_TENANT) or "").strip()
                tnt = v or None
        me: Optional[threading.Event] = None
        t0 = time.perf_counter()
        with self._lock:
            if self._successor is not None:
                pass            # retired between the entry check and the
                                # lock: redirect below, never enqueue here
            elif self._slots_free > 0 and not self._waiters:
                self._slots_free -= 1
                return self._issue(tag, 0.0, cancel, tenant=tnt)
            elif len(self._waiters) >= self.queue_depth:
                _record("rejected")
                _record("rejected.queue-full")
                depth = len(self._waiters)
                hint = self._retry_hint_locked()
                from spark_rapids_tpu import monitoring
                monitoring.instant("query-rejected", "recovery",
                                   args={"reason": "queue full"})
                _telemetry_reject("queue-full", depth, hint, tenant=tnt)
                raise QueryRejectedError(
                    f"run queue full ({depth} queued, "
                    f"{self.max_concurrent} running)",
                    kind="queue-full", queue_depth=depth,
                    retry_after_ms=hint)
            else:
                me = threading.Event()
                self._waiters.append(me)
        if me is None:
            return self._current().admit(
                conf, cancel=cancel, priority=priority, tenant=tenant,
                cost_ms=cost_ms, deadline_ms=deadline_ms)
        deadline = t0 + self.admission_timeout_ms / 1000.0
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0 or (cancel is not None and cancel.is_set()):
                with self._lock:
                    if me in self._waiters:
                        self._waiters.remove(me)
                    elif me.is_set():
                        # Granted between the timeout and the lock: the
                        # slot is ours to give back.
                        self._release_slot_locked()
                    depth = len(self._waiters)
                    hint = self._retry_hint_locked()
                from spark_rapids_tpu import monitoring
                if cancel is not None and cancel.is_set():
                    _record("cancelled")
                    monitoring.instant(
                        "query-cancelled", "recovery",
                        args={"reason": "cancelled while queued"})
                    raise faults.QueryCancelledError(
                        -1, "cancelled while queued")
                _record("rejected")
                _record("rejected.admission-timeout")
                monitoring.instant("query-rejected", "recovery",
                                   args={"reason": "admission timeout"})
                _telemetry_reject("admission-timeout", depth, hint,
                                  tenant=tnt)
                raise QueryRejectedError(
                    f"admission timeout after "
                    f"{self.admission_timeout_ms}ms "
                    f"({self.max_concurrent} running)",
                    kind="admission-timeout", queue_depth=depth,
                    retry_after_ms=hint)
            if me.wait(min(remaining, 0.05)):
                with self._lock:
                    queued_ms = (time.perf_counter() - t0) * 1000.0
                    return self._issue(tag, queued_ms, cancel, tenant=tnt)

    def _admit_qos(self, conf, cancel, priority, tenant, cost_ms,
                   deadline_ms) -> QueryTicket:
        """QoS admission (parallel/qos/): tenant quotas + deadline
        feasibility first, then the WFQ run queue instead of FIFO."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.parallel import qos as Q
        qos = self._qos
        qcls = Q.resolve_class(
            priority if priority is not None else
            (str(conf.get(C.QOS_PRIORITY_CLASS)) if conf is not None
             else None))
        tnt = Q.resolve_tenant(
            tenant if tenant is not None else
            (str(conf.get(C.QOS_TENANT) or "") if conf is not None
             else None))
        tag = None
        if conf is not None:
            t = int(conf.get(C.TEST_FAULTS_QUERY_TAG))
            if t >= 0:
                tag = t
            # Kernel-cache compile budget: enforced by evicting the
            # tenant's oldest entries, never by rejecting (the cache
            # has its own leaf lock — taken outside the manager's).
            evicted = qos.enforce_kernel_quota(conf, tnt)
            if evicted:
                Q._record("quotaEvictions", evicted)
                monitoring.instant(
                    "qos-quota-eviction", "recovery",
                    args={"tenant": tnt, "entriesEvicted": evicted})

        def reject(kind, reason, depth, hint):
            _record("rejected")
            Q._record(f"rejected.{kind}")
            monitoring.instant(
                "query-rejected", "recovery",
                args={"reason": reason, "kind": kind, "tenant": tnt,
                      "class": qcls})
            _telemetry_reject(kind, depth, hint, tenant=tnt, qcls=qcls)
            raise QueryRejectedError(reason, kind=kind, queue_depth=depth,
                                     retry_after_ms=hint)

        me: Optional[threading.Event] = None
        entry = None
        t0 = time.perf_counter()
        with self._lock:
            if self._successor is not None:
                # Retired between the entry check and the lock:
                # redirect below, never enqueue here.
                me = None
            else:
                if conf is not None:
                    reason = qos.deadline_rejects(conf, cost_ms,
                                                  deadline_ms)
                    if reason is not None:
                        # Retrying as-is can never help when the RAW
                        # cost estimate already exceeds the deadline —
                        # but when only the load-scaled slack made it
                        # unmeetable, a later resubmission against a
                        # drained queue can succeed: carry the hint.
                        hopeless = (cost_ms is None or not deadline_ms
                                    or cost_ms > deadline_ms)
                        reject("deadline-unmeetable", reason,
                               len(qos.queue),
                               None if hopeless
                               else self._retry_hint_locked())
                    reason = qos.tenant_rejects(
                        conf, tnt, list(self._active.values()))
                    if reason is not None:
                        reject("tenant-quota", reason, len(qos.queue),
                               self._retry_hint_locked())
                if self.brownout_active and qcls == "background":
                    # Memory-pressure brownout (scheduler.pressure.*):
                    # sustained device pressure sheds background load
                    # with a retry hint BEFORE the OOM ladders engage,
                    # while interactive/batch still admit.
                    reject("brownout",
                           f"brownout: sustained device pressure "
                           f"{self._pressure_score:.2f}, background "
                           f"load shed", len(qos.queue),
                           self._retry_hint_locked())
                if self._slots_free > 0 and len(qos.queue) == 0:
                    self._slots_free -= 1
                    qos.quotas.reserve(tnt)
                    return self._issue(tag, 0.0, cancel, qos_class=qcls,
                                       tenant=tnt, cost_ms=cost_ms)
                if len(qos.queue) >= self.queue_depth:
                    reject("queue-full",
                           f"run queue full ({len(qos.queue)} queued, "
                           f"{self.max_concurrent} running)",
                           len(qos.queue), self._retry_hint_locked())
                me = threading.Event()
                entry = qos.queue.push(qcls, cost_ms, me, tnt)
                qos.quotas.reserve(tnt)
        if me is None:
            return self._current().admit(
                conf, cancel=cancel, priority=priority, tenant=tenant,
                cost_ms=cost_ms, deadline_ms=deadline_ms)
        deadline = t0 + self.admission_timeout_ms / 1000.0
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0 or (cancel is not None and cancel.is_set()):
                with self._lock:
                    if not entry.granted:
                        qos.queue.discard(entry)
                    elif me.is_set():
                        # Granted between the timeout and the lock: the
                        # slot is ours to give back.
                        self._release_slot_locked()
                    qos.quotas.release(tnt)
                    depth = len(qos.queue)
                    hint = self._retry_hint_locked()
                if cancel is not None and cancel.is_set():
                    _record("cancelled")
                    monitoring.instant(
                        "query-cancelled", "recovery",
                        args={"reason": "cancelled while queued"})
                    raise faults.QueryCancelledError(
                        -1, "cancelled while queued")
                reject("admission-timeout",
                       f"admission timeout after "
                       f"{self.admission_timeout_ms}ms "
                       f"({self.max_concurrent} running)",
                       depth, hint)
            if me.wait(min(remaining, 0.05)):
                with self._lock:
                    queued_ms = (time.perf_counter() - t0) * 1000.0
                    return self._issue(tag, queued_ms, cancel,
                                       qos_class=qcls, tenant=tnt,
                                       cost_ms=cost_ms)

    def _issue(self, tag: Optional[int], queued_ms: float,
               cancel: Optional[threading.Event],
               qos_class: Optional[str] = None,
               tenant: Optional[str] = None,
               cost_ms: Optional[float] = None) -> QueryTicket:
        """Build the admitted ticket (caller holds the lock / the slot)."""
        self._next_id += 1
        token = faults.QueryToken(self._next_id, tag, tenant=tenant,
                                  qos_class=qos_class)
        if cancel is not None:
            # The handle pre-created the cancel event (so cancel() works
            # while still queued); the token adopts it.
            token.cancel = cancel
        ticket = QueryTicket(token, queued_ms, qos_class=qos_class,
                             tenant=tenant, cost_ms=cost_ms)
        self._active[token.query_id] = ticket
        _record("admitted")
        _record("queuedMs", queued_ms)
        if qos_class is not None:
            from spark_rapids_tpu.parallel import qos as Q
            Q._record(f"admitted.{qos_class}")
            self._qos.quotas.record_query(token.query_id, tenant)
        from spark_rapids_tpu.monitoring import telemetry
        if telemetry.enabled():
            telemetry.inc("srt_queries_admitted",
                          tenant=str(tenant or "-"),
                          **{"class": str(qos_class or "-")})
            telemetry.observe("srt_admission_queued_ms", queued_ms,
                              **{"class": str(qos_class or "-")})
        # Retro-record the admission wait as a "queued" span on the
        # query's OWN track: the id the wait was for only exists now.
        from spark_rapids_tpu import monitoring
        if monitoring.enabled():
            dur = int(queued_ms * 1e6)
            args = {"queuedMs": round(queued_ms, 2)}
            if qos_class is not None:
                args["class"] = qos_class
                args["tenant"] = tenant
            monitoring.record_span(
                "admission-queue", "queued", monitoring.now_ns() - dur,
                dur, qid=token.query_id, args=args,
                level=monitoring.LEVEL_QUERY)
        return ticket

    def _release_slot_locked(self) -> None:
        if self._qos is not None:
            entry, starved = self._qos.queue.pop_next()
            if entry is not None:
                if starved:
                    from spark_rapids_tpu import monitoring
                    from spark_rapids_tpu.parallel import qos as Q
                    Q._record("starvationBoundEngagements")
                    monitoring.instant(
                        "qos-starvation-bound", "recovery",
                        args={"class": entry.qos_class})
                entry.event.set()       # hand the slot over, WFQ order
            else:
                self._slots_free += 1
            return
        if self._waiters:
            self._waiters.pop(0).set()      # hand the slot over, FIFO
        else:
            self._slots_free += 1

    def _observe_service_locked(self, service_ms: float) -> None:
        if service_ms < 0:
            return
        if self._service_ewma_ms is None:
            self._service_ewma_ms = service_ms
        else:
            self._service_ewma_ms += 0.2 * (
                service_ms - self._service_ewma_ms)

    def _retry_hint_locked(self) -> float:
        """The retry_after_ms hint: the queue ahead of a resubmission
        drained at the observed service rate (250ms prior before any
        query has finished)."""
        base = self._service_ewma_ms \
            if self._service_ewma_ms is not None else 250.0
        queued = len(self._qos.queue) if self._qos is not None \
            else len(self._waiters)
        waves = (1 + queued) / max(self.max_concurrent, 1)
        return round(max(50.0, base * waves), 1)

    def note_pressure(self, score: float, conf=None) -> None:
        """Brownout state machine (scheduler.pressure.*): every dispatch
        funnel reports its catalog's pressure score here on teardown of a
        device section. Pressure sustained above the enter threshold for
        ``brownout.sustainMs`` flips brownout ON (background admissions
        shed with retry hints); dropping below the exit threshold flips
        it OFF — the hysteresis band keeps the gate from flapping."""
        if self._successor is not None:
            return self._current().note_pressure(score, conf)
        from spark_rapids_tpu import config as C
        if conf is None or not bool(conf.get(C.PRESSURE_ENABLED)):
            return
        enter = float(conf.get(C.PRESSURE_BROWNOUT_SCORE))
        exit_below = float(conf.get(C.PRESSURE_BROWNOUT_EXIT_SCORE))
        sustain_s = max(
            int(conf.get(C.PRESSURE_BROWNOUT_SUSTAIN_MS)), 0) / 1000.0
        now = time.perf_counter()
        flip = None
        would_enter = False
        with self._lock:
            self._pressure_score = score
            if score >= enter:
                if self._pressure_high_since is None:
                    self._pressure_high_since = now
                if (not self.brownout_active
                        and now - self._pressure_high_since >= sustain_s):
                    would_enter = True
            else:
                self._pressure_high_since = None
                if self.brownout_active and score < exit_below:
                    self.brownout_active = False
                    flip = "exit"
        if would_enter:
            # Autoscaler interplay (ISSUE 20): capacity BEFORE load
            # shedding. When an autoscaler registered a scale probe and
            # it accepts a scale-up (the fleet is below maxWorkers),
            # brownout entry is deferred for one more sustain window so
            # the new workers get a chance to absorb the pressure;
            # only a fleet already at its ceiling browns out.
            probe = _SCALE_PROBE
            deferred = False
            if probe is not None:
                try:
                    deferred = bool(probe(score))
                except Exception:       # a broken probe must not wedge
                    deferred = False    # the brownout safety valve
            with self._lock:
                if deferred:
                    self._pressure_high_since = now
                elif not self.brownout_active:
                    self.brownout_active = True
                    flip = "enter"
            if deferred:
                _record("brownoutDeferrals")
                from spark_rapids_tpu import monitoring
                monitoring.instant(
                    "brownout-deferred-scaleup", "recovery",
                    args={"pressureScore": round(score, 4)})
                from spark_rapids_tpu.monitoring import telemetry
                if telemetry.enabled():
                    telemetry.inc("srt_brownout_deferrals")
        if flip is not None:
            _record("brownouts" if flip == "enter" else "brownoutExits")
            from spark_rapids_tpu import monitoring
            monitoring.instant(
                f"brownout-{flip}", "recovery",
                args={"pressureScore": round(score, 4)})
            from spark_rapids_tpu.monitoring import telemetry
            if telemetry.enabled():
                telemetry.set_gauge(
                    "srt_brownout_active", 1 if flip == "enter" else 0)
                if flip == "enter":
                    telemetry.inc("srt_brownouts")

    def finish(self, ticket: QueryTicket) -> None:
        """Query teardown (success, failure, or cancel): release the run
        slot, wake the next queued query, disarm the deadline."""
        if self._successor is not None:
            return self._current().finish(ticket)
        if ticket.deadline_timer is not None:
            ticket.deadline_timer.cancel()
        service_ms = (time.perf_counter() - ticket.admitted_at) * 1000.0
        with self._lock:
            self._observe_service_locked(service_ms)
            if self._qos is not None and ticket.tenant is not None:
                self._qos.quotas.release(ticket.tenant)
            self._active.pop(ticket.query_id, None)
            self._release_slot_locked()

    # -- isolation -----------------------------------------------------------
    def register_context(self, ticket: QueryTicket, ctx) -> None:
        """Attach the query's ExecContext so cross-query eviction can
        reach its catalog (and only its catalog)."""
        ticket.ctx = ctx

    def evict_neighbors(self, requester_id: Optional[int]) -> int:
        """Last-resort OOM rung BEFORE the batch-target shrink: spill
        every OTHER active query's spillable device buffers to host.
        The offending query's own buffers were already spilled by the
        first two rungs — neighbors are only touched when that wasn't
        enough. Returns bytes freed; every non-trivial eviction bumps
        ``crossQueryEvictions``."""
        if self._successor is not None:
            return self._current().evict_neighbors(requester_id)
        with self._lock:
            victims = [t for qid, t in self._active.items()
                       if qid != requester_id and t.ctx is not None]
        freed = 0
        for t in victims:
            catalog = getattr(t.ctx, "_catalog", None)
            if catalog is None:
                continue                # lazily unbuilt: nothing to spill
            got = catalog.handle_oom()
            if got > 0:
                freed += got
                _record("crossQueryEvictions")
                faults.record("crossQueryEvictions")
                from spark_rapids_tpu import monitoring
                monitoring.instant(
                    "cross-query-eviction", "recovery",
                    args={"requester": requester_id,
                          "victim": t.query_id, "bytesFreed": got})
        return freed

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    @property
    def queued_count(self) -> int:
        with self._lock:
            if self._qos is not None:
                return len(self._qos.queue)
            return len(self._waiters)

    @property
    def qos(self):
        """The QosPolicy when the QoS subsystem is enabled, else None
        (FIFO mode)."""
        return self._qos


_MANAGER: Optional[QueryManager] = None
_MANAGER_LOCK = threading.Lock()


def _env_max_concurrent() -> Optional[int]:
    v = os.environ.get("SRT_SCHEDULER_MAX_CONCURRENT", "").strip()
    return int(v) if v else None


def _qos_sig(conf) -> Optional[tuple]:
    """The (weights, starvationBound) structural signature when the QoS
    subsystem is enabled for this conf/env, else None (FIFO)."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.parallel import qos as Q
    if not Q.qos_enabled(conf):
        return None
    if conf is not None:
        return (str(conf.get(C.QOS_WEIGHTS)),
                max(int(conf.get(C.QOS_STARVATION_BOUND)), 1))
    return (str(C.QOS_WEIGHTS.default),
            max(int(C.QOS_STARVATION_BOUND.default), 1))


def get_query_manager(conf=None) -> QueryManager:
    """The process-wide manager. Sized from the first conf seen (like
    the TPU semaphore) with the SRT_SCHEDULER_MAX_CONCURRENT env
    override; re-sized from a later conf only while completely idle —
    in-flight queries never see the bound change under them. The QoS
    gate (scheduler.qos.enabled / SRT_QOS) and its structural knobs
    (weights, starvation bound) participate in the same idle-only
    resize, so flipping the subsystem mid-flight is impossible."""
    from spark_rapids_tpu import config as C
    global _MANAGER
    want = None
    if conf is not None:
        want = (max(int(conf.get(C.SCHEDULER_MAX_CONCURRENT)), 1),
                max(int(conf.get(C.SCHEDULER_QUEUE_DEPTH)), 0),
                max(int(conf.get(C.SCHEDULER_ADMISSION_TIMEOUT_MS)), 1))
        env = _env_max_concurrent()
        if env is not None:
            want = (max(env, 1),) + want[1:]

    def build(sizes) -> QueryManager:
        from spark_rapids_tpu.parallel import qos as Q
        sig = _qos_sig(conf)
        policy = Q.QosPolicy(*sig) if sig is not None else None
        return QueryManager(*sizes, qos=policy)

    with _MANAGER_LOCK:
        if _MANAGER is None:
            if want is None:
                env = _env_max_concurrent()
                want = (max(env, 1) if env else 2, 16, 60000)
            _MANAGER = build(want)
        elif want is not None and (
                (_MANAGER.max_concurrent, _MANAGER.queue_depth,
                 _MANAGER.admission_timeout_ms) != want
                or (_MANAGER._qos.sig if _MANAGER._qos is not None
                    else None) != _qos_sig(conf)):
            new_mgr = None
            with _MANAGER._lock:
                idle = not _MANAGER._active and not _MANAGER._waiters \
                    and (_MANAGER._qos is None
                         or len(_MANAGER._qos.queue) == 0)
                if idle:
                    # Idle-check + retirement are ATOMIC under the old
                    # manager's lock: an admit racing this resize either
                    # enqueued first (idle is False, no resize) or sees
                    # the successor and follows the chain — a resize can
                    # never strand a queued ticket in a dead manager.
                    new_mgr = build(want)
                    _MANAGER._successor = new_mgr
            if new_mgr is not None:
                _MANAGER = new_mgr
        return _MANAGER


def note_pressure(score: float, conf=None) -> None:
    """Report a dispatch-funnel pressure observation (ops/base.py's
    collect teardown) to the live manager. No-op before the first query
    ever built one — pressure without a scheduler has nobody to shed."""
    with _MANAGER_LOCK:
        mgr = _MANAGER
    if mgr is not None:
        mgr.note_pressure(score, conf)


# Autoscaler scale-probe (ISSUE 20 brownout interplay): set by
# parallel/cluster/autoscaler.Autoscaler while its loop is live.
# Called with the pressure score at the moment sustained pressure
# would flip brownout ON; returning True means a scale-up was accepted
# (the fleet is below maxWorkers) and the brownout entry defers for
# one more sustain window. None / False / raising = brownout proceeds.
_SCALE_PROBE = None


def register_scale_probe(probe) -> None:
    """Install (or with ``None`` clear) the autoscaler's scale-up
    probe consulted before brownout engages."""
    global _SCALE_PROBE
    _SCALE_PROBE = probe


def backoff_ms(hint_ms: Optional[float], attempt: int, seed: int,
               max_backoff_ms: float) -> float:
    """Deterministic-jitter client backoff: the server's retry hint
    stretched by a per-(client, attempt) jitter in [0, 25%), capped.
    Knuth multiplicative hashing instead of wall-clock randomness keeps
    a thundering herd spread out *reproducibly* — the convergence test
    replays the exact same schedule every run."""
    base = float(hint_ms) if hint_ms and hint_ms > 0 else 250.0
    jitter = (((seed + 1) * 2654435761 + attempt * 40503) % 1000) / 4000.0
    return min(base * (1.0 + jitter), float(max_backoff_ms))


def collect_with_retry(attempt_fn, conf=None,
                       max_attempts: Optional[int] = None,
                       max_backoff_ms: Optional[float] = None,
                       seed: int = 0, sleep=time.sleep):
    """Client-side half of the backpressure contract: run one collect
    attempt; on a :class:`QueryRejectedError` carrying a
    ``retry_after_ms`` hint, back off for the hinted interval (plus
    deterministic per-client jitter, capped at
    ``client.retry.maxBackoffMs``) and resubmit, up to
    ``client.retry.maxAttempts`` total attempts. Rejections WITHOUT a
    hint re-raise immediately — the manager only omits the hint when
    retrying as-is can never help (a deadline the raw cost estimate
    already exceeds). Every deferred resubmission bumps
    ``clientRetries``/``srt_client_retries`` so the soak can prove the
    herd converged instead of hammering."""
    from spark_rapids_tpu import config as C
    if max_attempts is None:
        max_attempts = int(conf.get(C.CLIENT_RETRY_MAX_ATTEMPTS)) \
            if conf is not None \
            else int(C.CLIENT_RETRY_MAX_ATTEMPTS.default)
    if max_backoff_ms is None:
        max_backoff_ms = float(conf.get(C.CLIENT_RETRY_MAX_BACKOFF_MS)) \
            if conf is not None \
            else float(C.CLIENT_RETRY_MAX_BACKOFF_MS.default)
    max_attempts = max(int(max_attempts), 1)
    attempt = 0
    while True:
        try:
            return attempt_fn()
        except QueryRejectedError as e:
            attempt += 1
            if e.retry_after_ms is None or attempt >= max_attempts:
                raise
            delay_ms = backoff_ms(e.retry_after_ms, attempt, seed,
                                  max_backoff_ms)
            _record("clientRetries")
            _record(f"clientRetries.{e.kind}")
            from spark_rapids_tpu.monitoring import telemetry
            if telemetry.enabled():
                telemetry.inc("srt_client_retries", kind=e.kind)
            sleep(delay_ms / 1000.0)


def query_memory_fraction(conf, manager: QueryManager) -> float:
    """Resolved fair-share fraction for one admitted query's catalog
    budget: the explicit conf, or 1/maxConcurrentQueries when 0 (auto)
    and queries can actually overlap."""
    from spark_rapids_tpu import config as C
    frac = float(conf.get(C.SCHEDULER_QUERY_MEMORY_FRACTION))
    if frac <= 0:
        frac = 1.0 / manager.max_concurrent
    return min(max(frac, 0.01), 1.0)


class QueryHandle:
    """Async collect handle (``DataFrame.submit()``): the query runs on
    a daemon worker thread; ``cancel()`` sets the shared cancel event —
    effective both while queued (the admission wait aborts) and
    mid-flight (the next dispatch checkpoint unwinds)."""

    def __init__(self, run_collect, timeout_ms: Optional[float] = None):
        self._cancel = threading.Event()
        self._rows = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def work():
            try:
                self._rows = run_collect(self._cancel, timeout_ms)
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(
            target=work, daemon=True, name="srt-query")
        self._thread.start()

    def cancel(self) -> None:
        self._cancel.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Rows on success; re-raises the query's error (including
        QueryCancelledError / QueryRejectedError) on failure."""
        if not self._done.wait(timeout):
            raise TimeoutError("query still running")
        if self._error is not None:
            raise self._error
        return self._rows
