"""Multi-query admission control, isolation, and cancellation (ISSUE 5).

Every robustness layer before this one (fault/recovery ladder, lineage
recovery, watchdog, pipelined executor) assumed exactly one query in
flight: the TPU semaphore serializes device *partitions*, not *queries*,
each query's buffer catalog believes it owns the whole device budget,
and nothing could cancel, deadline, or shed a query. The reference gets
cross-task isolation for free from Spark's scheduler plus GpuSemaphore
and the owner-tagged RapidsBufferCatalog (SURVEY §2.2); this module is
the single-process re-design of that layer. Four pieces:

1. **Admission control** — :class:`QueryManager` holds a bounded run
   queue (``spark.rapids.sql.scheduler.{maxConcurrentQueries,queueDepth,
   admissionTimeoutMs}``). At most ``maxConcurrentQueries`` collects run
   at once; excess queries wait FIFO in a queue of ``queueDepth``; a
   query arriving with the queue full — or waiting past the admission
   timeout — is SHED with :class:`QueryRejectedError` instead of letting
   unbounded concurrency OOM the device (the reference leans on Spark's
   task scheduler for the same bound).

2. **Per-query resource isolation** — every admitted query gets a
   monotonically increasing query id; its catalog (and every buffer,
   stage output, and kernel-cache reservation it creates) is owner-tagged
   with that id, its device budget is scaled by the fair share
   (``scheduler.queryMemoryFraction``), and the OOM ladder spills the
   *offending* query's buffers (its own catalog) through two rungs
   before :func:`evict_neighbors` touches anyone else's
   (``crossQueryEvictions``). Teardown — success, failure, or cancel —
   closes every owned handle and records the catalog leak report as the
   proof (``ExecContext.last_leak_report``).

3. **Cooperative cancellation + deadlines** — admission issues a
   :class:`faults.QueryToken`; ``DataFrame.collect(timeout_ms=...)``
   arms a deadline timer on it and :meth:`QueryHandle.cancel` sets it
   directly. Every dispatch funnel's ``fault_point`` doubles as a
   cancellation checkpoint, the TPU semaphore acquire and the pipeline's
   ordered wait poll the token, and the watchdog/prefetch/stage worker
   threads inherit it — so a cancelled query unwinds mid-flight with
   :class:`faults.QueryCancelledError`, releasing the semaphore and all
   owned buffers on the way out.

4. **Cross-query fault containment** — faults.py's query-scoped arming
   (``kind@site/query=N``) matches the token's fault tag, so chaos
   tests inject an OOM/stall/lostoutput into query A and assert query
   B's results and recovery counters are bit-identical to a solo run
   (tests/test_scheduler.py).

Counters (process-global here + the per-query ``Scheduler@query``
metrics entry): ``queuedMs``, ``admitted``, ``rejected``, ``cancelled``,
``deadlineKills``, ``crossQueryEvictions``.

``SRT_SCHEDULER_MAX_CONCURRENT=1`` (env) degenerates to strictly serial
queries — byte-identical to the pre-scheduler engine (the CI matrix
proves it over the whole suite).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu import faults

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def _record(name: str, amount: float = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    """Process-global scheduler counters (bench.py's ``scheduler`` JSON
    block)."""
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _COUNTERS.clear()


def metrics_entry(ctx):
    """The per-query Scheduler metrics entry (next to Recovery@query;
    registered level-filter exempt through the ops/base.py audit
    registry)."""
    from spark_rapids_tpu.ops.base import query_metrics_entry
    return query_metrics_entry(ctx, "Scheduler")


def record_plan_cache(ctx, hit: bool) -> None:
    """Per-tenant plan-cache outcome (plan/plan_cache.py) on the query's
    Scheduler@query entry plus the process counters bench.py's
    ``scheduler`` block reports: ``planCacheBindOnly`` executions
    skipped planning entirely (plan once, bind literals, dispatch);
    ``planCacheMiss`` executions paid a template plan this tenant's
    later calls amortize."""
    name = "planCacheBindOnly" if hit else "planCacheMiss"
    metrics_entry(ctx).add(name, 1)
    _record(name)


class QueryRejectedError(RuntimeError):
    """Load shed: the run queue was full, or the admission wait timed
    out. Deliberately NOT a transient error (no retry marker): the
    caller — a serving tier, a test — decides whether to resubmit."""

    def __init__(self, reason: str):
        super().__init__(
            f"REJECTED: {reason} (spark.rapids.sql.scheduler.*)")
        self.reason = reason


class QueryTicket:
    """One admitted query: its token (cancellation handle + owner id),
    admission bookkeeping, and the context registration cross-query
    eviction walks."""

    __slots__ = ("token", "queued_ms", "ctx", "deadline_timer")

    def __init__(self, token: faults.QueryToken, queued_ms: float):
        self.token = token
        self.queued_ms = queued_ms
        self.ctx = None                 # registered by PhysicalPlan.collect
        self.deadline_timer: Optional[threading.Timer] = None

    @property
    def query_id(self) -> int:
        return self.token.query_id

    def arm_deadline(self, timeout_ms: Optional[float]) -> None:
        """Deadline -> the SAME cancel event cancellation uses, so every
        checkpoint/wait tests one flag. The timer thread only sets an
        event — the query unwinds cooperatively at its next checkpoint."""
        if timeout_ms is None or timeout_ms <= 0:
            return
        t = threading.Timer(
            timeout_ms / 1000.0,
            lambda: self.token.request_cancel("deadline exceeded"))
        t.daemon = True
        t.start()
        self.deadline_timer = t

    def cancel(self, reason: str = "cancelled") -> None:
        self.token.request_cancel(reason)


class QueryManager:
    """THE process-wide query scheduler (GpuSemaphore's missing other
    half: admission at QUERY granularity). One instance per process
    (:func:`get_query_manager`); resizable only while idle so tests can
    reconfigure without racing in-flight queries."""

    def __init__(self, max_concurrent: int = 2, queue_depth: int = 16,
                 admission_timeout_ms: int = 60000):
        self.max_concurrent = max(int(max_concurrent), 1)
        self.queue_depth = max(int(queue_depth), 0)
        self.admission_timeout_ms = max(int(admission_timeout_ms), 1)
        self._lock = threading.Lock()
        self._slots_free = self.max_concurrent
        self._waiters: List[threading.Event] = []   # FIFO run queue
        self._active: Dict[int, QueryTicket] = {}
        self._next_id = 0

    # -- admission -----------------------------------------------------------
    def admit(self, conf=None,
              cancel: Optional[threading.Event] = None) -> QueryTicket:
        """Block until a run slot frees (FIFO), up to the admission
        timeout; raise :class:`QueryRejectedError` immediately when the
        queue is full (load shed) or on timeout. ``cancel`` (the
        eventual query's cancel event, when the caller pre-creates it
        for a handle) aborts the wait too — a queued query is
        cancellable before it ever runs."""
        from spark_rapids_tpu import config as C
        tag = None
        if conf is not None:
            t = int(conf.get(C.TEST_FAULTS_QUERY_TAG))
            if t >= 0:
                tag = t
        me: Optional[threading.Event] = None
        t0 = time.perf_counter()
        with self._lock:
            if self._slots_free > 0 and not self._waiters:
                self._slots_free -= 1
                return self._issue(tag, 0.0, cancel)
            if len(self._waiters) >= self.queue_depth:
                _record("rejected")
                from spark_rapids_tpu import monitoring
                monitoring.instant("query-rejected", "recovery",
                                   args={"reason": "queue full"})
                raise QueryRejectedError(
                    f"run queue full ({len(self._waiters)} queued, "
                    f"{self.max_concurrent} running)")
            me = threading.Event()
            self._waiters.append(me)
        deadline = t0 + self.admission_timeout_ms / 1000.0
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0 or (cancel is not None and cancel.is_set()):
                with self._lock:
                    if me in self._waiters:
                        self._waiters.remove(me)
                    elif me.is_set():
                        # Granted between the timeout and the lock: the
                        # slot is ours to give back.
                        self._release_slot_locked()
                from spark_rapids_tpu import monitoring
                if cancel is not None and cancel.is_set():
                    _record("cancelled")
                    monitoring.instant(
                        "query-cancelled", "recovery",
                        args={"reason": "cancelled while queued"})
                    raise faults.QueryCancelledError(
                        -1, "cancelled while queued")
                _record("rejected")
                monitoring.instant("query-rejected", "recovery",
                                   args={"reason": "admission timeout"})
                raise QueryRejectedError(
                    f"admission timeout after "
                    f"{self.admission_timeout_ms}ms "
                    f"({self.max_concurrent} running)")
            if me.wait(min(remaining, 0.05)):
                with self._lock:
                    queued_ms = (time.perf_counter() - t0) * 1000.0
                    return self._issue(tag, queued_ms, cancel)

    def _issue(self, tag: Optional[int], queued_ms: float,
               cancel: Optional[threading.Event]) -> QueryTicket:
        """Build the admitted ticket (caller holds the lock / the slot)."""
        self._next_id += 1
        token = faults.QueryToken(self._next_id, tag)
        if cancel is not None:
            # The handle pre-created the cancel event (so cancel() works
            # while still queued); the token adopts it.
            token.cancel = cancel
        ticket = QueryTicket(token, queued_ms)
        self._active[token.query_id] = ticket
        _record("admitted")
        _record("queuedMs", queued_ms)
        # Retro-record the admission wait as a "queued" span on the
        # query's OWN track: the id the wait was for only exists now.
        from spark_rapids_tpu import monitoring
        if monitoring.enabled():
            dur = int(queued_ms * 1e6)
            monitoring.record_span(
                "admission-queue", "queued", monitoring.now_ns() - dur,
                dur, qid=token.query_id,
                args={"queuedMs": round(queued_ms, 2)},
                level=monitoring.LEVEL_QUERY)
        return ticket

    def _release_slot_locked(self) -> None:
        if self._waiters:
            self._waiters.pop(0).set()      # hand the slot over, FIFO
        else:
            self._slots_free += 1

    def finish(self, ticket: QueryTicket) -> None:
        """Query teardown (success, failure, or cancel): release the run
        slot, wake the next queued query, disarm the deadline."""
        if ticket.deadline_timer is not None:
            ticket.deadline_timer.cancel()
        with self._lock:
            self._active.pop(ticket.query_id, None)
            self._release_slot_locked()

    # -- isolation -----------------------------------------------------------
    def register_context(self, ticket: QueryTicket, ctx) -> None:
        """Attach the query's ExecContext so cross-query eviction can
        reach its catalog (and only its catalog)."""
        ticket.ctx = ctx

    def evict_neighbors(self, requester_id: Optional[int]) -> int:
        """Last-resort OOM rung BEFORE the batch-target shrink: spill
        every OTHER active query's spillable device buffers to host.
        The offending query's own buffers were already spilled by the
        first two rungs — neighbors are only touched when that wasn't
        enough. Returns bytes freed; every non-trivial eviction bumps
        ``crossQueryEvictions``."""
        with self._lock:
            victims = [t for qid, t in self._active.items()
                       if qid != requester_id and t.ctx is not None]
        freed = 0
        for t in victims:
            catalog = getattr(t.ctx, "_catalog", None)
            if catalog is None:
                continue                # lazily unbuilt: nothing to spill
            got = catalog.handle_oom()
            if got > 0:
                freed += got
                _record("crossQueryEvictions")
                faults.record("crossQueryEvictions")
                from spark_rapids_tpu import monitoring
                monitoring.instant(
                    "cross-query-eviction", "recovery",
                    args={"requester": requester_id,
                          "victim": t.query_id, "bytesFreed": got})
        return freed

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    @property
    def queued_count(self) -> int:
        with self._lock:
            return len(self._waiters)


_MANAGER: Optional[QueryManager] = None
_MANAGER_LOCK = threading.Lock()


def _env_max_concurrent() -> Optional[int]:
    v = os.environ.get("SRT_SCHEDULER_MAX_CONCURRENT", "").strip()
    return int(v) if v else None


def get_query_manager(conf=None) -> QueryManager:
    """The process-wide manager. Sized from the first conf seen (like
    the TPU semaphore) with the SRT_SCHEDULER_MAX_CONCURRENT env
    override; re-sized from a later conf only while completely idle —
    in-flight queries never see the bound change under them."""
    from spark_rapids_tpu import config as C
    global _MANAGER
    want = None
    if conf is not None:
        want = (max(int(conf.get(C.SCHEDULER_MAX_CONCURRENT)), 1),
                max(int(conf.get(C.SCHEDULER_QUEUE_DEPTH)), 0),
                max(int(conf.get(C.SCHEDULER_ADMISSION_TIMEOUT_MS)), 1))
        env = _env_max_concurrent()
        if env is not None:
            want = (max(env, 1),) + want[1:]
    with _MANAGER_LOCK:
        if _MANAGER is None:
            if want is None:
                env = _env_max_concurrent()
                want = (max(env, 1) if env else 2, 16, 60000)
            _MANAGER = QueryManager(*want)
        elif want is not None and (
                _MANAGER.max_concurrent, _MANAGER.queue_depth,
                _MANAGER.admission_timeout_ms) != want:
            with _MANAGER._lock:
                idle = not _MANAGER._active and not _MANAGER._waiters
            if idle:
                _MANAGER = QueryManager(*want)
        return _MANAGER


def query_memory_fraction(conf, manager: QueryManager) -> float:
    """Resolved fair-share fraction for one admitted query's catalog
    budget: the explicit conf, or 1/maxConcurrentQueries when 0 (auto)
    and queries can actually overlap."""
    from spark_rapids_tpu import config as C
    frac = float(conf.get(C.SCHEDULER_QUERY_MEMORY_FRACTION))
    if frac <= 0:
        frac = 1.0 / manager.max_concurrent
    return min(max(frac, 0.01), 1.0)


class QueryHandle:
    """Async collect handle (``DataFrame.submit()``): the query runs on
    a daemon worker thread; ``cancel()`` sets the shared cancel event —
    effective both while queued (the admission wait aborts) and
    mid-flight (the next dispatch checkpoint unwinds)."""

    def __init__(self, run_collect, timeout_ms: Optional[float] = None):
        self._cancel = threading.Event()
        self._rows = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()

        def work():
            try:
                self._rows = run_collect(self._cancel, timeout_ms)
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(
            target=work, daemon=True, name="srt-query")
        self._thread.start()

    def cancel(self) -> None:
        self._cancel.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Rows on success; re-raises the query's error (including
        QueryCancelledError / QueryRejectedError) on failure."""
        if not self._done.wait(timeout):
            raise TimeoutError("query still running")
        if self._error is not None:
            raise self._error
        return self._rows
