"""Pipelined partition executor: overlap host decode/encode with device
dispatch (ISSUE 4).

The reference wins much of its throughput from *overlap*, not kernels:
the MULTITHREADED reader (GpuParquetScan.scala:1144) decodes files on a
host thread pool while the device consumes earlier batches, and
GpuSemaphore admits a bounded number of tasks so the device stays
saturated without oversubscribing HBM. This engine's partition loops
(``Exec.collect``, exchange map-side materialization, broadcast collect)
used to run strictly serially: host Arrow decode, filter-stat pruning,
wire encode, ``device_put`` and TPU compute never overlapped.

Two cooperating pieces fix that:

1. **Partition pipeline** (:func:`open_pipeline`): a bounded host thread
   pool runs the *separable host half* of each partition — everything an
   ``Exec.prefetch_host`` hook can do before ``device_put`` (scan-unit
   decode, stats pruning, wire encode; columnar/wire.py documents the
   encode half as thread-safe CPU-only work) — ``prefetchPartitions``
   ahead of a single ordered consumer that performs all device dispatch.
   Results therefore stay deterministically ordered, upload of partition
   p+1 overlaps compute of p, and faults raised on prefetch threads are
   captured and re-raised at the ordered consumption point, so the OOM
   ladder / stage recompute / transient retry demotion order (PR 2-3) is
   unchanged. Watchdog deadlines wrap the consumer's per-partition wait:
   ``_take`` polls the attempt's cancel event, and a killed attempt
   cancels its partition's prefetch so injected stalls unwind instead of
   lingering.

2. **Concurrent independent stages** (:func:`prematerialize_stages`):
   PR 3's stage DAG (parallel/stages.py) names the plan's exchange
   boundaries; stages whose parents are all materialized are independent,
   so e.g. the build- and probe-side scans of a join materialize their
   exchange outputs in parallel (bounded by
   ``pipeline.maxConcurrentStages``; device dispatch stays inside the
   query's TPU-semaphore permit). Waves run bottom-up with a barrier per
   wave, and a wave's first error (smallest stage id — deterministic) is
   re-raised to the planner ladder exactly as the serial pull would have
   raised it.

``spark.rapids.sql.pipeline.enabled=false`` or ``SRT_PIPELINE=0``
restores today's serial dispatch byte-for-byte: :func:`open_pipeline`
then returns the no-op serial pipeline and no thread is ever created.

Counters (process-global here + the per-query ``Pipeline@query`` metrics
entry, surfaced by ``DataFrame.metrics()`` and bench.py's JSON):
``hostPrefetchMs``, ``consumerWaitMs``, ``pipelineStalls``,
``prefetchedPartitions``, ``concurrentStages`` and the derived
``overlapRatio`` (fraction of host-prefetch time the consumer did NOT
wait for — 0 means the pipeline degenerated to serial, 1 means decode
was entirely hidden behind device work).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import os
import threading
import time
from typing import Dict, List, Optional

_LOG = logging.getLogger("spark_rapids_tpu.pipeline")

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def _record(ctx, name: str, amount: float) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount
    if ctx is not None:
        metrics_entry(ctx).add(name, amount)


def record(ctx, name: str, amount: float) -> None:
    """Public counter hook for prefetch-side producers (the scan's
    wire-encode + staging-buffer pack, io/scan.py): counts land in both
    the process-global pipeline counters and the per-query
    ``Pipeline@query`` metrics entry. With the ingest fast path the
    prefetch pool stages fully-packed upload buffers
    (``stagingBytesPrefetched``), so the ordered consumer's only work
    per partition is device_put transfers + jitted decode dispatches."""
    _record(ctx, name, amount)


def counters() -> Dict[str, float]:
    """Process-global pipeline counters (bench.py's ``pipeline`` JSON
    block), with the derived overlapRatio folded in."""
    with _COUNTER_LOCK:
        out = dict(_COUNTERS)
    return _with_overlap_ratio(out)


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _COUNTERS.clear()


def _with_overlap_ratio(vals: Dict[str, float]) -> Dict[str, float]:
    prefetch = vals.get("hostPrefetchMs", 0.0)
    if prefetch > 0:
        waited = min(vals.get("consumerWaitMs", 0.0), prefetch)
        vals["overlapRatio"] = round(1.0 - waited / prefetch, 4)
    return vals


def metrics_entry(ctx):
    """The per-query Pipeline metrics entry (next to Recovery@query;
    registered level-filter exempt through the ops/base.py audit
    registry)."""
    from spark_rapids_tpu.ops.base import query_metrics_entry
    return query_metrics_entry(ctx, "Pipeline")


def finalize_metrics(ctx) -> None:
    """Recompute the query-scoped overlapRatio from the entry's
    cumulative ms counters (a ratio cannot accumulate additively across
    the query's pipelines)."""
    m = ctx.metrics.get("Pipeline@query")
    if m is not None:
        with m._lock:
            _with_overlap_ratio(m.values)


@dataclasses.dataclass(frozen=True)
class PipelineParams:
    prefetch_partitions: int
    host_threads: int
    max_concurrent_stages: int


def params_of(conf) -> Optional[PipelineParams]:
    """Resolved pipeline parameters, or None when the pipeline is off
    (conf or the SRT_PIPELINE=0 escape hatch — the serial path then runs
    exactly as before)."""
    from spark_rapids_tpu import config as C
    if os.environ.get("SRT_PIPELINE", "").strip() == "0":
        return None
    if not bool(conf.get(C.PIPELINE_ENABLED)):
        return None
    return PipelineParams(
        prefetch_partitions=max(
            int(conf.get(C.PIPELINE_PREFETCH_PARTITIONS)), 1),
        host_threads=max(int(conf.get(C.PIPELINE_HOST_THREADS)), 1),
        max_concurrent_stages=max(
            int(conf.get(C.PIPELINE_MAX_CONCURRENT_STAGES)), 1))


# ---------------------------------------------------------------------------
# Partition pipeline
# ---------------------------------------------------------------------------

class _ConsumeCancelled(RuntimeError):
    """The watchdog killed the consuming attempt while it waited on a
    prefetch; the abandoned attempt thread unwinds on this (the watchdog
    already discarded the attempt, so nobody observes it)."""


class _Slot:
    __slots__ = ("future", "cancel", "consumed")

    def __init__(self, future, cancel):
        self.future = future
        self.cancel = cancel
        self.consumed = False


class _SerialPipeline:
    """The disabled pipeline: ``consume`` runs the partition inline with
    zero threads, zero buffering, zero counters — today's serial path."""

    def consume(self, partition: int, fn):
        return fn()

    def close(self):
        pass


class PartitionPipeline:
    """Bounded producer/consumer over one partition loop.

    Producers run ``source.prefetch_host(ctx, p)`` for partitions up to
    ``prefetch_partitions`` ahead of the consumer; the consumer calls
    :meth:`consume` in strict partition order from ONE thread (the
    calling/watchdog thread), so device dispatch order — and therefore
    result order — is identical to the serial path."""

    def __init__(self, ctx, source, nparts: int, params: PipelineParams):
        from spark_rapids_tpu import faults
        self._ctx = ctx
        self._source = source
        self._nparts = nparts
        self._depth = params.prefetch_partitions
        self._sink = faults.get_recovery_sink()
        self._token = faults.get_query_token()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(params.host_threads, max(nparts, 1)),
            thread_name_prefix="srt-prefetch")
        self._slots: Dict[int, _Slot] = {}
        self._submitted = -1
        self._closed = False

    # -- producers -----------------------------------------------------------
    def _prefetch_task(self, partition: int, cancel) -> None:
        from spark_rapids_tpu import faults, monitoring
        faults.set_recovery_sink(self._sink)
        faults.set_query_token(self._token)
        faults.set_cancel_event(cancel)
        t0 = time.perf_counter()
        try:
            if not cancel.is_set():
                with monitoring.span("prefetch", "host-prefetch",
                                     args={"partition": partition}):
                    self._source.prefetch_host(self._ctx, partition)
        finally:
            faults.set_cancel_event(None)
            faults.set_query_token(None)
            faults.set_recovery_sink(None)
            _record(self._ctx, "hostPrefetchMs",
                    (time.perf_counter() - t0) * 1000.0)
            _record(self._ctx, "prefetchedPartitions", 1)

    def _ensure_submitted(self, upto: int) -> None:
        upto = min(upto, self._nparts - 1)
        while self._submitted < upto:
            self._submitted += 1
            p = self._submitted
            cancel = threading.Event()
            fut = self._pool.submit(self._prefetch_task, p, cancel)
            self._slots[p] = _Slot(fut, cancel)

    # -- the ordered consumer ------------------------------------------------
    def _take(self, partition: int) -> None:
        """Block (cancellably) until partition's host half is done;
        re-raise any prefetch-thread fault HERE — the ordered consumption
        point — so recovery sees it exactly where the serial path would
        have raised it."""
        from spark_rapids_tpu import faults
        self._ensure_submitted(partition + self._depth)
        slot = self._slots.get(partition)
        if slot is None or slot.consumed:
            return                      # re-dispatch after a kill: inline
        slot.consumed = True
        fut = slot.future
        wait_span = None
        if not fut.done():
            _record(self._ctx, "pipelineStalls", 1)
            # The ordered consumer actually blocked on this partition's
            # host half: that wait is queue time, on the trace timeline.
            from spark_rapids_tpu import monitoring
            wait_span = monitoring.span("pipeline-wait", "queued",
                                        args={"partition": partition})
            wait_span.__enter__()
        t0 = time.perf_counter()
        try:
            while True:
                try:
                    fut.result(timeout=0.05)
                    return
                except concurrent.futures.TimeoutError:
                    if fut.done():
                        raise   # the TASK raised TimeoutError, not the poll
                    # Query cancel/deadline: stop waiting, cancel the
                    # prefetch, and unwind at this ordered point — the
                    # same place a prefetch fault would have surfaced.
                    tok = faults.get_query_token()
                    if tok is not None and tok.cancelled():
                        slot.cancel.set()
                        raise tok.error()
                    wd_cancel = faults.get_cancel_event()
                    if wd_cancel is not None and wd_cancel.is_set():
                        # Watchdog killed this attempt: cancel the
                        # partition's prefetch (unwinds injected stalls)
                        # and unwind the abandoned attempt thread.
                        slot.cancel.set()
                        raise _ConsumeCancelled(
                            f"partition {partition} consume cancelled")
        except (_ConsumeCancelled, faults.QueryCancelledError):
            raise
        except BaseException:
            if slot.cancel.is_set():
                # The error is the prefetch unwinding on OUR cancel (a
                # killed stall): the re-dispatched attempt recomputes
                # inline, matching the serial watchdog-retry semantics.
                return
            raise
        finally:
            if wait_span is not None:
                wait_span.__exit__(None, None, None)
            waited = (time.perf_counter() - t0) * 1000.0
            if waited > 0:
                _record(self._ctx, "consumerWaitMs", waited)

    def consume(self, partition: int, fn):
        """Wait for partition's prefetch (if any), then run ``fn`` — the
        device half — on the calling thread."""
        self._take(partition)
        return fn()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for slot in self._slots.values():
            slot.cancel.set()
        self._pool.shutdown(wait=False, cancel_futures=True)
        # Drop unconsumed prefetch buffers (a failed/cancelled collect
        # must not leave encoded partitions pinned in the context).
        stale = {str(p) for p, s in self._slots.items()
                 if not s.consumed and s.future.done()}
        if stale:
            for key in [k for k in list(self._ctx.cache)
                        if isinstance(k, str)
                        and k.startswith("scan-prefetch:")
                        and k.rsplit(":", 1)[-1] in stale]:
                self._ctx.cache.pop(key, None)
        finalize_metrics(self._ctx)


def open_pipeline(ctx, source, nparts: int):
    """A :class:`PartitionPipeline` for this partition loop, or the
    serial no-op when the pipeline is disabled, the loop is trivial
    (``nparts <= 1`` gives nothing to overlap), or the subtree exposes no
    separable host half."""
    params = params_of(ctx.conf)
    if params is None or nparts <= 1 or not source.host_prefetchable():
        return _SerialPipeline()
    return PartitionPipeline(ctx, source, nparts, params)


# ---------------------------------------------------------------------------
# Concurrent independent stages
# ---------------------------------------------------------------------------

def prematerialize_stages(ctx, root) -> None:
    """Materialize independent stages' exchange outputs concurrently.

    Stages run in bottom-up waves: a stage is ready when every parent
    (upstream) stage's output is materialized. Waves of one run inline
    (zero overhead — the lazy pull would do the same work); larger waves
    fan out on threads bounded by ``pipeline.maxConcurrentStages``.
    Every materialization is idempotent against the context cache, so a
    ladder-recovered re-collect re-runs only what was invalidated."""
    params = params_of(ctx.conf)
    if params is None or params.max_concurrent_stages <= 1:
        return
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory.oom import (get_active_catalog,
                                             set_active_catalog)
    from spark_rapids_tpu.ops.base import _watchdog_params
    from spark_rapids_tpu.parallel import stages as S
    graph = S.build_stage_graph(root)
    runnable = {st.stage_id: st for st in graph.stages.values()
                if st.boundary is not None
                and callable(getattr(st.boundary, "stage_prematerialize",
                                     None))}
    if len(runnable) < 2:
        return
    wd = _watchdog_params(ctx.conf)
    catalog = get_active_catalog()
    sink = faults.get_recovery_sink()
    token = faults.get_query_token()

    def run_stage(st):
        from spark_rapids_tpu import monitoring

        def materialize():
            st.boundary.stage_prematerialize(ctx)
        with monitoring.span(st.name, "stage",
                             level=monitoring.LEVEL_QUERY):
            if wd is None:
                materialize()
            else:
                st.boundary._watchdog_run(ctx, wd, st.name,
                                          materialize)

    def run_stage_threaded(st):
        set_active_catalog(catalog)
        faults.set_recovery_sink(sink)
        faults.set_query_token(token)
        try:
            run_stage(st)
        finally:
            faults.set_query_token(None)
            faults.set_recovery_sink(None)

    done: set = set()
    pending = dict(runnable)
    while pending:
        # Ready = every parent stage's output already materialized. A
        # stage with a non-prematerializable parent (e.g. a mesh
        # exchange) never becomes ready and materializes lazily in the
        # consumer instead — running it here could double-materialize
        # the shared lazy parent from two threads.
        wave = sorted((st for st in pending.values()
                       if all(pid in done for pid in st.parents)),
                      key=lambda st: st.stage_id)
        if not wave:
            break
        for st in wave:
            pending.pop(st.stage_id)
        if len(wave) == 1:
            run_stage(wave[0])
        else:
            _record(ctx, "concurrentStages", len(wave))
            errors: Dict[int, BaseException] = {}
            nworkers = min(params.max_concurrent_stages, len(wave))
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=nworkers,
                    thread_name_prefix="srt-stage") as pool:
                futs = {st.stage_id: pool.submit(run_stage_threaded, st)
                        for st in wave}
                for sid, fut in futs.items():
                    try:
                        fut.result()
                    except BaseException as e:
                        errors[sid] = e
            if errors:
                # Deterministic choice: the smallest stage id is the one
                # the serial pull order would have hit first.
                raise errors[min(errors)]
        done.update(st.stage_id for st in wave)
