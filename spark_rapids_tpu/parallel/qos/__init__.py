"""Serving QoS subsystem (ISSUE 14): cost-aware scheduling for the
multi-query admission layer.

Replaces the QueryManager's FIFO run queue — when enabled — with:

- **Priority classes** ``interactive`` / ``batch`` / ``background``
  (per query via conf or the ``priority=`` kwarg of
  ``DataFrame.collect/submit``), drained by weighted fair queueing
  with a configurable weight vector and a HARD starvation bound
  (policy.py).
- **Shortest-job-first within a class** using the plan/cost.py
  estimate; plan-cache hits reuse the template's CostReport so the
  ordering key is free for repeat shapes.
- **Per-tenant quotas** — in-flight query caps, owner-tagged catalog
  bytes, kernel-cache compile budgets (quotas.py).
- **Deadline-aware admission** — a query whose estimate cannot meet
  its ``timeout_ms`` deadline is rejected at admit time (admission.py).

Default OFF: ``spark.rapids.sql.scheduler.qos.enabled`` (conf wins) or
``SRT_QOS=1`` (env, the CI matrix hook). Disabled, the QueryManager's
FIFO path is byte-for-byte the pre-QoS scheduler — the ``qos-on``
tier-1 matrix entry proves the whole suite passes identically with the
subsystem live.

See docs/serving.md for the model and the 1000-query soak contract.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

from spark_rapids_tpu.parallel.qos.admission import QosPolicy
from spark_rapids_tpu.parallel.qos.policy import (CLASS_RANK, CLASSES,
                                                  DEFAULT_CLASS, WfqQueue,
                                                  parse_weights,
                                                  resolve_class)
from spark_rapids_tpu.parallel.qos.quotas import (DEFAULT_TENANT,
                                                  TenantQuotas,
                                                  resolve_tenant)

__all__ = [
    "CLASSES", "CLASS_RANK", "DEFAULT_CLASS", "DEFAULT_TENANT",
    "QosPolicy", "TenantQuotas", "WfqQueue", "counters", "parse_weights",
    "qos_enabled", "reset_counters", "resolve_class", "resolve_tenant",
]

_COUNTER_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def _record(name: str, amount: float = 1) -> None:
    with _COUNTER_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    """Process-global QoS counters (bench.py's ``qos`` JSON block):
    per-class admissions (``admitted.<class>``), rejections by kind
    (``rejected.queue-full`` / ``rejected.tenant-quota`` /
    ``rejected.deadline-unmeetable`` / ``rejected.admission-timeout``),
    ``starvationBoundEngagements``, ``quotaEvictions``, and per-tenant
    plan-cache outcomes (``planCacheHit.<tenant>`` /
    ``planCacheMiss.<tenant>``)."""
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _COUNTER_LOCK:
        _COUNTERS.clear()


def qos_enabled(conf=None) -> bool:
    """Conf key wins; else the SRT_QOS env (CI matrix hook); else the
    registered default (False) — the cost_enabled/plan_cache_enabled
    gate pattern."""
    from spark_rapids_tpu import config as C
    if conf is not None and conf.raw.get(C.QOS_ENABLED.key) is not None:
        return bool(conf.get(C.QOS_ENABLED))
    env = os.environ.get("SRT_QOS")
    if env is not None:
        return env.strip() not in ("", "0", "false", "no")
    return bool(C.QOS_ENABLED.default)
