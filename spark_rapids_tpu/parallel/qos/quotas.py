"""Per-tenant quotas (ISSUE 14 tentpole part c).

A *tenant* is a serving-tier identity — one user, one client pool, one
product — named by ``spark.rapids.sql.scheduler.qos.tenant`` (or the
``tenant=`` kwarg of ``DataFrame.collect/submit``). The tracker holds
three admission-time caps, all default-unlimited (0):

- **In-flight queries** (``tenantMaxInFlight``): running + queued
  queries of the tenant; checked before the query ever enters the run
  queue, so one tenant cannot monopolize the queue depth either.
- **Catalog bytes** (``tenantMaxCatalogBytes``): the sum of the
  tenant's active queries' owner-tagged catalog registrations
  (:meth:`BufferCatalog.owned_bytes` — the per-query accounting view
  the scheduler's isolation tests already assert on). A tenant sitting
  on that many spillable bytes is rejected until its queries retire.
- **Kernel-cache entries** (``tenantMaxKernelCacheEntries``): compiled
  kernels whose owner tag (:meth:`KernelCache.owners`) maps to one of
  the tenant's query ids. Over the cap the tenant's OLDEST entries are
  EVICTED (counter ``quotaEvictions``) rather than the query rejected —
  compilation quota is a cache budget, not an admission failure.

Ownership attribution: the kernel cache tags entries with the query id
that paid the compile, so the tracker keeps a persistent
``query id -> tenant`` map (entries outlive the query that compiled
them). Bounded: ids whose entries left the cache are pruned on sweep.

Pure bookkeeping: the QueryManager's lock covers every mutation.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

DEFAULT_TENANT = "default"


def resolve_tenant(name: Optional[str]) -> str:
    v = str(name).strip() if name else ""
    return v or DEFAULT_TENANT


class TenantQuotas:
    """In-flight reservations + owner attribution for one QueryManager."""

    def __init__(self):
        self._inflight: Dict[str, int] = {}
        self._qid_tenant: Dict[int, str] = {}

    # -- in-flight reservations ----------------------------------------------
    def inflight(self, tenant: str) -> int:
        return self._inflight.get(tenant, 0)

    def reserve(self, tenant: str) -> None:
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1

    def release(self, tenant: str) -> None:
        n = self._inflight.get(tenant, 0) - 1
        if n <= 0:
            self._inflight.pop(tenant, None)
        else:
            self._inflight[tenant] = n

    # -- ownership attribution -----------------------------------------------
    def record_query(self, query_id: int, tenant: str) -> None:
        """Remember which tenant an issued query id belongs to; kernel
        cache entries it compiles stay attributable after it retires."""
        self._qid_tenant[query_id] = tenant

    def tenant_of(self, query_id: Optional[int]) -> Optional[str]:
        if query_id is None:
            return None
        return self._qid_tenant.get(query_id)

    def query_ids(self, tenant: str) -> set:
        return {qid for qid, t in self._qid_tenant.items() if t == tenant}

    def prune(self, live_query_ids: Iterable) -> None:
        """Drop attribution for ids with no remaining kernel-cache
        entries and no active ticket (bounds the map)."""
        keep = set(live_query_ids)
        for qid in [q for q in self._qid_tenant if q not in keep]:
            self._qid_tenant.pop(qid, None)

    # -- catalog bytes -------------------------------------------------------
    @staticmethod
    def catalog_bytes(tickets) -> int:
        """Owner-tagged registered bytes across the given tickets'
        contexts (each admitted query owns its own catalog; the owner
        tag is its query id)."""
        total = 0
        for t in tickets:
            ctx = getattr(t, "ctx", None)
            catalog = getattr(ctx, "_catalog", None)
            if catalog is None:
                continue
            owned = catalog.owned_bytes()
            total += owned.get(t.query_id, 0)
        return total

    # -- kernel-cache entries ------------------------------------------------
    def kernel_entries(self, tenant: str, owners: Dict) -> int:
        """How many kernel-cache entries the tenant's query ids own.
        ``owners`` is :meth:`KernelCache.owners` (key -> query id)."""
        qids = self.query_ids(tenant)
        return sum(1 for qid in owners.values() if qid in qids)
