"""Cost-aware admission policy (ISSUE 14 tentpole parts c/d).

:class:`QosPolicy` is the object a QoS-enabled QueryManager carries in
place of its FIFO waiter list: the WFQ run queue (policy.py), the
per-tenant quota tracker (quotas.py), and the admission-time checks —
tenant caps and the deadline feasibility test — that run BEFORE a query
ever takes a queue slot. All methods are called under the manager's
lock unless noted.

Deadline-aware admission: ``collect(timeout_ms=...)`` already arms a
kill timer; with QoS on the SAME deadline is tested against the
plan/cost.py estimate at admit time — a query whose estimated
device+host time (scaled by ``qos.deadlineSlack``) cannot fit its
deadline is rejected IMMEDIATELY (kind ``deadline-unmeetable``) instead
of burning a run slot and device time only to be deadline-killed
mid-flight. Un-priced queries (cost model off/skipped) always pass —
the in-flight kill timer remains the backstop.

Retry-after hints: every load-type rejection carries an estimate of
when resubmitting could succeed, derived from the manager's EWMA of
observed query service times. Deadline rejections carry
``retry_after_ms=None`` — retrying the same query with the same
deadline can never help.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.parallel.qos.policy import WfqQueue, parse_weights
from spark_rapids_tpu.parallel.qos.quotas import TenantQuotas


class QosPolicy:
    """Everything a QueryManager needs beyond FIFO, in one handle."""

    def __init__(self, weights_spec: str, starvation_bound: int):
        self.weights_spec = str(weights_spec)
        self.queue = WfqQueue(parse_weights(weights_spec), starvation_bound)
        self.quotas = TenantQuotas()

    @property
    def sig(self):
        """Structural identity for the idle-only manager resize check."""
        return (self.weights_spec, self.queue.starvation_bound)

    # -- admission checks (caller holds the manager lock) --------------------
    def deadline_rejects(self, conf, cost_ms: Optional[float],
                         deadline_ms: Optional[float]) -> Optional[str]:
        """The rejection reason when the cost estimate cannot meet the
        deadline, else None (admit).

        The manager splits the resulting rejection's ``retry_after_ms``
        on WHY it was unmeetable: when only the load-scaled slack broke
        the deadline (``cost_ms <= deadline_ms < cost_ms * slack``) a
        resubmission against a drained queue can succeed, so the hint
        is carried; when the RAW estimate already exceeds the deadline
        the hint is None — retrying as-is can never help, and
        ``collect_with_retry`` re-raises immediately."""
        from spark_rapids_tpu import config as C
        if deadline_ms is None or deadline_ms <= 0 or cost_ms is None:
            return None
        if not bool(conf.get(C.QOS_DEADLINE_ADMISSION)):
            return None
        slack = max(float(conf.get(C.QOS_DEADLINE_SLACK)), 0.0)
        est = cost_ms * slack
        if est > deadline_ms:
            return (f"deadline {deadline_ms:.0f}ms unmeetable: cost "
                    f"estimate {est:.0f}ms (qos.deadlineSlack applied)")
        return None

    def tenant_rejects(self, conf, tenant: str,
                       active_tickets) -> Optional[str]:
        """The rejection reason when the tenant is over an admission
        cap (in-flight queries or catalog bytes), else None."""
        from spark_rapids_tpu import config as C
        cap = int(conf.get(C.QOS_TENANT_MAX_IN_FLIGHT))
        if cap > 0 and self.quotas.inflight(tenant) >= cap:
            return (f"tenant {tenant!r} at in-flight cap "
                    f"({self.quotas.inflight(tenant)}/{cap})")
        bcap = int(conf.get(C.QOS_TENANT_MAX_CATALOG_BYTES))
        if bcap > 0:
            mine = [t for t in active_tickets
                    if getattr(t, "tenant", None) == tenant]
            used = self.quotas.catalog_bytes(mine)
            if used >= bcap:
                return (f"tenant {tenant!r} at catalog-bytes cap "
                        f"({used}/{bcap} owner-tagged bytes)")
        return None

    def enforce_kernel_quota(self, conf, tenant: str) -> int:
        """Kernel-cache compile quota: evict the tenant's OLDEST cache
        entries down to the cap (never rejects — a compile budget is a
        cache budget). Returns evicted count. Takes the cache's own
        lock; call OUTSIDE hot paths only (admission)."""
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.ops import kernel_cache as KC
        cap = int(conf.get(C.QOS_TENANT_MAX_KERNEL_ENTRIES))
        if cap <= 0:
            return 0
        cache = KC.cache()
        have = self.quotas.kernel_entries(tenant, cache.owners())
        if have <= cap:
            return 0
        return cache.evict_owned(self.quotas.query_ids(tenant), keep=cap)
