"""Priority classes + weighted fair queueing with a hard starvation
bound (ISSUE 14 tentpole part a/b).

Three fixed priority classes — ``interactive`` > ``batch`` >
``background`` — drain through :class:`WfqQueue`, a stride scheduler:
every class carries a virtual time that advances by ``1/weight`` each
time it is served, and the next run slot goes to the non-empty class
with the SMALLEST virtual time (ties break by class rank). Service is
therefore proportional to the weight vector over any window, and the
drain order is a pure function of the arrival schedule — no clocks, no
randomness — which is what the determinism tests pin.

On top of the stride ordering sits a HARD starvation bound: every time
a non-empty class is passed over for a dispatch its bypass counter
ticks; once any class has been bypassed ``starvation_bound`` times in a
row its head runs NEXT regardless of virtual time (the engagement is
counted — bench.py reports it). With weights like 100:1:1 the stride
schedule alone would make background wait ~100 grants between services;
the bound caps that wait absolutely.

Within a class, entries drain shortest-job-first by the plan/cost.py
estimate (``CostReport.est_device_ms + est_host_ms``; plan-cache hits
reuse the template's report so the lookup is free for repeat shapes).
Un-priced queries (cost model off or skipped) order after every priced
one, FIFO among themselves — the class-level starvation bound still
guarantees the class progresses.

Pure data structure: no locks (the QueryManager's lock covers it), no
engine imports.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Tuple

CLASSES: Tuple[str, ...] = ("interactive", "batch", "background")
CLASS_RANK: Dict[str, int] = {name: i for i, name in enumerate(CLASSES)}
DEFAULT_CLASS = "batch"
_UNPRICED = float("inf")


def resolve_class(name: Optional[str]) -> str:
    """Normalize a priority-class spec (submit kwarg or conf value) to
    one of :data:`CLASSES`; empty/None falls back to ``batch``."""
    if not name:
        return DEFAULT_CLASS
    v = str(name).strip().lower()
    if v not in CLASS_RANK:
        raise ValueError(
            f"unknown priority class {name!r} (expected one of {CLASSES})")
    return v


def parse_weights(spec: str) -> Dict[str, float]:
    """``"8,3,1"`` -> ``{interactive: 8, batch: 3, background: 1}``.
    Weights must be positive (a zero weight is a starvation machine the
    bound would have to carry alone)."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) != len(CLASSES):
        raise ValueError(
            f"scheduler.qos.weights expects {len(CLASSES)} comma-separated "
            f"values (one per class {CLASSES}), got {spec!r}")
    out = {}
    for name, p in zip(CLASSES, parts):
        w = float(p)
        if w <= 0:
            raise ValueError(
                f"scheduler.qos.weights: weight for {name!r} must be > 0, "
                f"got {w}")
        out[name] = w
    return out


class QueueEntry:
    """One waiting query: its class, SJF cost key, arrival sequence, and
    the wake event the granted slot sets. ``granted``/``cancelled`` make
    removal race-free under the manager lock (lazy deletion: a cancelled
    entry is skipped at pop time)."""

    __slots__ = ("qos_class", "cost_ms", "seq", "event", "tenant",
                 "granted", "cancelled")

    def __init__(self, qos_class: str, cost_ms: Optional[float], seq: int,
                 event, tenant: Optional[str] = None):
        self.qos_class = qos_class
        self.cost_ms = float(cost_ms) if cost_ms is not None else _UNPRICED
        self.seq = seq
        self.event = event
        self.tenant = tenant
        self.granted = False
        self.cancelled = False

    def sort_key(self) -> Tuple[float, int]:
        return (self.cost_ms, self.seq)


class _ClassQueue:
    __slots__ = ("heap", "vtime", "bypass", "live")

    def __init__(self):
        self.heap: List[Tuple[Tuple[float, int], QueueEntry]] = []
        self.vtime = 0.0
        self.bypass = 0
        self.live = 0           # non-cancelled entries in the heap

    def push(self, entry: QueueEntry) -> None:
        heapq.heappush(self.heap, (entry.sort_key(), entry))
        self.live += 1

    def pop(self) -> Optional[QueueEntry]:
        while self.heap:
            _, e = heapq.heappop(self.heap)
            if e.cancelled:
                continue
            self.live -= 1
            return e
        return None


class WfqQueue:
    """The QoS run queue: one SJF heap per class, drained by stride
    scheduling with a hard starvation bound."""

    def __init__(self, weights: Dict[str, float], starvation_bound: int):
        self.weights = dict(weights)
        self.starvation_bound = max(int(starvation_bound), 1)
        self._classes = {name: _ClassQueue() for name in CLASSES}
        self._seq = 0
        self._global_vtime = 0.0

    def __len__(self) -> int:
        return sum(c.live for c in self._classes.values())

    def depth(self, qos_class: Optional[str] = None) -> int:
        if qos_class is None:
            return len(self)
        return self._classes[qos_class].live

    def push(self, qos_class: str, cost_ms: Optional[float], event,
             tenant: Optional[str] = None) -> QueueEntry:
        cq = self._classes[qos_class]
        if cq.live == 0:
            # Re-activation: a long-idle class joins at the CURRENT
            # virtual time instead of cashing in unbounded credit for
            # the time it had nothing to run (classic stride re-entry).
            cq.vtime = max(cq.vtime, self._global_vtime)
        self._seq += 1
        entry = QueueEntry(qos_class, cost_ms, self._seq, event, tenant)
        cq.push(entry)
        return entry

    def discard(self, entry: QueueEntry) -> None:
        """Remove a waiter that timed out / cancelled while queued.
        Lazy: the heap drops it at pop time; counts adjust now."""
        if not entry.cancelled and not entry.granted:
            entry.cancelled = True
            self._classes[entry.qos_class].live -= 1

    def pop_next(self) -> Tuple[Optional[QueueEntry], bool]:
        """The next query to grant a run slot: ``(entry, starved)``.
        ``starved`` is True when the hard starvation bound — not the
        stride order — picked the class (the engagement counter the
        soak asserts on). ``(None, False)`` when nothing is queued."""
        nonempty = [(name, cq) for name, cq in self._classes.items()
                    if cq.live > 0]
        if not nonempty:
            return None, False
        starved = [(name, cq) for name, cq in nonempty
                   if cq.bypass >= self.starvation_bound]
        engaged = False
        if starved:
            # Hard bound: the longest-bypassed class runs NEXT. Ties
            # break by bypass count then class rank.
            name, cq = max(
                starved,
                key=lambda nc: (nc[1].bypass, -CLASS_RANK[nc[0]]))
            engaged = True
        else:
            name, cq = min(
                nonempty,
                key=lambda nc: (nc[1].vtime, CLASS_RANK[nc[0]]))
        entry = cq.pop()
        assert entry is not None
        entry.granted = True
        # System virtual time = the vtime at which service happened;
        # classes re-activating later join here (no credit hoarding).
        self._global_vtime = max(self._global_vtime, cq.vtime)
        cq.vtime += 1.0 / self.weights[name]
        cq.bypass = 0
        for other, ocq in self._classes.items():
            if other != name and ocq.live > 0:
                ocq.bypass += 1
        return entry, engaged
