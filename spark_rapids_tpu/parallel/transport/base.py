"""Shuffle transport SPI (ISSUE 6).

The reference treats shuffle transport as a swappable layer: the
columnar serializer fallback (GpuColumnarBatchSerializer.scala:38) works
everywhere, and the UCX/RDMA plugin (shuffle-plugin/.../ucx/UCX.scala)
slots in behind the same RapidsShuffleInternalManager interface when the
fabric supports it. This package mirrors that split for the TPU engine:
every exchange funnel talks to a :class:`ShuffleTransport` chosen by
``spark.rapids.sql.shuffle.transport`` instead of hard-coding where
shuffle shards live.

Contract (see docs/shuffle.md for the full narrative):

- ``Transport.open(conf, tag, ...)`` starts ONE map/reduce session for
  one exchange materialization. ``tag`` identifies the exchange's
  durable output (stable across a recompute of the same exchange).
- ``session.write_shard(partition, batch)`` appends one map-side piece
  to a reduce partition's shard list. Shards are owner-tagged with the
  exchange id, so a loss detected at fetch time flows through
  lineage-scoped stage recompute (parallel/stages.py), not whole-query
  retry.
- ``session.commit()`` publishes the map output atomically: fetches
  must never observe a half-written shard set.
- ``session.fetch_shards(partition)`` returns the partition's shard
  handles (``.capacity``, ``.get() -> DeviceBatch``, ``.release()``,
  ``.close()`` — the SpillableBatch protocol, memory/stores.py), in
  deterministic map order.
- ``session.invalidate()`` drops the durable output (the
  ``stage_invalidate`` boundary contract) so a recompute rewrites it;
  ``session.abort()`` cleans up a partial materialization;
  ``session.close()`` is query teardown.

Serialized shards are CRC-framed via the existing ``wire.frame_blob``
format, so a flipped bit on any transport's at-rest data is DETECTED at
fetch (one refetch, counter ``remoteShardRefetches``) instead of
decoding into silently wrong rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


class ShardLostError(RuntimeError):
    """A durable shuffle shard is gone (missing spool file, vanished
    manifest, injected ``lostshard``). Carries the UNAVAILABLE marker so
    an unattributable loss still lands in the whole-query retry, and
    ``fault_owner`` (the owning exchange exec's id) so lineage recovery
    (parallel/stages.py) can invalidate and recompute exactly the owning
    stage instead."""

    def __init__(self, what: str, owner: Optional[int] = None):
        super().__init__(
            f"UNAVAILABLE: lost shuffle shard: {what}")
        self.fault_owner = owner


class TransportError(RuntimeError):
    """Non-recoverable transport misconfiguration (unknown transport
    name, unreachable spool directory, rendezvous timeout)."""


class ShuffleSession:
    """One exchange materialization through one transport. Subclasses
    implement the five SPI verbs; the base class only carries the
    identity fields every implementation needs."""

    def __init__(self, tag: str, owner: Optional[int]):
        # ``tag`` names the durable output; ``owner`` is the owning
        # exchange exec's id() — the lineage attribution every
        # loss/corruption error must carry.
        self.tag = tag
        self.owner = owner
        # Observed per-partition byte sizes (the size-observation hook
        # runtime adaptive re-planning and byte-aware partition
        # coalescing read, parallel/replan.py / exchange._groups): every
        # implementation records what it actually wrote, in its own
        # units (device bytes inprocess/mesh, framed blob bytes
        # hostfile) — EXACT sizes, the GpuCustomShuffleReaderExec
        # materialized-stats analog.
        self.shard_bytes: Dict[int, int] = {}

    def record_shard_bytes(self, partition: int, nbytes: int) -> None:
        self.shard_bytes[partition] = \
            self.shard_bytes.get(partition, 0) + int(nbytes)

    def observed_bytes(self, partition: Optional[int] = None) -> int:
        """Total observed bytes of one partition, or of the whole map
        output (partition=None). Only meaningful after commit()."""
        if partition is not None:
            return self.shard_bytes.get(partition, 0)
        return sum(self.shard_bytes.values())

    # -- map side ------------------------------------------------------------
    def write_shard(self, partition: int, batch) -> None:
        raise NotImplementedError

    def commit(self) -> None:
        raise NotImplementedError

    # -- reduce side ---------------------------------------------------------
    def fetch_shards(self, partition: int) -> Sequence:
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------------
    def abort(self) -> None:
        """Failed mid-materialization: release whatever was written (the
        retry ladder re-runs the materialization from scratch)."""
        self.invalidate()

    def invalidate(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Query teardown: release everything. Must be idempotent."""
        self.invalidate()


class ShuffleTransport:
    """Transport factory. Stateless; one session per exchange
    materialization."""

    name = "?"

    def open(self, conf, tag: str, num_partitions: int,
             owner: Optional[int] = None, catalog=None,
             metrics=None) -> ShuffleSession:
        raise NotImplementedError
