"""Object-store shuffle transport: the cross-MACHINE data plane
(ISSUE 17 tentpole leg a — the S3/GCS stand-in the hostfile spool
cannot be).

Same contract as ``hostfile`` — CRC-framed shard blobs
(``memory/stores.batch_to_shard_blob``), a manifest as the publication
barrier, refetch-once-then-stage-recompute on corruption, owner-tagged
:class:`ShardLostError` on loss — but keys in a flat object namespace
behind a pluggable :class:`ObjectStoreBackend` instead of paths on a
shared filesystem::

    <prefix>/<exchange-tag>/<worker>/p00003-0001.shard
    <prefix>/<exchange-tag>/<worker>.manifest.json
    <prefix>/<exchange-tag>/exchange.manifest.json   (exclusive mode)

The manifest PUT is the atomicity contract here: an object store serves
whole objects, so a fetcher sees the previous complete manifest or the
new complete manifest, never a torn mix — the object-namespace analog of
``os.replace``. Shard objects are invisible until their manifest lands.

What this transport adds over hostfile is the FAILURE MODEL of a real
remote store:

- every backend request (put/get/list/delete) runs under bounded retry
  with exponential backoff and DETERMINISTIC jitter (derived from the
  object key + attempt, so a fleet of fetchers riding out the same 5xx
  burst desynchronizes without nondeterminism) — counter
  ``objectstoreRetries``; exhausted retries raise a typed
  'UNAVAILABLE:' error onto the transient rung of the recovery ladder;
- a 404 on a manifest-listed shard is NOT retried: that shard is GONE,
  and the owner-tagged :class:`ShardLostError` routes to ONE stage
  recompute, never a whole-query retry;
- fault kinds: ``unavailable@objectstore`` fails one backend request
  (absorbed by the retry loop), ``slowput@transport`` injects latency
  into a shard write, and the hostfile kinds
  (``lostshard``/``corrupt``/``oom``/``transient`` ``@transport``)
  apply unchanged at the fetch funnel.

Shipped backend: :class:`HttpObjectStoreBackend` (stdlib urllib) against
the localhost stub server in this module (``scripts/objstore_stub.py``
is its CLI), which supports injectable latency, 5xx bursts, and shard
loss through an admin endpoint — the chaos half of the CI matrix. With
no endpoint configured, an in-process stub is started once per process;
the cluster coordinator pins the resolved endpoint into dispatched
worker confs so every process shares one store.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.parallel.transport.base import (
    ShardLostError, ShuffleSession, ShuffleTransport)
from spark_rapids_tpu.parallel.transport.hostfile import (
    HostFileShardHandle, valid_manifest)

_LOG = logging.getLogger("spark_rapids_tpu.transport")

_BACKOFF_CAP_S = 2.0


class ObjectStoreUnavailableError(RuntimeError):
    """The backend failed TRANSIENTLY (5xx, refused/reset connection,
    socket timeout) and bounded retry was exhausted. Typed
    'UNAVAILABLE:' so it lands on the transient rung of the recovery
    ladder (whole-query retry driver-side, CFAIL+requeue on a cluster
    worker) — the store being down is not shard loss, and a stage
    recompute against the same dead store would not help."""

    def __init__(self, what: str):
        super().__init__(f"UNAVAILABLE: object store: {what}")


class ObjectMissingError(KeyError):
    """GET/DELETE of a key the store does not have (HTTP 404). Distinct
    from unavailability: for a manifest-listed shard this is LOSS and
    goes to stage recompute, not retry."""


# -- backend SPI --------------------------------------------------------------

class ObjectStoreBackend:
    """Minimal put/get/list/delete object SPI. Implementations raise
    :class:`ObjectStoreUnavailableError` for transient faults (the
    session retries those) and :class:`ObjectMissingError` for a
    definitive 404 (the session maps it to loss). One instance may be
    shared across sessions and threads."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Must be idempotent: deleting a missing key is not an error."""
        raise NotImplementedError

    def list_keys(self, prefix: str) -> List[str]:
        raise NotImplementedError


class HttpObjectStoreBackend(ObjectStoreBackend):
    """Backend over the stub server's HTTP surface (PUT/GET/DELETE
    ``/o/<key>``, GET ``/list?prefix=``) via stdlib urllib — no new
    dependencies. Any real S3/GCS-compatible gateway exposing the same
    four verbs slots in behind :func:`register_backend`."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/o/{urllib.parse.quote(key, safe='/')}"

    def _request(self, method: str, url: str,
                 data: Optional[bytes] = None) -> bytes:
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise ObjectMissingError(url) from e
            raise ObjectStoreUnavailableError(
                f"{method} {url}: HTTP {e.code}") from e
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                TimeoutError, OSError) as e:
            raise ObjectStoreUnavailableError(
                f"{method} {url}: {e}") from e

    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", self._url(key), data=data)

    def get(self, key: str) -> bytes:
        return self._request("GET", self._url(key))

    def delete(self, key: str) -> None:
        try:
            self._request("DELETE", self._url(key))
        except ObjectMissingError:
            pass

    def list_keys(self, prefix: str) -> List[str]:
        q = urllib.parse.urlencode({"prefix": prefix})
        body = self._request("GET", f"{self.endpoint}/list?{q}")
        return list(json.loads(body.decode("utf-8")))


_BACKENDS = {"http": HttpObjectStoreBackend}


def register_backend(scheme: str, cls) -> None:
    """Register a backend class for an endpoint scheme (the plugin
    point for a real store)."""
    _BACKENDS[scheme] = cls


def make_backend(endpoint: str, timeout_s: float) -> ObjectStoreBackend:
    scheme = urllib.parse.urlsplit(endpoint).scheme or "http"
    cls = _BACKENDS.get(scheme, _BACKENDS.get("http"))
    if scheme == "https":
        cls = _BACKENDS["http"]
    return cls(endpoint, timeout_s=timeout_s)


# -- localhost stub server ----------------------------------------------------

class _StubState:
    def __init__(self):
        self.lock = threading.Lock()
        self.objects: Dict[str, bytes] = {}
        self.latency_ms = 0
        self.fail_remaining = 0
        self.fail_code = 503
        self.puts = 0
        self.gets = 0
        self.failed = 0


class _StubHandler(BaseHTTPRequestHandler):
    server_version = "srt-objstore/1"

    def log_message(self, fmt, *args):  # pragma: no cover - quiet
        pass

    @property
    def _state(self) -> _StubState:
        return self.server.state  # type: ignore[attr-defined]

    def _reply(self, code: int, body: bytes = b"",
               ctype: str = "application/octet-stream") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _inject(self) -> bool:
        """Data-plane fault injection (admin surface is exempt so chaos
        tests can always steer the stub). Returns True if this request
        was failed."""
        st = self._state
        with st.lock:
            latency = st.latency_ms
            if st.fail_remaining > 0:
                st.fail_remaining -= 1
                st.failed += 1
                code = st.fail_code
            else:
                code = 0
        if latency:
            time.sleep(latency / 1000.0)
        if code:
            self._reply(code, b"injected failure", "text/plain")
            return True
        return False

    def _key(self) -> Optional[str]:
        path = urllib.parse.urlsplit(self.path).path
        if not path.startswith("/o/"):
            return None
        return urllib.parse.unquote(path[len("/o/"):])

    def do_PUT(self):
        key = self._key()
        if key is None:
            return self._reply(400, b"bad path", "text/plain")
        if self._inject():
            return
        n = int(self.headers.get("Content-Length", "0"))
        data = self.rfile.read(n)
        st = self._state
        with st.lock:
            st.objects[key] = data
            st.puts += 1
        self._reply(200)

    def do_GET(self):
        split = urllib.parse.urlsplit(self.path)
        if split.path == "/health":
            return self._reply(200, b"ok", "text/plain")
        if split.path == "/admin/stats":
            st = self._state
            with st.lock:
                body = json.dumps({
                    "keys": len(st.objects), "puts": st.puts,
                    "gets": st.gets, "failed": st.failed,
                    "fail_remaining": st.fail_remaining,
                    "latency_ms": st.latency_ms}).encode()
            return self._reply(200, body, "application/json")
        if split.path == "/list":
            if self._inject():
                return
            prefix = urllib.parse.parse_qs(split.query).get(
                "prefix", [""])[0]
            st = self._state
            with st.lock:
                keys = sorted(k for k in st.objects
                              if k.startswith(prefix))
            return self._reply(200, json.dumps(keys).encode(),
                               "application/json")
        key = self._key()
        if key is None:
            return self._reply(400, b"bad path", "text/plain")
        if self._inject():
            return
        st = self._state
        with st.lock:
            data = st.objects.get(key)
            st.gets += 1
        if data is None:
            return self._reply(404, b"no such object", "text/plain")
        self._reply(200, data)

    def do_DELETE(self):
        key = self._key()
        if key is None:
            return self._reply(400, b"bad path", "text/plain")
        if self._inject():
            return
        st = self._state
        with st.lock:
            st.objects.pop(key, None)
        self._reply(200)

    def do_POST(self):
        """Admin surface: /admin/latency?ms=N, /admin/fail?n=N[&code=C],
        /admin/drop?prefix=K (exact key or prefix), /admin/reset."""
        split = urllib.parse.urlsplit(self.path)
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(split.query).items()}
        st = self._state
        if split.path == "/admin/latency":
            with st.lock:
                st.latency_ms = int(q.get("ms", "0"))
            return self._reply(200)
        if split.path == "/admin/fail":
            with st.lock:
                st.fail_remaining = int(q.get("n", "1"))
                st.fail_code = int(q.get("code", "503"))
            return self._reply(200)
        if split.path == "/admin/drop":
            prefix = q.get("prefix", "")
            with st.lock:
                dropped = [k for k in st.objects
                           if k == prefix or k.startswith(prefix)]
                for k in dropped:
                    del st.objects[k]
            return self._reply(200, json.dumps(dropped).encode(),
                               "application/json")
        if split.path == "/admin/reset":
            with st.lock:
                st.objects.clear()
                st.latency_ms = 0
                st.fail_remaining = 0
            return self._reply(200)
        self._reply(404, b"no such admin op", "text/plain")


class ObjectStoreStub:
    """In-process localhost object store for tests/CI: a threading HTTP
    server over an in-memory key space, with an admin endpoint for
    injecting latency, 5xx bursts, and shard loss. NOT a durability
    stand-in — it exists so the transport's retry/loss machinery can be
    exercised against real sockets."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.state = _StubState()
        self._httpd = ThreadingHTTPServer((host, port), _StubHandler)
        self._httpd.daemon_threads = True
        self._httpd.state = self.state  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="srt-objstore-stub", daemon=True)
        self._thread.start()

    @property
    def endpoint(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    # Direct steering for in-process tests (the HTTP admin surface
    # drives the same state for out-of-process chaos).
    def set_latency(self, ms: int) -> None:
        with self.state.lock:
            self.state.latency_ms = int(ms)

    def fail_next(self, n: int, code: int = 503) -> None:
        with self.state.lock:
            self.state.fail_remaining = int(n)
            self.state.fail_code = int(code)

    def drop(self, prefix: str) -> List[str]:
        with self.state.lock:
            dropped = [k for k in self.state.objects
                       if k == prefix or k.startswith(prefix)]
            for k in dropped:
                del self.state.objects[k]
        return dropped

    def keys(self, prefix: str = "") -> List[str]:
        with self.state.lock:
            return sorted(k for k in self.state.objects
                          if k.startswith(prefix))

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_STUB_LOCK = threading.Lock()
_LOCAL_STUB: Optional[ObjectStoreStub] = None


def ensure_local_stub() -> ObjectStoreStub:
    """The per-process fallback stub (started once, daemon threads):
    what an unconfigured endpoint resolves to, so single-process runs
    and the SRT_SHUFFLE_TRANSPORT=objectstore CI matrix work with zero
    setup."""
    global _LOCAL_STUB
    with _STUB_LOCK:
        if _LOCAL_STUB is None:
            _LOCAL_STUB = ObjectStoreStub()
            _LOG.info("started in-process object-store stub at %s",
                      _LOCAL_STUB.endpoint)
        return _LOCAL_STUB


def stop_local_stub() -> None:
    global _LOCAL_STUB
    with _STUB_LOCK:
        if _LOCAL_STUB is not None:
            _LOCAL_STUB.close()
            _LOCAL_STUB = None


def resolve_endpoint(conf) -> str:
    """Endpoint selection: conf key > SRT_OBJECTSTORE_ENDPOINT env >
    the in-process stub."""
    from spark_rapids_tpu import config as C
    ep = str(conf.get(C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT) or "") \
        .strip()
    if not ep:
        ep = os.environ.get("SRT_OBJECTSTORE_ENDPOINT", "").strip()
    if not ep:
        ep = ensure_local_stub().endpoint
    return ep


# -- session ------------------------------------------------------------------

class ObjectStoreSession(ShuffleSession):
    """One exchange materialization against the object store. Mirrors
    HostFileSession's surface (including ``fetch_only`` /
    ``keep_on_close`` cluster roles and exclusive-manifest mode) so the
    cluster runtime can adopt either transport's manifests through the
    same code path."""

    def __init__(self, conf, tag: str, num_partitions: int,
                 owner: Optional[int], catalog, metrics):
        super().__init__(tag, owner)
        from spark_rapids_tpu import config as C
        self._catalog = catalog
        self._metrics = metrics
        self.num_partitions = num_partitions
        prefix = str(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX) or "").strip("/")
        self.worker = str(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_WORKER_ID) or "") \
            or f"w{os.getpid()}"
        self.exclusive = bool(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_EXCLUSIVE_MANIFEST))
        self.expected_workers = 1 if self.exclusive else max(int(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_EXPECTED_WORKERS)), 1)
        self.fetch_timeout_ms = int(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_FETCH_TIMEOUT_MS))
        self.retries = max(int(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_RETRIES)), 0)
        self.backoff_ms = max(int(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_BACKOFF_MS)), 1)
        timeout_s = max(int(conf.get(
            C.SHUFFLE_TRANSPORT_OBJECTSTORE_TIMEOUT_MS)), 100) / 1000.0
        self.endpoint = resolve_endpoint(conf)
        self.backend = make_backend(self.endpoint, timeout_s=timeout_s)
        self.fetch_only = False
        self.keep_on_close = False
        # Key namespace root for this exchange's durable output.
        self.root = f"{prefix}/{tag}" if prefix else tag
        self._seq: Dict[int, int] = {}
        self._written: Dict[int, List[dict]] = {}
        self._committed = False
        self._manifests: Optional[List[dict]] = None
        self._handles: Dict[int, List[HostFileShardHandle]] = {}

    def _manifest_key(self, worker: Optional[str] = None) -> str:
        name = "exchange.manifest.json" if self.exclusive else \
            f"{worker or self.worker}.manifest.json"
        return f"{self.root}/{name}"

    # -- bounded retry --------------------------------------------------------
    def _call(self, op: str, key: str, fn):
        """One backend request under bounded retry: exponential backoff
        (backoffMs * 2^(i-1), capped at 2s) plus deterministic jitter
        derived from (key, attempt) — desynchronizes a fleet retrying
        through the same outage without introducing nondeterminism.
        Exhausted retries surface the typed UNAVAILABLE error."""
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.parallel import transport as T
        last: Optional[ObjectStoreUnavailableError] = None
        for attempt in range(self.retries + 1):
            if attempt:
                base = min(self.backoff_ms * (2 ** (attempt - 1)) /
                           1000.0, _BACKOFF_CAP_S)
                jitter = (zlib.crc32(f"{key}|{attempt}".encode())
                          % 1000) / 1000.0
                time.sleep(base * (1.0 + 0.5 * jitter))
                T.record("objectstoreRetries")
            try:
                e = faults.check_fault("objectstore", ("unavailable",))
                if e is not None:
                    raise ObjectStoreUnavailableError(
                        f"injected unavailable ({op} {key})")
                return fn()
            except ObjectStoreUnavailableError as err:
                last = err
                _LOG.warning("object store %s %s failed transiently "
                             "(attempt %d/%d): %s", op, key, attempt + 1,
                             self.retries + 1, err)
        assert last is not None
        raise last

    def _delete_prefix(self, prefix: str) -> None:
        """Best-effort namespace cleanup (invalidate/close): a store
        outage during cleanup must never fail the query."""
        try:
            keys = self._call("list", prefix,
                              lambda: self.backend.list_keys(prefix))
            for k in keys:
                self._call("delete", k,
                           lambda k=k: self.backend.delete(k))
        except ObjectStoreUnavailableError as e:
            _LOG.warning("object store cleanup of %s skipped: %s",
                         prefix, e)

    # -- map side ------------------------------------------------------------
    def write_shard(self, partition: int, batch) -> None:
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.memory.stores import batch_to_shard_blob
        from spark_rapids_tpu.parallel import transport as T
        faults.fault_point("transport.write", owner=self.owner)
        e = faults.check_fault("transport", ("slowput",))
        if e is not None:
            # Injected slow writer: exercises commit-barrier overlap
            # (fetchers keep polling; nothing is visible until the
            # manifest PUT) — latency, never an error.
            T.record("slowPuts")
            time.sleep(0.25)
        blob = batch_to_shard_blob(batch)
        seq = self._seq.get(partition, 0)
        self._seq[partition] = seq + 1
        fname = f"p{partition:05d}-{seq:04d}.shard"
        key = f"{self.root}/{self.worker}/{fname}"
        self._call("put", key, lambda: self.backend.put(key, blob))
        rows = batch.rows_hint
        self.record_shard_bytes(partition, len(blob))
        self._written.setdefault(partition, []).append(
            {"file": f"{self.worker}/{fname}",
             "capacity": int(batch.capacity),
             "rows": None if rows is None else int(rows),
             "bytes": len(blob)})
        T.record("transportBytesWritten", len(blob))
        T.record("transportShardsWritten")
        if self._metrics is not None:
            self._metrics.add("transportBytesWritten", len(blob))
            self._metrics.add("transportShardsWritten", 1)

    def commit(self) -> None:
        manifest = {"worker": self.worker,
                    "num_partitions": self.num_partitions,
                    "shards": {str(p): entries
                               for p, entries in self._written.items()}}
        blob = json.dumps(manifest).encode("utf-8")
        key = self._manifest_key()
        # The whole-object PUT is the publication barrier: shard
        # objects became durable above, but no fetcher lists/reads them
        # until this manifest object exists — and a recompute's commit
        # REPLACES it atomically (old complete set or new complete set,
        # never a mix).
        self._call("put", key, lambda: self.backend.put(key, blob))
        self._committed = True

    # -- reduce side ---------------------------------------------------------
    def _load_manifests(self) -> List[dict]:
        if self._manifests is not None:
            return self._manifests
        deadline = time.monotonic() + self.fetch_timeout_ms / 1000.0
        manifests: List[dict] = []
        while True:
            manifests = []
            prefix = f"{self.root}/"
            keys = self._call("list", prefix,
                              lambda: self.backend.list_keys(prefix))
            for k in keys:
                name = k[len(prefix):]
                if "/" in name or not name.endswith(".manifest.json"):
                    continue
                if self.exclusive and name != "exchange.manifest.json":
                    continue
                try:
                    m = json.loads(self._call(
                        "get", k,
                        lambda k=k: self.backend.get(k)).decode("utf-8"))
                except (ObjectMissingError, ValueError):
                    continue      # racing writer/cleanup; re-poll
                if not valid_manifest(m):
                    continue      # torn/partial upload; not published
                manifests.append(m)
            if len(manifests) >= self.expected_workers:
                break
            if time.monotonic() >= deadline:
                raise ShardLostError(
                    f"exchange {self.tag}: {len(manifests)}/"
                    f"{self.expected_workers} worker manifests under "
                    f"{self.endpoint}/{self.root} after "
                    f"{self.fetch_timeout_ms}ms", owner=self.owner)
            time.sleep(0.02)
        manifests.sort(key=lambda m: str(m.get("worker", "")))
        self._manifests = manifests
        return manifests

    def fetch_shards(self, partition: int):
        handles = self._handles.get(partition)
        if handles is None:
            handles = []
            for m in self._load_manifests():
                for entry in m.get("shards", {}).get(str(partition), []):
                    # HostFileShardHandle is transport-agnostic: it only
                    # needs _fetch_blob(locator); our locator is a key.
                    handles.append(HostFileShardHandle(
                        self, f"{self.root}/{entry['file']}",
                        int(entry["capacity"]), entry.get("rows")))
            self._handles[partition] = handles
        return handles

    def _fetch_blob(self, key: str):
        """GET + CRC-verify + upload one shard object; the transport
        fault site and the refetch-once rung live here (mirroring
        hostfile._fetch_blob)."""
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.columnar.wire import WireCorruptionError
        from spark_rapids_tpu.memory.stores import shard_blob_to_batch
        from spark_rapids_tpu.parallel import transport as T
        faults.check_cancelled()
        e = faults.check_fault("transport",
                               ("lostshard", "oom", "transient"))
        if e is not None:
            if e.kind == "oom":
                raise faults.InjectedOomError("transport")
            if e.kind == "transient":
                raise faults.InjectedTransientError("transport")
            # lostshard: delete the object at rest FIRST — recovery
            # must rewrite the shard, not re-read a survivor.
            try:
                self._call("delete", key,
                           lambda: self.backend.delete(key))
            except ObjectStoreUnavailableError:
                pass
            T.record("remoteShardsLost")
            raise ShardLostError(f"injected loss of {key}",
                                 owner=self.owner)
        last: Optional[WireCorruptionError] = None
        for _ in range(2):
            try:
                framed = self._call("get", key,
                                    lambda: self.backend.get(key))
            except ObjectMissingError as err:
                T.record("remoteShardsLost")
                raise ShardLostError(f"{key}: object missing",
                                     owner=self.owner) from err
            framed = faults.corrupt_blob("transport", framed)
            try:
                batch = shard_blob_to_batch(framed)
            except WireCorruptionError as err:
                last = err
                faults.record("corruptionsDetected")
                T.record("remoteShardRefetches")
                faults.record("remoteShardRefetches")
                _LOG.warning("shard frame checksum mismatch (%s), "
                             "refetching: %s", key, err)
                continue
            T.record("transportBytesFetched", len(framed))
            T.record("transportShardsFetched")
            if self._metrics is not None:
                self._metrics.add("transportBytesFetched", len(framed))
                self._metrics.add("transportShardsFetched", 1)
            return batch
        # Persistently corrupt at rest: owner-tag so lineage recovery
        # recomputes just the owning stage.
        last.fault_owner = self.owner
        raise last

    # -- lifecycle -----------------------------------------------------------
    def _close_handles(self) -> None:
        for hs in self._handles.values():
            for h in hs:
                h.close()
        self._handles = {}
        self._manifests = None

    def invalidate(self) -> None:
        """Stage recompute contract: drop the WHOLE durable output under
        the tag. Fetch-only sessions (cluster consumers) drop only their
        local caches — the producer's objects are the coordinator's to
        clean."""
        self._close_handles()
        if self.fetch_only:
            return
        self._delete_prefix(f"{self.root}/")
        self._written = {}
        self._seq = {}
        self._committed = False

    def close(self) -> None:
        self._close_handles()
        if self.fetch_only or self.keep_on_close:
            return
        self._delete_prefix(f"{self.root}/{self.worker}/")
        if self._committed or not self.exclusive:
            try:
                self._call("delete", self._manifest_key(),
                           lambda: self.backend.delete(
                               self._manifest_key()))
            except ObjectStoreUnavailableError:
                pass


class ObjectStoreTransport(ShuffleTransport):
    name = "objectstore"

    def open(self, conf, tag: str, num_partitions: int,
             owner: Optional[int] = None, catalog=None,
             metrics=None) -> ObjectStoreSession:
        return ObjectStoreSession(conf, tag, num_partitions, owner,
                                  catalog, metrics)
