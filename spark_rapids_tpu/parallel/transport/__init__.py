"""Pluggable shuffle transport (ISSUE 6): registry + selection.

Three built-in transports, one SPI (see base.py and docs/shuffle.md):

- ``inprocess`` — the BufferCatalog-backed single-process exchange
  (shards are SpillableBatch handles under the memory ladder).
- ``mesh`` — the ICI collective path: MeshExchangeExec runs the
  all_to_all program and registers each device's post-exchange shard
  through this SPI.
- ``hostfile`` — shards spool to a shared directory as CRC-framed blobs
  with a manifest/socket rendezvous, so N independent worker processes
  can map-write and reduce-fetch each other's shards (the DCN
  multi-slice stand-in).
- ``objectstore`` — the same contract keyed into a flat object
  namespace behind a pluggable put/get/list backend (HTTP stub shipped;
  the S3/GCS stand-in), with bounded retry + deterministic-jitter
  backoff on transient backend errors (objectstore.py).

Selection: ``spark.rapids.sql.shuffle.transport`` conf, then the
``SRT_SHUFFLE_TRANSPORT`` env (whole-process override, the CI matrix
hook), then the legacy ``spark.rapids.sql.mesh.enabled`` key, then
``inprocess``. Third-party transports register via
:func:`register_transport` — the RapidsShuffleManager plugin point of
this engine.

Counters (process-global here + the per-query ``Transport@query``
metrics entry): ``transportBytesWritten``, ``transportBytesFetched``,
``transportShardsWritten``, ``transportShardsFetched``,
``remoteShardRefetches`` (CRC-failed fetches that re-read),
``remoteShardsLost`` (losses handed to lineage recovery). bench.py
surfaces them as the JSON ``transport`` block.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional

from spark_rapids_tpu.parallel.transport.base import (     # noqa: F401
    ShardLostError, ShuffleSession, ShuffleTransport, TransportError)

_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def record(name: str, amount: float = 1) -> None:
    """Bump a process-global transport counter (bench.py JSON block)."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount


def counters() -> Dict[str, float]:
    with _LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()


def metrics_entry(ctx):
    """The per-query Transport metrics entry (next to Recovery@query;
    registered level-filter exempt through the ops/base.py audit
    registry)."""
    from spark_rapids_tpu.ops.base import query_metrics_entry
    return query_metrics_entry(ctx, "Transport")


# -- registry ----------------------------------------------------------------

def _make_inprocess() -> ShuffleTransport:
    from spark_rapids_tpu.parallel.transport.inprocess import \
        InProcessTransport
    return InProcessTransport()


def _make_hostfile() -> ShuffleTransport:
    from spark_rapids_tpu.parallel.transport.hostfile import \
        HostFileTransport
    return HostFileTransport()


def _make_mesh() -> ShuffleTransport:
    from spark_rapids_tpu.parallel.transport.mesh import MeshTransport
    return MeshTransport()


def _make_objectstore() -> ShuffleTransport:
    from spark_rapids_tpu.parallel.transport.objectstore import \
        ObjectStoreTransport
    return ObjectStoreTransport()


_REGISTRY: Dict[str, Callable[[], ShuffleTransport]] = {
    "inprocess": _make_inprocess,
    "hostfile": _make_hostfile,
    "mesh": _make_mesh,
    "objectstore": _make_objectstore,
}
_INSTANCES: Dict[str, ShuffleTransport] = {}


def register_transport(name: str,
                       factory: Callable[[], ShuffleTransport]) -> None:
    """Register a third-party transport under ``name`` (selectable via
    spark.rapids.sql.shuffle.transport)."""
    with _LOCK:
        _REGISTRY[name] = factory
        _INSTANCES.pop(name, None)


def transport_name(conf) -> str:
    """Resolve the configured transport name: explicit conf key > an
    explicitly-set legacy mesh.enabled=true > the SRT_SHUFFLE_TRANSPORT
    env (process-wide default) > inprocess. Session-explicit settings
    beat the env so a query that opts into the mesh keeps it even under
    a CI transport matrix."""
    from spark_rapids_tpu import config as C
    name = str(conf.get(C.SHUFFLE_TRANSPORT) or "").strip().lower()
    if not name and C.MESH_ENABLED.key in conf.raw and \
            bool(conf.get(C.MESH_ENABLED)):
        name = "mesh"
    if not name:
        name = os.environ.get("SRT_SHUFFLE_TRANSPORT", "").strip().lower()
    if not name:
        name = "mesh" if bool(conf.get(C.MESH_ENABLED)) else "inprocess"
    if name not in _REGISTRY:
        raise TransportError(
            f"unknown shuffle transport {name!r} "
            f"(registered: {sorted(_REGISTRY)})")
    return name


def get_transport(name: str) -> ShuffleTransport:
    """The (process-cached) transport instance for ``name``."""
    with _LOCK:
        t = _INSTANCES.get(name)
        if t is None:
            factory = _REGISTRY.get(name)
            if factory is None:
                raise TransportError(
                    f"unknown shuffle transport {name!r} "
                    f"(registered: {sorted(_REGISTRY)})")
            t = _INSTANCES[name] = factory()
    return t


def materialization_transport(conf) -> ShuffleTransport:
    """The transport a materialized (single-process) ShuffleExchangeExec
    should spool through. 'mesh' resolves to 'inprocess' here: the mesh
    transport lives inside MeshExchangeExec's collective program, and
    the materialized exchange is exactly its local degrade target."""
    name = transport_name(conf)
    if name == "mesh":
        name = "inprocess"
    return get_transport(name)
