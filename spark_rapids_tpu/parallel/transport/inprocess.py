"""In-process shuffle transport: today's BufferCatalog-backed exchange
path, refactored behind the SPI.

Shards stay on-device as SpillableBatch handles (memory/stores.py) —
spillable under the memory ladder, CRC-framed via ``wire.frame_blob``
once they reach the disk tier, owner-tagged by the per-query catalog.
This is the serializer-fallback half of the reference's transport split
(GpuColumnarBatchSerializer.scala:38): always available, zero copies,
single process.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.parallel.transport.base import (
    ShuffleSession, ShuffleTransport)


class InProcessSession(ShuffleSession):
    def __init__(self, tag: str, num_partitions: int,
                 owner: Optional[int], catalog):
        super().__init__(tag, owner)
        self._catalog = catalog
        self.buckets: List[list] = [[] for _ in range(num_partitions)]
        self._committed = False

    def write_shard(self, partition: int, batch) -> None:
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.memory.stores import (
            PRIORITY_SHUFFLE_OUTPUT, SpillableBatch)
        faults.fault_point("transport.write", owner=self.owner)
        self.record_shard_bytes(partition, batch.device_size_bytes())
        self.buckets[partition].append(SpillableBatch(
            self._catalog, batch, PRIORITY_SHUFFLE_OUTPUT))

    def commit(self) -> None:
        # Device handles are visible the moment they register; commit is
        # the SPI's publication barrier and a no-op here.
        self._committed = True

    def fetch_shards(self, partition: int):
        return self.buckets[partition]

    def invalidate(self) -> None:
        for blist in self.buckets:
            for sb in blist:
                sb.close()
        self.buckets = [[] for _ in self.buckets]
        self._committed = False


class InProcessTransport(ShuffleTransport):
    name = "inprocess"

    def open(self, conf, tag: str, num_partitions: int,
             owner: Optional[int] = None, catalog=None,
             metrics=None) -> InProcessSession:
        assert catalog is not None, \
            "inprocess transport needs the query's buffer catalog"
        return InProcessSession(tag, num_partitions, owner, catalog)
