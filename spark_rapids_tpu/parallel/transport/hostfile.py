"""Host-file / socket shuffle transport: the cross-process data plane
(ISSUE 6 tentpole — the DCN multi-slice stand-in).

Map side: every shard serializes to ONE self-describing CRC-framed blob
(``memory/stores.batch_to_shard_blob`` — ``wire.frame_blob`` on the
outside) and spools to a shared directory::

    <dir>/<exchange-tag>/<worker>/p00003-0001.shard
    <dir>/<exchange-tag>/<worker>.manifest.json     (atomic rename)

``commit()`` publishes the manifest — shard files are invisible to
fetchers until their manifest lands, so a fetch never observes a
half-written map output — and, when a socket rendezvous is configured
(``...hostfile.rendezvous``), announces the commit over TCP so fetchers
block on the commit barrier instead of polling the directory.

Reduce side: ``fetch_shards(p)`` waits for ``expectedWorkers``
manifests, then serves partition p's shards in (worker, sequence) order
— deterministic, so results are bit-identical to the in-process path.
Fetched blobs re-upload and register with the query's BufferCatalog as
spillable outputs (memory/stores.py), exactly like in-process buckets.

Failure story (the reason this is a transport and not a file format):

- a fetched frame failing its CRC re-reads ONCE (counter
  ``remoteShardRefetches``) — injected corruption at rest recovers, a
  persistently bad frame raises ``WireCorruptionError`` owner-tagged so
  lineage recovery (parallel/stages.py) recomputes the owning stage;
- a missing shard/manifest raises :class:`ShardLostError`, also
  owner-tagged: one lost remote shard costs ONE stage recompute, never
  a whole-query retry;
- the ``lostshard@transport`` fault kind deletes the shard at rest
  before raising, so chaos tests prove recovery REWRITES data rather
  than re-reading a survivor.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.parallel.transport.base import (
    ShardLostError, ShuffleSession, ShuffleTransport)

_LOG = logging.getLogger("spark_rapids_tpu.transport")


def default_spool_dir() -> str:
    return os.path.join(tempfile.gettempdir(),
                        f"srt_shuffle_{os.getpid()}")


def valid_manifest(m) -> bool:
    """Torn-manifest guard, shared by every file/object transport: a
    manifest counts as PUBLISHED only if it parsed into the complete
    schema commit() writes. A manifest written WITHOUT the atomic
    rename (a crashed writer, a non-atomic copy onto the spool, a
    truncated upload) must read as 'not yet published' and keep the
    fetcher polling — never surface as a KeyError/TypeError crash deep
    in fetch_shards."""
    if not isinstance(m, dict):
        return False
    if not isinstance(m.get("worker"), str):
        return False
    if not isinstance(m.get("num_partitions"), int):
        return False
    shards = m.get("shards")
    if not isinstance(shards, dict):
        return False
    for entries in shards.values():
        if not isinstance(entries, list):
            return False
        for e in entries:
            if not isinstance(e, dict) or \
                    not isinstance(e.get("file"), str) or \
                    not isinstance(e.get("capacity"), int):
                return False
    return True


class HostFileShardHandle:
    """Lazy shard handle with the SpillableBatch protocol: ``capacity``
    is known from the manifest (no I/O), ``get()`` reads + verifies +
    uploads on first use and serves the catalog-registered (spillable)
    batch afterwards."""

    def __init__(self, session: "HostFileSession", path: str,
                 capacity: int, rows: Optional[int]):
        self._session = session
        self._path = path
        self.capacity = capacity
        self._rows = rows
        self._sb = None          # SpillableBatch once fetched (catalog)
        self._batch = None       # plain DeviceBatch (no catalog)
        self._closed = False

    def get(self):
        if self._sb is not None:
            return self._sb.get()
        if self._batch is not None:
            return self._batch
        batch = self._session._fetch_blob(self._path)
        if self._rows is not None and batch.rows_hint is None:
            batch.rows_hint = self._rows
        catalog = self._session._catalog
        if catalog is not None:
            from spark_rapids_tpu.memory.stores import (
                PRIORITY_SHUFFLE_OUTPUT, SpillableBatch)
            self._sb = SpillableBatch(catalog, batch,
                                      PRIORITY_SHUFFLE_OUTPUT)
            return self._sb.get()
        self._batch = batch
        return batch

    def release(self, priority: int = 0) -> None:
        if self._sb is not None:
            self._sb.release(priority)

    def close(self) -> None:
        if not self._closed:
            if self._sb is not None:
                self._sb.close()
            self._sb = self._batch = None
            self._closed = True


class HostFileSession(ShuffleSession):
    def __init__(self, conf, tag: str, num_partitions: int,
                 owner: Optional[int], catalog, metrics):
        super().__init__(tag, owner)
        from spark_rapids_tpu import config as C
        self._catalog = catalog
        self._metrics = metrics
        self.num_partitions = num_partitions
        base = str(conf.get(C.SHUFFLE_TRANSPORT_HOSTFILE_DIR) or "") \
            or default_spool_dir()
        self.worker = str(conf.get(
            C.SHUFFLE_TRANSPORT_HOSTFILE_WORKER_ID) or "") \
            or f"w{os.getpid()}"
        # Exclusive-manifest mode (cluster stage outputs): ONE tag-scoped
        # manifest published by whichever worker computed the stage —
        # commit() atomically REPLACES it, so a recompute on a different
        # worker can never leave a fetcher a mix of old and new shards.
        self.exclusive = bool(conf.get(
            C.SHUFFLE_TRANSPORT_HOSTFILE_EXCLUSIVE_MANIFEST))
        self.expected_workers = 1 if self.exclusive else max(int(conf.get(
            C.SHUFFLE_TRANSPORT_HOSTFILE_EXPECTED_WORKERS)), 1)
        self.fetch_timeout_ms = int(conf.get(
            C.SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS))
        from spark_rapids_tpu.parallel.transport import rendezvous as RV
        self._rv_addr = RV.parse_addr(str(conf.get(
            C.SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS) or ""))
        self._rv_params = RV.client_params(conf)
        # Cluster session roles (parallel/cluster/): a fetch-only
        # session consumes another process's stage output — its
        # invalidate/close must never delete the producer's spool data;
        # keep_on_close additionally preserves THIS session's published
        # output past ctx.close() (the coordinator owns query-end spool
        # cleanup, not the producing worker's context teardown).
        self.fetch_only = False
        self.keep_on_close = False
        self.root = os.path.join(base, tag)
        self._my_dir = os.path.join(self.root, self.worker)
        self._seq: Dict[int, int] = {}
        # This worker's manifest entries: partition -> [entry, ...]
        self._written: Dict[int, List[dict]] = {}
        self._committed = False
        # Fetch-side caches: worker manifests + per-partition handles.
        self._manifests: Optional[List[dict]] = None
        self._handles: Dict[int, List[HostFileShardHandle]] = {}

    def _manifest_path(self, worker: Optional[str] = None) -> str:
        name = "exchange.manifest.json" if self.exclusive else \
            f"{worker or self.worker}.manifest.json"
        return os.path.join(self.root, name)

    # -- map side ------------------------------------------------------------
    def write_shard(self, partition: int, batch) -> None:
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.memory.stores import batch_to_shard_blob
        from spark_rapids_tpu.parallel import transport as T
        faults.fault_point("transport.write", owner=self.owner)
        blob = batch_to_shard_blob(batch)
        seq = self._seq.get(partition, 0)
        self._seq[partition] = seq + 1
        os.makedirs(self._my_dir, exist_ok=True)
        fname = f"p{partition:05d}-{seq:04d}.shard"
        path = os.path.join(self._my_dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        rows = batch.rows_hint
        self.record_shard_bytes(partition, len(blob))
        self._written.setdefault(partition, []).append(
            {"file": f"{self.worker}/{fname}",
             "capacity": int(batch.capacity),
             "rows": None if rows is None else int(rows),
             "bytes": len(blob)})
        T.record("transportBytesWritten", len(blob))
        T.record("transportShardsWritten")
        if self._metrics is not None:
            self._metrics.add("transportBytesWritten", len(blob))
            self._metrics.add("transportShardsWritten", 1)

    def commit(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        manifest = {"worker": self.worker,
                    "num_partitions": self.num_partitions,
                    "shards": {str(p): entries
                               for p, entries in self._written.items()}}
        path = self._manifest_path()
        tmp = path + f".{self.worker}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
        # os.replace is the atomicity contract: a concurrent fetcher
        # sees the previous complete manifest or this complete manifest,
        # never a torn or merged one — in exclusive mode this is what
        # makes a stage recompute on a different worker REPLACE the dead
        # worker's shard set wholesale.
        os.replace(tmp, path)
        self._committed = True
        if self._rv_addr is not None:
            from spark_rapids_tpu.parallel.transport import rendezvous \
                as RV
            timeout_s, retries, backoff = self._rv_params
            try:
                RV.announce_commit(self._rv_addr, self.tag, self.worker,
                                   timeout_s=timeout_s, retries=retries,
                                   backoff_ms=backoff)
            except RV.RendezvousUnavailableError as e:
                # The manifest is already durable on the spool; a dead
                # rendezvous only loses the event wait, so fetchers
                # degrade to directory polling instead of this commit
                # failing the query.
                from spark_rapids_tpu.parallel import transport as T
                T.record("rendezvousDegraded")
                _LOG.warning("rendezvous unavailable at commit "
                             "(degrading fetchers to manifest "
                             "polling): %s", e)

    # -- reduce side ---------------------------------------------------------
    def _load_manifests(self) -> List[dict]:
        if self._manifests is not None:
            return self._manifests
        if self._rv_addr is not None:
            from spark_rapids_tpu.parallel.transport import rendezvous \
                as RV
            timeout_s, retries, backoff = self._rv_params
            try:
                RV.wait_committed(self._rv_addr, self.tag,
                                  self.expected_workers,
                                  self.fetch_timeout_ms,
                                  connect_timeout_s=timeout_s,
                                  retries=retries, backoff_ms=backoff)
            except RV.RendezvousUnavailableError as e:
                # Degrade to directory polling below — the spool is the
                # source of truth; the rendezvous only saves the poll.
                from spark_rapids_tpu.parallel import transport as T
                T.record("rendezvousDegraded")
                _LOG.warning("rendezvous unavailable at fetch "
                             "(degrading to manifest polling): %s", e)
        deadline = time.monotonic() + self.fetch_timeout_ms / 1000.0
        manifests: List[dict] = []
        while True:
            manifests = []
            try:
                names = sorted(os.listdir(self.root))
            except FileNotFoundError:
                names = []
            for name in names:
                if not name.endswith(".manifest.json"):
                    continue
                if self.exclusive and name != "exchange.manifest.json":
                    continue
                try:
                    with open(os.path.join(self.root, name),
                              encoding="utf-8") as f:
                        m = json.load(f)
                except (OSError, ValueError):
                    continue      # racing writer; re-poll
                if not valid_manifest(m):
                    continue      # torn/partial write; not published yet
                manifests.append(m)
            if len(manifests) >= self.expected_workers:
                break
            if time.monotonic() >= deadline:
                raise ShardLostError(
                    f"exchange {self.tag}: {len(manifests)}/"
                    f"{self.expected_workers} worker manifests in "
                    f"{self.root} after {self.fetch_timeout_ms}ms",
                    owner=self.owner)
            time.sleep(0.02)
        manifests.sort(key=lambda m: str(m.get("worker", "")))
        self._manifests = manifests
        return manifests

    def fetch_shards(self, partition: int):
        handles = self._handles.get(partition)
        if handles is None:
            handles = []
            for m in self._load_manifests():
                for entry in m.get("shards", {}).get(str(partition), []):
                    handles.append(HostFileShardHandle(
                        self, os.path.join(self.root, entry["file"]),
                        int(entry["capacity"]), entry.get("rows")))
            self._handles[partition] = handles
        return handles

    def _fetch_blob(self, path: str):
        """Read + CRC-verify + upload one shard file; the transport
        fault site and the refetch-once rung live here."""
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.columnar.wire import WireCorruptionError
        from spark_rapids_tpu.memory.stores import shard_blob_to_batch
        from spark_rapids_tpu.parallel import transport as T
        faults.check_cancelled()
        e = faults.check_fault("transport",
                               ("lostshard", "oom", "transient"))
        if e is not None:
            if e.kind == "oom":
                raise faults.InjectedOomError("transport")
            if e.kind == "transient":
                raise faults.InjectedTransientError("transport")
            # lostshard: delete the data at rest FIRST — recovery must
            # rewrite the shard, not re-read a survivor.
            try:
                os.remove(path)
            except OSError:
                pass
            T.record("remoteShardsLost")
            raise ShardLostError(f"injected loss of {path}",
                                 owner=self.owner)
        last: Optional[WireCorruptionError] = None
        for _ in range(2):
            try:
                with open(path, "rb") as f:
                    framed = f.read()
            except OSError as err:
                T.record("remoteShardsLost")
                raise ShardLostError(f"{path}: {err}",
                                     owner=self.owner) from err
            framed = faults.corrupt_blob("transport", framed)
            try:
                batch = shard_blob_to_batch(framed)
            except WireCorruptionError as err:
                last = err
                faults.record("corruptionsDetected")
                T.record("remoteShardRefetches")
                faults.record("remoteShardRefetches")
                _LOG.warning("shard frame checksum mismatch (%s), "
                             "refetching: %s", path, err)
                continue
            T.record("transportBytesFetched", len(framed))
            T.record("transportShardsFetched")
            if self._metrics is not None:
                self._metrics.add("transportBytesFetched", len(framed))
                self._metrics.add("transportShardsFetched", 1)
            return batch
        # Persistently corrupt at rest: the durable output is gone.
        # Owner-tag the failure so lineage recovery recomputes just the
        # owning stage (the exchange.serve CRC contract, applied here).
        last.fault_owner = self.owner
        raise last

    # -- lifecycle -----------------------------------------------------------
    def _close_handles(self) -> None:
        for hs in self._handles.values():
            for h in hs:
                h.close()
        self._handles = {}
        self._manifests = None

    def invalidate(self) -> None:
        """Drop the WHOLE durable output (stage recompute contract): a
        recompute rewrites every worker's shards under the same tag. A
        fetch-only session (cluster consumer of another process's stage
        output) drops only its LOCAL handle/manifest caches — deleting
        the producer's spool data is the coordinator's call, never a
        consumer's."""
        self._close_handles()
        if self.fetch_only:
            return
        shutil.rmtree(self.root, ignore_errors=True)
        self._written = {}
        self._seq = {}
        self._committed = False

    def close(self) -> None:
        """Query teardown: release fetched handles and remove what THIS
        worker wrote. Other workers' spool data survives — their
        sessions own it (cross-process fetches may still be running).
        keep_on_close sessions (cluster stage outputs) release handles
        only: the published spool output outlives this context, and the
        coordinator removes the query's spool tree at query end."""
        self._close_handles()
        if self.fetch_only or self.keep_on_close:
            return
        shutil.rmtree(self._my_dir, ignore_errors=True)
        if self._committed or not self.exclusive:
            # Only a committed manifest is ours to retract: in exclusive
            # mode the single manifest may belong to ANOTHER worker's
            # commit, which an uncommitted session must never delete.
            try:
                os.remove(self._manifest_path())
            except OSError:
                pass
        try:
            os.rmdir(self.root)   # last worker out turns off the lights
        except OSError:
            pass


class HostFileTransport(ShuffleTransport):
    name = "hostfile"

    def open(self, conf, tag: str, num_partitions: int,
             owner: Optional[int] = None, catalog=None,
             metrics=None) -> HostFileSession:
        return HostFileSession(conf, tag, num_partitions, owner,
                               catalog, metrics)
