"""Lightweight socket rendezvous for the hostfile shuffle transport.

The spool directory carries the DATA; this module carries the
MEMBERSHIP signal: a committing worker announces "exchange X, worker W
committed" over one short-lived TCP connection, and a reduce-side
fetcher blocks until N distinct workers have committed an exchange —
replacing manifest-file polling with an event wait (the metadata round
of the reference's UCX transport, ~ActiveMessage registration, shrunk
to one line of text).

Wire protocol (UTF-8 lines, one request per connection):

    COMMIT <exchange-tag> <worker-id>\n      -> OK\n
    WAIT <exchange-tag> <n> <timeout-ms>\n   -> OK <k>\n | TIMEOUT <k>\n
    LIST <exchange-tag>\n                    -> OK <w1,w2,...>\n
    PING\n                                   -> OK\n

The server is a few dozen lines on purpose: it coordinates, it never
carries shard bytes, and losing it only degrades fetchers back to
manifest polling.

Hardening (ISSUE 13 satellite): every client round trip runs under a
connect/read timeout with a bounded retry + deterministic exponential
backoff, so a dead rendezvous peer fails FAST with the typed
:class:`RendezvousUnavailableError` (message-prefixed ``UNAVAILABLE:``,
which ``memory/oom.is_transient_error`` maps onto the recovery
ladder's transient rung) instead of hanging a fetch indefinitely. The
accept side gets a read timeout too, so a half-open client can never
pin a handler thread.

Subclassing: unknown verbs are delegated to ``server.dispatch_extra``
— the cluster control plane (parallel/cluster/coordinator.py) extends
this exact server with stage-task verbs so workers "register with the
rendezvous" through one socket and one wire grammar.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

_BACKOFF_CAP_S = 2.0


class RendezvousUnavailableError(ConnectionError):
    """A rendezvous peer was unreachable within the bounded retry
    schedule. The ``UNAVAILABLE:`` prefix makes it a transient error to
    the recovery ladder (memory/oom.is_transient_error); the hostfile
    transport additionally catches it and degrades to manifest-file
    polling instead of failing the fetch."""

    def __init__(self, addr: Tuple[str, int], attempts: int,
                 last: BaseException):
        super().__init__(
            f"UNAVAILABLE: rendezvous {addr[0]}:{addr[1]} unreachable "
            f"after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}")
        self.addr = addr
        self.attempts = attempts


class _State:
    def __init__(self):
        self.lock = threading.Condition()
        self.committed: Dict[str, Set[str]] = {}


class _Handler(socketserver.StreamRequestHandler):
    # A dead/half-open client times out its read instead of pinning a
    # handler thread forever (accept-side hardening).
    timeout = 30.0

    def handle(self):
        state: _State = self.server.state        # type: ignore[attr-defined]
        line = self.rfile.readline().decode("utf-8", "replace").strip()
        parts = line.split()
        if not parts:
            return
        cmd = parts[0].upper()
        if cmd == "PING":
            self.wfile.write(b"OK\n")
        elif cmd == "COMMIT" and len(parts) == 3:
            _, tag, worker = parts
            with state.lock:
                state.committed.setdefault(tag, set()).add(worker)
                state.lock.notify_all()
            self.wfile.write(b"OK\n")
        elif cmd == "LIST" and len(parts) == 2:
            with state.lock:
                ws = sorted(state.committed.get(parts[1], ()))
            self.wfile.write(f"OK {','.join(ws)}\n".encode())
        elif cmd == "WAIT" and len(parts) == 4:
            _, tag, n_s, timeout_s = parts
            n, timeout_ms = int(n_s), int(timeout_s)
            deadline = time.monotonic() + timeout_ms / 1000.0
            with state.lock:
                while len(state.committed.get(tag, ())) < n:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    state.lock.wait(min(left, 0.2))
                k = len(state.committed.get(tag, ()))
            ok = b"OK" if k >= n else b"TIMEOUT"
            self.wfile.write(ok + f" {k}\n".encode())
        else:
            # Protocol extension point: a subclassed server (the cluster
            # coordinator) serves its extra verbs here; the base server
            # answers ERR exactly as before.
            resp = self.server.dispatch_extra(parts)    # type: ignore
            self.wfile.write(b"ERR\n" if resp is None else resp)


class _TCPServer(socketserver.ThreadingTCPServer):
    # A restarted coordinator must rebind its fixed port immediately
    # after its predecessor was SIGKILLed; without SO_REUSEADDR the
    # lingering TIME_WAIT sockets make the bind fail with EADDRINUSE
    # and failover recovery never comes up.
    allow_reuse_address = True


class RendezvousServer:
    """Threaded TCP rendezvous. ``addr`` is the bound (host, port) —
    pass port 0 to let the OS pick one (tests)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        srv = self._srv = _TCPServer(
            (host, port), _Handler, bind_and_activate=True)
        srv.daemon_threads = True
        srv.state = _State()                  # type: ignore[attr-defined]
        srv.dispatch_extra = self.dispatch_extra  # type: ignore
        self.addr: Tuple[str, int] = srv.server_address[:2]
        self._thread = threading.Thread(
            target=srv.serve_forever, name="srt-rendezvous",
            daemon=True)
        self._thread.start()

    def dispatch_extra(self, parts: List[str]) -> Optional[bytes]:
        """Handle one non-base verb; None = unknown (client gets ERR).
        Subclasses (parallel/cluster/coordinator.py) override."""
        return None

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def _roundtrip(addr: Tuple[str, int], line: str,
               timeout_s: float = 10.0, retries: int = 3,
               backoff_ms: int = 50) -> str:
    """One request/response round trip with bounded retry.

    ``timeout_s`` bounds the connect AND the response read of each
    attempt; a refused/timed-out attempt backs off deterministically
    (``backoff_ms * 2^i``, capped, plus a deterministic jitter derived
    from the request line — so a fleet of clients retrying through one
    coordinator outage desynchronizes instead of stampeding in
    lockstep, without introducing nondeterminism) and retries up to
    ``retries`` extra times before raising
    :class:`RendezvousUnavailableError`.
    """
    attempts = max(int(retries), 0) + 1
    last: Optional[BaseException] = None
    for i in range(attempts):
        if i:
            base = min(backoff_ms * (2 ** (i - 1)) / 1000.0,
                       _BACKOFF_CAP_S)
            jitter = (zlib.crc32(f"{line}|{i}".encode()) % 1000) / 1000.0
            time.sleep(base * (1.0 + 0.25 * jitter))
        try:
            with socket.create_connection(addr, timeout=timeout_s) as s:
                s.sendall(line.encode("utf-8"))
                f = s.makefile("rb")
                return f.readline().decode("utf-8", "replace").strip()
        except (OSError, socket.timeout) as e:
            last = e
    raise RendezvousUnavailableError(addr, attempts, last)


def parse_addr(spec: str) -> Optional[Tuple[str, int]]:
    spec = (spec or "").strip()
    if not spec:
        return None
    host, _, port = spec.rpartition(":")
    return (host or "127.0.0.1", int(port))


def client_params(conf) -> Tuple[float, int, int]:
    """(timeout_s, retries, backoff_ms) for one round trip, from the
    hostfile.rendezvous.* hardening keys."""
    from spark_rapids_tpu import config as C
    return (max(int(conf.get(
                C.SHUFFLE_TRANSPORT_HOSTFILE_RV_CONNECT_TIMEOUT_MS)),
                1) / 1000.0,
            max(int(conf.get(C.SHUFFLE_TRANSPORT_HOSTFILE_RV_RETRIES)),
                0),
            max(int(conf.get(
                C.SHUFFLE_TRANSPORT_HOSTFILE_RV_BACKOFF_MS)), 1))


def announce_commit(addr: Tuple[str, int], tag: str, worker: str,
                    timeout_s: float = 10.0, retries: int = 3,
                    backoff_ms: int = 50) -> None:
    _roundtrip(addr, f"COMMIT {tag} {worker}\n", timeout_s=timeout_s,
               retries=retries, backoff_ms=backoff_ms)


def wait_committed(addr: Tuple[str, int], tag: str, n: int,
                   timeout_ms: int, connect_timeout_s: float = 10.0,
                   retries: int = 3, backoff_ms: int = 50) -> bool:
    """Block until ``n`` workers committed ``tag``; False on timeout."""
    resp = _roundtrip(addr, f"WAIT {tag} {n} {timeout_ms}\n",
                      timeout_s=timeout_ms / 1000.0 + connect_timeout_s,
                      retries=retries, backoff_ms=backoff_ms)
    return resp.startswith("OK")
