"""Lightweight socket rendezvous for the hostfile shuffle transport.

The spool directory carries the DATA; this module carries the
MEMBERSHIP signal: a committing worker announces "exchange X, worker W
committed" over one short-lived TCP connection, and a reduce-side
fetcher blocks until N distinct workers have committed an exchange —
replacing manifest-file polling with an event wait (the metadata round
of the reference's UCX transport, ~ActiveMessage registration, shrunk
to one line of text).

Wire protocol (UTF-8 lines, one request per connection):

    COMMIT <exchange-tag> <worker-id>\n      -> OK\n
    WAIT <exchange-tag> <n> <timeout-ms>\n   -> OK <k>\n | TIMEOUT <k>\n
    LIST <exchange-tag>\n                    -> OK <w1,w2,...>\n
    PING\n                                   -> OK\n

The server is a few dozen lines on purpose: it coordinates, it never
carries shard bytes, and losing it only degrades fetchers back to
manifest polling.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Dict, Optional, Set, Tuple


class _State:
    def __init__(self):
        self.lock = threading.Condition()
        self.committed: Dict[str, Set[str]] = {}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        state: _State = self.server.state        # type: ignore[attr-defined]
        line = self.rfile.readline().decode("utf-8", "replace").strip()
        parts = line.split()
        if not parts:
            return
        cmd = parts[0].upper()
        if cmd == "PING":
            self.wfile.write(b"OK\n")
        elif cmd == "COMMIT" and len(parts) == 3:
            _, tag, worker = parts
            with state.lock:
                state.committed.setdefault(tag, set()).add(worker)
                state.lock.notify_all()
            self.wfile.write(b"OK\n")
        elif cmd == "LIST" and len(parts) == 2:
            with state.lock:
                ws = sorted(state.committed.get(parts[1], ()))
            self.wfile.write(f"OK {','.join(ws)}\n".encode())
        elif cmd == "WAIT" and len(parts) == 4:
            _, tag, n_s, timeout_s = parts
            n, timeout_ms = int(n_s), int(timeout_s)
            deadline = time.monotonic() + timeout_ms / 1000.0
            with state.lock:
                while len(state.committed.get(tag, ())) < n:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    state.lock.wait(min(left, 0.2))
                k = len(state.committed.get(tag, ()))
            ok = b"OK" if k >= n else b"TIMEOUT"
            self.wfile.write(ok + f" {k}\n".encode())
        else:
            self.wfile.write(b"ERR\n")


class RendezvousServer:
    """Threaded TCP rendezvous. ``addr`` is the bound (host, port) —
    pass port 0 to let the OS pick one (tests)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.state = _State()            # type: ignore[attr-defined]
        self.addr: Tuple[str, int] = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="srt-rendezvous",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def _roundtrip(addr: Tuple[str, int], line: str,
               timeout_s: float = 10.0) -> str:
    with socket.create_connection(addr, timeout=timeout_s) as s:
        s.sendall(line.encode("utf-8"))
        f = s.makefile("rb")
        return f.readline().decode("utf-8", "replace").strip()


def parse_addr(spec: str) -> Optional[Tuple[str, int]]:
    spec = (spec or "").strip()
    if not spec:
        return None
    host, _, port = spec.rpartition(":")
    return (host or "127.0.0.1", int(port))


def announce_commit(addr: Tuple[str, int], tag: str, worker: str) -> None:
    _roundtrip(addr, f"COMMIT {tag} {worker}\n")


def wait_committed(addr: Tuple[str, int], tag: str, n: int,
                   timeout_ms: int) -> bool:
    """Block until ``n`` workers committed ``tag``; False on timeout."""
    resp = _roundtrip(addr, f"WAIT {tag} {n} {timeout_ms}\n",
                      timeout_s=timeout_ms / 1000.0 + 10.0)
    return resp.startswith("OK")
