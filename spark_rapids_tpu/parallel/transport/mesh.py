"""Mesh shuffle transport: the ICI collective path behind the SPI.

The data plane here is ``jax.lax.all_to_all`` inside MeshExchangeExec's
shard_map program — the fabric moves the bytes, not this module. What
the SPI contributes is the DURABLE half: each device's post-exchange
shard registers through ``write_shard`` as an owner-tagged spillable
catalog handle (memory/stores.py — bounded by the memory ladder,
CRC-framed once spilled), and ``fetch_shards``/``invalidate`` give the
collective output the same lineage-recovery contract as every other
transport: lose a shard, recompute one stage.

The transport does not fold partitions itself — MeshExchangeExec's
fold/split pass (partition count != mesh size) writes one shard per
LOGICAL partition, so consumers never see mesh geometry.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.parallel.transport.base import (
    ShuffleSession, ShuffleTransport)


class MeshSession(ShuffleSession):
    def __init__(self, tag: str, num_partitions: int,
                 owner: Optional[int], catalog):
        super().__init__(tag, owner)
        self._catalog = catalog
        self.buckets: List[list] = [[] for _ in range(num_partitions)]

    def write_shard(self, partition: int, batch) -> None:
        from spark_rapids_tpu.memory.stores import (
            PRIORITY_SHUFFLE_OUTPUT, SpillableBatch)
        self.record_shard_bytes(partition, batch.device_size_bytes())
        self.buckets[partition].append(SpillableBatch(
            self._catalog, batch, PRIORITY_SHUFFLE_OUTPUT))

    def commit(self) -> None:
        pass

    def fetch_shards(self, partition: int):
        return self.buckets[partition]

    def invalidate(self) -> None:
        for blist in self.buckets:
            for sb in blist:
                sb.close()
        self.buckets = [[] for _ in self.buckets]


class MeshTransport(ShuffleTransport):
    name = "mesh"

    def open(self, conf, tag: str, num_partitions: int,
             owner: Optional[int] = None, catalog=None,
             metrics=None) -> MeshSession:
        assert catalog is not None, \
            "mesh transport needs the query's buffer catalog"
        return MeshSession(tag, num_partitions, owner, catalog)
