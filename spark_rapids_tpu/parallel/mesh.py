"""Device-mesh collectives: the ICI/DCN distribution layer
(ref: SURVEY.md §2.6 TPU mapping — the UCX client/server pull protocol of
shuffle-plugin/.../ucx/UCX.scala becomes a *planned collective exchange*).

Design (scaling-book recipe): pick a mesh, annotate shardings, let XLA
insert collectives.
- One logical table = one DeviceBatch per device, sharded over the ``data``
  mesh axis (per-partition data parallelism, SURVEY.md §2.5).
- Hash shuffle = ``jax.lax.all_to_all`` over ICI: each device splits its
  batch into per-destination pieces (the contiguousSplit analog), the
  collective transposes piece ownership, receivers concatenate.
- Broadcast join build = ``all_gather`` once (GpuBroadcastExchangeExec).
- Partial->final aggregation crosses the exchange exactly like the
  reference's partial/final GpuHashAggregate pair.

Everything here is shape-static and runs under ``shard_map`` + ``jit``; the
driver validates it on an N-virtual-device CPU mesh
(xla_force_host_platform_device_count) exactly like tests/conftest.py.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.shims import (shard_map, tree_flatten,
                                    tree_map, tree_unflatten)

from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, concat_batches)
from spark_rapids_tpu.parallel.partitioning import Partitioning, split_batch

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              axis: str = DATA_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS):
    """Sharding that splits every batch leaf's leading (row) axis across the
    mesh — used to lay out a logical table as one shard per device."""
    return NamedSharding(mesh, P(axis))


# ---------------------------------------------------------------------------
# Collective shuffle (inside shard_map)
# ---------------------------------------------------------------------------

def all_to_all_exchange(batch: DeviceBatch, pids: jnp.ndarray,
                        n_devices: int,
                        axis: str = DATA_AXIS,
                        piece_capacity: Optional[int] = None
                        ) -> DeviceBatch:
    """ICI hash-shuffle step for one device's shard (call under shard_map).

    Splits the local batch into per-destination pieces, exchanges piece
    ownership with ``all_to_all`` (one fused ICI collective, not a peer
    pull protocol), and concatenates the received pieces.

    ``piece_capacity`` is the static per-destination piece size. Default
    (None) is the worst case — every piece at the full shard capacity, an
    n_devices-fold wire inflation. The planner's two-phase path
    (SURVEY §7 sizes-then-data) exchanges COUNTS first and passes the
    observed max, so the collective moves ~the real data volume.
    """
    pieces = split_batch(batch, pids, n_devices)
    if piece_capacity is not None:
        # split_batch pieces are already packed prefixes; truncating to
        # the exchanged max is a static slice, not another scatter pass.
        def trunc(p: DeviceBatch) -> DeviceBatch:
            cols = tuple(
                DeviceColumn(c.dtype, c.data[:piece_capacity],
                             c.validity[:piece_capacity],
                             c.lengths[:piece_capacity]
                             if c.dtype.is_string else None)
                for c in p.columns)
            return DeviceBatch(
                cols, jnp.minimum(p.num_rows, piece_capacity))
        pieces = [trunc(p) for p in pieces]
    # Stack piece leaves -> leading axis = destination device.
    stacked = tree_map(lambda *xs: jnp.stack(xs), *pieces)
    received = jax.lax.all_to_all(stacked, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
    # received leaf shape == stacked leaf shape; index i = piece from peer i.
    parts = [tree_map(lambda x, i=i: x[i], received)
             for i in range(n_devices)]
    total_cap = sum(p.capacity for p in parts)
    return concat_batches(parts, bucket_capacity(total_cap))


def exchange_counts(batch: DeviceBatch, pids: jnp.ndarray,
                    n_devices: int, axis: str = DATA_AXIS) -> jnp.ndarray:
    """Phase 1 of the two-phase shuffle: this device's per-destination
    live-row counts, all_to_all'd so every device holds the counts of the
    pieces it WILL receive — a (n_devices,) int32 collective, the
    metadata exchange that replaces the reference's UCX metadata round
    (SURVEY §2.6)."""
    live = batch.row_mask()
    key = jnp.where(live, pids, n_devices)
    counts = jax.ops.segment_sum(
        jnp.ones((batch.capacity,), jnp.int32), key,
        num_segments=n_devices + 1)[:n_devices]
    return jax.lax.all_to_all(counts[:, None], axis, split_axis=0,
                              concat_axis=0, tiled=False).reshape(-1)


def all_gather_batch(batch: DeviceBatch, n_devices: int,
                     axis: str = DATA_AXIS) -> DeviceBatch:
    """Replicate every device's shard to all devices (broadcast build side:
    the one-time all-gather replacing collect+torrent-broadcast+re-upload).
    """
    gathered = jax.lax.all_gather(batch, axis, axis=0, tiled=False)
    parts = [tree_map(lambda x, i=i: x[i], gathered)
             for i in range(n_devices)]
    total_cap = sum(p.capacity for p in parts)
    return concat_batches(parts, bucket_capacity(total_cap))


# ---------------------------------------------------------------------------
# Distributed plan step: shard_map over a q1-shaped pipeline
# ---------------------------------------------------------------------------

def distributed_aggregate_step(mesh: Mesh, agg_exec,
                               partitioning: Partitioning,
                               axis: str = DATA_AXIS):
    """Build a jitted distributed aggregation step over ``mesh``.

    Per device (under shard_map):
      partial = local groupby update of the device's shard
      exchanged = all_to_all by hash(key) pmod n  (ICI shuffle)
      final = merge + finalize of the received partials

    ``agg_exec`` is a HashAggregateExec used purely for its kernels
    (update/merge/finalize are pure batch->batch functions).
    """
    n = mesh.devices.size

    def step(local_batch: DeviceBatch) -> DeviceBatch:
        partial = agg_exec._update_batch(local_batch,
                                         jnp.asarray(0, jnp.int64))
        pids = partitioning.partition_ids(partial)
        exchanged = all_to_all_exchange(partial, pids, n, axis)
        merged = agg_exec._merge_batch(exchanged)
        return agg_exec._finalize_batch(merged)

    def wrapped(stacked_local):
        # in_specs P(axis) leaves a unit device axis on each leaf locally.
        local = tree_map(lambda x: x[0], stacked_local)
        out = step(local)
        return tree_map(lambda x: x[None], out)

    sharded = shard_map(wrapped, mesh, in_specs=(P(axis),),
                        out_specs=P(axis))
    return jax.jit(sharded)


def distributed_join_agg_step(mesh: Mesh, join_exec, agg_exec,
                              join_partitioning_left,
                              join_partitioning_right,
                              agg_partitioning,
                              axis: str = DATA_AXIS,
                              join_out_capacity: Optional[int] = None):
    """Distributed join + aggregate step (TPC-H q3-shaped):

    per device: all_to_all both sides by join key -> local hash join ->
    partial agg -> all_to_all by group key -> final agg.

    Returns (result, overflowed): a join can emit up to |L|x|R| pairs per
    device; ``join_out_capacity`` bounds the static expansion buffer
    (default: the exact |L|x|R| product when small, else 4x the input).
    ``overflowed`` is a per-device bool — callers must check it, since
    pairs beyond the capacity are truncated.
    """
    from spark_rapids_tpu.ops import join as J
    n = mesh.devices.size

    def step(left: DeviceBatch, right: DeviceBatch):
        lex = all_to_all_exchange(
            left, join_partitioning_left.partition_ids(left), n, axis)
        rex = all_to_all_exchange(
            right, join_partitioning_right.partition_ids(right), n, axis)
        built = J.build_side(rex, [k.ordinal
                                   for k in join_exec.right_keys])
        lo, counts, plive = J.probe_ranges(
            built, lex, [k.ordinal for k in join_exec.left_keys])
        if join_out_capacity is not None:
            out_cap = bucket_capacity(join_out_capacity)
        elif lex.capacity * rex.capacity <= (1 << 20):
            out_cap = bucket_capacity(lex.capacity * rex.capacity)
        else:
            out_cap = bucket_capacity(4 * (lex.capacity + rex.capacity))
        p, b, valid, num_rows, overflow = J.expand_pairs(
            lo, counts, out_cap, lex.capacity)
        valid = J._pair_keys_equal(
            built, b, lex, p, [k.ordinal for k in join_exec.left_keys],
            valid)
        probe_cols = J._gather_cols(lex, p, valid)
        build_cols = J._gather_cols(built.batch, b, valid)
        pairs = DeviceBatch(
            tuple(probe_cols) + tuple(build_cols), num_rows).compact(valid)
        partial = agg_exec._update_batch(pairs, jnp.asarray(0, jnp.int64))
        pids = agg_partitioning.partition_ids(partial)
        exchanged = all_to_all_exchange(partial, pids, n, axis)
        merged = agg_exec._merge_batch(exchanged)
        return agg_exec._finalize_batch(merged), overflow

    def wrapped(l_stacked, r_stacked):
        left = tree_map(lambda x: x[0], l_stacked)
        right = tree_map(lambda x: x[0], r_stacked)
        out, overflow = step(left, right)
        return (tree_map(lambda x: x[None], out), overflow[None])

    sharded = shard_map(wrapped, mesh, in_specs=(P(axis), P(axis)),
                        out_specs=P(axis))
    return jax.jit(sharded)


def shard_batches(mesh: Mesh, per_device: List[DeviceBatch],
                  axis: str = DATA_AXIS) -> DeviceBatch:
    """Assemble per-device shards into one globally-sharded DeviceBatch
    (leaves get a leading device axis mapped onto the mesh)."""
    stacked = tree_map(lambda *xs: jnp.stack(xs), *per_device)
    sharding = NamedSharding(mesh, P(axis))
    return tree_map(lambda x: jax.device_put(x, sharding), stacked)
