"""Stage-graph recovery: lineage-scoped fault tolerance (ISSUE 3).

Spark's core resilience story is *lineage*: when a task's input shuffle
data is lost, only the stages that produced the lost partitions recompute
— never the whole job (Zaharia et al., RDDs, NSDI 2012). This engine's
per-query materializations (shuffle buckets, broadcast singles, mesh
shards) already live in the ExecContext, so the same story falls out of
two pieces:

1. **A stage DAG over the physical plan.** :func:`build_stage_graph`
   splits the Exec tree at exchange/broadcast boundaries (any exec with a
   ``stage_invalidate`` method is a boundary). Each :class:`Stage` owns
   the operators between its boundary exchange and the next boundaries
   below; ``parents`` point at the stages whose durable outputs feed it —
   the lineage edges recovery walks.

2. **Durable, invalidatable stage outputs.** Every exchange registers its
   materialization through the shuffle-transport SPI
   (``parallel/transport/``): spillable catalog handles on the
   ``inprocess``/``mesh`` transports (``memory/stores.py``
   SpillableBatch — bounded by the memory ladder, CRC-framed via
   ``wire.frame_blob`` once spilled to disk), CRC-framed spool files on
   the cross-process ``hostfile`` transport — and exposes
   ``stage_invalidate(ctx)`` to drop it. Because re-running a collect on
   the SAME context serves every still-cached materialization instead of
   recomputing it, *invalidate-one-stage + re-collect* IS partition-scoped
   recovery: only the lost stage (and the never-materialized result
   stage above it) re-executes; sibling stages' scans never run again.
   A lost or persistently-corrupt REMOTE shard behaves identically: the
   transport fetch raises owner-tagged (``ShardLostError`` /
   ``WireCorruptionError`` with ``fault_owner``), :func:`stage_for_error`
   maps it to the owning exchange's stage, and the recompute REWRITES
   the shard at rest — one stage, never a whole-query retry.

The same DAG also powers the pipelined executor (parallel/pipeline.py,
ISSUE 4): stages whose parents are all materialized are *independent*,
so their boundary exchanges' ``stage_prematerialize`` hooks run
concurrently (the build- and probe-side scans of a shuffled join
materialize in parallel), bounded by
``spark.rapids.sql.pipeline.maxConcurrentStages``.

The planner's retry ladder (plan/planner.py) demotes through:
watchdog partition retry (ops/base.py) -> stage recompute (this module)
-> whole-query retry on a fresh context (only when the loss cannot be
attributed to a stage — "a root stage is gone" — or the recompute budget
is spent). Every recompute bumps the ``stageRecomputes`` counter (plus a
per-stage ``stageRecomputes.stage<N>`` detail) through
spark_rapids_tpu.faults, surfacing in ``DataFrame.metrics()`` and
bench.py's recovery JSON block.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional

_LOG = logging.getLogger("spark_rapids_tpu.stages")


def is_stage_boundary(op) -> bool:
    """An exec whose materialized output is a durable stage output —
    shuffle, broadcast and mesh exchanges all expose stage_invalidate."""
    return callable(getattr(op, "stage_invalidate", None))


@dataclasses.dataclass
class Stage:
    """One stage: the subtree between a boundary exchange (whose
    materialization is this stage's output; None for the result stage)
    and the child boundaries feeding it."""

    stage_id: int
    boundary: Optional[object]
    ops: List[object] = dataclasses.field(default_factory=list)
    parents: List[int] = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        root = "result" if self.boundary is None else \
            type(self.boundary).__name__
        return f"Stage#{self.stage_id}<{root}>"


class StageGraph:
    """Stage DAG of one physical plan: stages keyed by id, plus the
    exchange-exec-id -> stage index recovery uses to map a lost-output
    error back to the stage that owns the lost materialization."""

    def __init__(self):
        self.stages: Dict[int, Stage] = {}
        self.by_exchange: Dict[int, int] = {}
        self.root_stage_id: int = 0

    def __len__(self) -> int:
        return len(self.stages)

    def stage_of_exchange(self, exchange_id: int) -> Optional[Stage]:
        sid = self.by_exchange.get(exchange_id)
        return None if sid is None else self.stages.get(sid)

    def pretty(self) -> str:  # pragma: no cover - debug/explain aid
        lines = []
        for st in self.stages.values():
            members = ", ".join(type(o).__name__ for o in st.ops)
            lines.append(f"{st.name} parents={st.parents} [{members}]")
        return "\n".join(lines)


def build_stage_graph(root) -> StageGraph:
    """Split the physical plan at exchange boundaries into the stage DAG
    (Spark DAGScheduler's stage cut, applied to this engine's tree)."""
    g = StageGraph()

    def new_stage(boundary) -> Stage:
        st = Stage(len(g.stages), boundary)
        g.stages[st.stage_id] = st
        if boundary is not None:
            g.by_exchange[id(boundary)] = st.stage_id
        return st

    def walk(op, stage: Stage):
        stage.ops.append(op)
        for ch in op.children:
            if is_stage_boundary(ch):
                child = new_stage(ch)
                stage.parents.append(child.stage_id)
                walk(ch, child)
            else:
                walk(ch, stage)

    result = new_stage(None)
    g.root_stage_id = result.stage_id
    if is_stage_boundary(root):
        # Degenerate plan rooted at an exchange: the result stage is
        # empty and the root exchange owns its own (recoverable) stage.
        child = new_stage(root)
        result.parents.append(child.stage_id)
        walk(root, child)
    else:
        walk(root, result)
    return g


def stage_for_error(graph: Optional[StageGraph], e) -> Optional[Stage]:
    """Map a failure to the stage whose durable output is gone. Only
    errors tagged with a ``fault_owner`` (the owning exchange's id — set
    by injection sites and by the checksum-failure wrappers on durable
    reads) are attributable; anything else means a root/unattributable
    loss and the caller falls back to the whole-query retry."""
    if graph is None:
        return None
    owner = getattr(e, "fault_owner", None)
    if owner is None:
        return None
    return graph.stage_of_exchange(owner)


def invalidate_stage(ctx, stage: Stage) -> None:
    """Drop the stage's durable output from the context (cache entries +
    catalog registrations) so the next execution recomputes it from its
    parents' still-materialized outputs."""
    if stage.boundary is not None:
        stage.boundary.stage_invalidate(ctx)
    _LOG.warning("lineage recovery: invalidated %s; recomputing it from "
                 "its parent stages on the next attempt", stage.name)


def record_recompute(ctx, stage: Stage) -> None:
    """Bump the recovery counters for one stage recompute: the global
    aggregate, the per-stage detail (bench.py's JSON emits both), the
    query's Recovery metrics entry, and a flight-recorder instant so
    the rework shows on the trace timeline."""
    from spark_rapids_tpu import faults, monitoring
    from spark_rapids_tpu.ops.base import query_metrics_entry
    faults.record("stageRecomputes")
    faults.record(f"stageRecomputes.stage{stage.stage_id}")
    query_metrics_entry(ctx, "Recovery").add("stageRecomputes", 1)
    monitoring.instant("stage-recompute", "recovery",
                       args={"stage": stage.name})


def materialized_stage_count(ctx, graph: Optional[StageGraph]) -> int:
    """How many boundary stages still hold a durable, context-cached
    output right now. Class-aware preemption (plan/planner.py) reads
    this when a preempted query resumes: every stage counted here is
    served from its materialization instead of recomputing — the
    ``resumedStages`` counter that proves a suspension lost no work."""
    if graph is None or ctx is None:
        return 0
    n = 0
    for st in graph.stages.values():
        b = st.boundary
        if b is None:
            continue                    # the result stage is never durable
        key_fn = getattr(b, "_cache_key", None)
        if callable(key_fn):
            keys = (key_fn(True), key_fn(False))
        else:                           # mesh exchanges key by exec id
            keys = (f"meshx:{id(b):x}", f"meshx-host:{id(b):x}")
        if any(k in ctx.cache for k in keys):
            n += 1
    return n
