"""Cluster broadcast artifact cache (ISSUE 17 tentpole leg c).

Broadcast stages are NOT dispatchable (parallel/cluster/coordinator.py:
every process materializes broadcast singles locally, Spark executor
semantics), so in an N-process cluster the same build side is collected
and concatenated N times. This module turns the shuffle transport into
a build-artifact cache for them: the FIRST process to build a broadcast
single publishes it through the query's transport (hostfile spool or
objectstore) under a content-addressed tag, and every later process
adopts the committed blob instead of re-collecting the child.

Key discipline (``ClusterExecInfo.broadcast_tag``)::

    bc-<plan_fp>-s<sid>-g<gensum>

- ``plan_fp`` — sha256 of the query's plan pickle: two queries never
  collide, and driver + workers agree byte-for-byte (both hash the same
  shipped file);
- ``sid`` — the broadcast stage id in the shared deterministic DFS
  numbering;
- ``gensum`` — the sum of the GENERATIONS of the broadcast stage's
  dispatchable upstream stages: a recomputed input bumps its
  generation, which changes the tag, so a cached build of pre-recompute
  inputs is simply never found (defense-in-depth on top of
  bit-identical recomputes).

Same durability contract as every stage output: CRC-framed shard blob,
manifest-as-publication-barrier, refetch-once on CRC mismatch, and a
lost/corrupt cache entry degrades to a LOCAL REBUILD — a miss, never an
error and never a stage recompute (sessions are opened ``owner=None``
so a loss is unattributable by design).

Counters (process-global, bench.py's ``transport`` block):
``broadcastCacheHits``, ``broadcastCacheMisses`` (miss = built
locally), ``broadcastCachePublishes``.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

_LOG = logging.getLogger("spark_rapids_tpu.cluster")


def _cache_conf(ctx) -> Tuple[Optional[object], Optional[int]]:
    """(installed ClusterExecInfo, fetch timeout ms) when the cache is
    usable in this context, else (None, None)."""
    from spark_rapids_tpu import config as C
    info = ctx.cache.get("cluster")
    if info is None or not bool(ctx.conf.get(C.BROADCAST_CACHE_ENABLED)):
        return None, None
    return info, max(
        int(ctx.conf.get(C.BROADCAST_CACHE_FETCH_TIMEOUT_MS)), 1)


def maybe_fetch(ctx, exchange):
    """A published broadcast single for ``exchange``, or None (cache
    disabled / not a tagged broadcast stage / not published yet /
    lost / corrupt — all of which mean: build it locally).

    On a hit, returns ``(handle, batch)``: the handle satisfies the
    SpillableBatch get/release protocol the broadcast hit path uses, so
    the caller parks it at the exchange's cache key exactly like a
    locally-built single; its session is parked in ``ctx.cache`` too,
    so context teardown releases the fetched buffers."""
    info, timeout_ms = _cache_conf(ctx)
    if info is None:
        return None
    tag = info.broadcast_tag(exchange)
    if tag is None:
        return None
    from spark_rapids_tpu import monitoring
    from spark_rapids_tpu.parallel import transport as T
    try:
        sess = info.open_session(ctx, tag, 1, owner=None,
                                 fetch_timeout_ms=timeout_ms)
        sess.fetch_only = True
        handles = sess.fetch_shards(0)
        if len(handles) != 1:
            raise ValueError(
                f"broadcast cache entry {tag} has {len(handles)} "
                f"shards (want exactly 1)")
        batch = handles[0].get()
    except Exception as e:
        # Everything is a miss: not-yet-published (fetch timeout),
        # shard lost under us, CRC failure past the refetch, store
        # unavailable. The local rebuild is always correct.
        T.record("broadcastCacheMisses")
        _LOG.debug("broadcast cache miss for %s: %s: %s", tag,
                   type(e).__name__, e)
        return None
    ctx.cache[f"bcastcache-sess:{tag}"] = sess
    T.record("broadcastCacheHits")
    monitoring.instant("broadcast-cache-hit", "shuffle",
                       args={"tag": tag, "rows": batch.rows_hint})
    _LOG.info("broadcast cache hit: adopted %s (%d capacity) instead "
              "of rebuilding", tag, batch.capacity)
    return handles[0], batch


def maybe_publish(ctx, exchange, single) -> None:
    """Best-effort publication of a locally-built broadcast single:
    write-shard + commit under the exchange's broadcast tag. Failures
    are swallowed — the cache is an accelerator, never a correctness
    dependency; concurrent publishers are safe (the manifest PUT/rename
    is atomic and both blobs are bit-identical builds of the same
    inputs)."""
    info, _ = _cache_conf(ctx)
    if info is None:
        return
    tag = info.broadcast_tag(exchange)
    if tag is None:
        return
    from spark_rapids_tpu.parallel import transport as T
    try:
        sess = info.open_session(ctx, tag, 1, owner=None)
        sess.write_shard(0, single)
        sess.commit()
        ctx.cache[f"bcastcache-sess:{tag}"] = sess
        T.record("broadcastCachePublishes")
        T.record("broadcastCacheMisses")     # built locally = a miss
        _LOG.info("broadcast cache publish: %s", tag)
    except Exception as e:
        _LOG.warning("broadcast cache publish of %s failed (cache "
                     "skipped): %s: %s", tag, type(e).__name__, e)
