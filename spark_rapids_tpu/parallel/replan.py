"""Runtime adaptive re-planning (ISSUE 7 tentpole, part b).

The stats-only AQE-lite (autoBroadcastJoinThreshold over footer
estimates, post-shuffle partition coalescing) plans from ESTIMATES; this
module re-plans mid-query from EXACT materialized sizes, the way the
reference's GpuCustomShuffleReaderExec.scala:132 reader rebuilds the
remaining plan once a shuffle's map output statistics exist.

Flow (driven from the top-level device collect funnel, ops/base.py,
before stage prematerialization):

1. Walk the physical plan's device regions for shuffled hash joins whose
   both inputs are materialized exchanges (the stage-DAG boundaries of
   parallel/stages.py), bottom-up so inner joins decide first.
2. For each candidate, materialize ONLY the build-side exchange — its
   transport session records the exact per-partition byte sizes
   (`ShuffleSession.record_shard_bytes`, the size-observation hook).
3. When the observed build size fits ``autoBroadcastJoinThreshold``, the
   join DEMOTES to a broadcast hash join: a rewritten subtree whose
   build input is the already-materialized exchange (served as broadcast
   shards) and whose probe input is the probe exchange's CHILD — the
   probe side never shuffles at all, which is the win. The fusion pass
   re-runs over the rewritten subtree (idempotent where nothing new
   fuses), and the skipped probe exchange is flagged so stage
   prematerialization does not shuffle it anyway.
4. Decisions are per-query (keyed in ``ctx.cache``), so the cached
   physical plan is untouched, the host oracle path never sees them, and
   lineage recovery still maps a lost build shard to the ORIGINAL
   exchange's stage: a recompute after ``stage_invalidate`` re-observes
   the sizes and re-derives the same demotion deterministically.

Counters land in the query's ``Cost@query`` metrics entry
(``replanChecks`` / ``joinDemotions`` / ``replanObservedBytes`` /
``estimateErrorPct``) and in the process-global cost counters bench.py
reports.
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from spark_rapids_tpu import config as C

_LOG = logging.getLogger("spark_rapids_tpu.replan")


def _metrics(ctx):
    from spark_rapids_tpu.ops.base import query_metrics_entry
    return query_metrics_entry(ctx, "Cost")


def decision_key(join) -> str:
    return f"replan:{id(join):x}"


def _candidates(root) -> List[Tuple[object, bool]]:
    """(join, on_device) for every shuffled-hash-join over two
    materialized exchanges, bottom-up (inner joins first), restricted to
    device regions — host islands run the oracle engine verbatim."""
    from spark_rapids_tpu.ops.base import (DeviceToHostExec,
                                           HostToDeviceExec)
    from spark_rapids_tpu.ops.join import ShuffledHashJoinExec
    from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
    out: List[Tuple[object, bool]] = []

    def walk(op, device: bool):
        if isinstance(op, DeviceToHostExec):
            kid_dev = [True]
        elif isinstance(op, HostToDeviceExec):
            kid_dev = [False]
        else:
            kid_dev = [device] * len(op.children)
        for c, d in zip(op.children, kid_dev):
            walk(c, d)
        if device and type(op) is ShuffledHashJoinExec and \
                op.join_type != "full" and \
                all(isinstance(c, ShuffleExchangeExec)
                    for c in op.children):
            out.append((op, device))

    walk(root, True)
    return out


def plan_adaptive(ctx, root) -> None:
    """Decide demotions for this query. Idempotent per context: re-runs
    after a lineage-scoped stage recompute re-use cached decisions (and
    a recomputed build exchange re-derives the same one)."""
    from spark_rapids_tpu.plan import cost as COST
    if ctx.cache.get("engine") != "device":
        return
    if not bool(ctx.conf.get(C.AQE_REPLAN)):
        return
    threshold = int(ctx.conf.get(C.AUTO_BROADCAST_THRESHOLD))
    if threshold < 0:       # Spark semantics: -1 disables auto-broadcast
        return
    for join, _ in _candidates(root):
        key = decision_key(join)
        if key in ctx.cache:
            continue
        m = _metrics(ctx)
        m.add("replanChecks", 1)
        COST._record("replanChecks")
        build_right = join.join_type != "right"
        build_ex = join.children[1] if build_right else join.children[0]
        probe_ex = join.children[0] if build_right else join.children[1]
        observed = build_ex.observed_total_bytes(ctx)
        m.add("replanObservedBytes", observed)
        est = getattr(join, "est_build_bytes", None)
        if est is not None and observed > 0:
            m.add("estimateErrorPct",
                  abs(est - observed) * 100.0 / observed)
        if observed > threshold:
            ctx.cache[key] = None
            continue
        delegate = _demote(ctx, join, build_ex, probe_ex, build_right)
        ctx.cache[key] = delegate
        ctx.cache[f"replan-skip:{id(probe_ex):x}"] = True
        m.add("joinDemotions", 1)
        COST._record("joinDemotions")
        from spark_rapids_tpu import monitoring
        monitoring.instant(
            "join-demotion", "replan",
            args={"join": join.name, "observedBytes": observed,
                  "threshold": threshold})
        _LOG.warning(
            "runtime re-plan: demoting %s to broadcast (observed build "
            "side %d bytes <= threshold %d; probe shuffle skipped)",
            join.name, observed, threshold)


def _demote(ctx, join, build_ex, probe_ex, build_right: bool):
    """Rewritten subtree for one demotion: a BroadcastHashJoinExec whose
    build child is the ALREADY-MATERIALIZED exchange (its reduce buckets
    stream as broadcast shards, zero re-shuffling) and whose probe child
    is the probe exchange's unshuffled input. Keys/condition carry over —
    both sides' schemas are unchanged."""
    from spark_rapids_tpu.ops.join import BroadcastHashJoinExec
    probe_child = probe_ex.children[0]
    if build_right:
        left, right = probe_child, build_ex
    else:
        left, right = build_ex, probe_child
    delegate = BroadcastHashJoinExec(
        left, right, join.left_keys, join.right_keys, join.join_type,
        join.condition)
    # Re-run the fusion pass over the rewritten subtree (the ISSUE 7
    # contract): already-fused runs below are fixed points, so this only
    # fuses shapes the exchange removal newly exposed.
    if bool(ctx.conf.get(C.STAGE_FUSION_ENABLED)):
        from spark_rapids_tpu.plan.fusion import fuse_stages
        delegate, refused = fuse_stages(delegate, True)
        if refused:
            _metrics(ctx).add("replanRefusions", refused)
    return delegate


def demoted(ctx, join):
    """The delegate for ``join`` in this query, or None (no demotion /
    replan never ran / host engine)."""
    return ctx.cache.get(decision_key(join))
