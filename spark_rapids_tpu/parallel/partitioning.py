"""Partitioning strategies (ref: GpuHashPartitioning.scala,
GpuRangePartitioning.scala + GpuRangePartitioner.scala,
GpuRoundRobinPartitioning.scala, GpuSinglePartitioning.scala,
GpuPartitioning.scala:44-124).

Each strategy maps rows to partition ids on device; ``split_batch`` is the
``Table.contiguousSplit`` analog — it packs each destination's rows into its
own fixed-capacity batch (compact-by-mask per destination, so every piece
keeps a static shape for XLA).

Hash partitioning uses the bit-exact Spark murmur3 (exprs/hash.py) with
``pmod(hash, n)`` — TPU shuffle partitions line up with CPU Spark's, the
parity requirement SURVEY.md §7 calls out.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.exprs.hash import Murmur3Hash
from spark_rapids_tpu.ops import kernels
from spark_rapids_tpu.ops.sort import SortOrder


class Partitioning:
    """Maps each row to a partition id in [0, num_partitions)."""

    num_partitions: int

    @property
    def jittable(self) -> bool:
        """False when any key expression needs a host roundtrip."""
        return True

    def partition_ids(self, batch: DeviceBatch) -> jnp.ndarray:
        raise NotImplementedError

    def partition_ids_host(self, hb: HostBatch) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def partition_ids(self, batch):
        return jnp.zeros((batch.capacity,), jnp.int32)

    def partition_ids_host(self, hb):
        return np.zeros(hb.num_rows, np.int32)


class HashPartitioning(Partitioning):
    """pmod(murmur3(keys), n) — exactly Spark's HashPartitioning."""

    def __init__(self, keys: Sequence[Expression], num_partitions: int):
        self.keys = list(keys)
        self.num_partitions = num_partitions
        self._hash = Murmur3Hash(self.keys)

    @property
    def jittable(self) -> bool:
        return all(k.jittable for k in self.keys)

    def partition_ids(self, batch):
        h = as_device_column(self._hash.eval(batch), batch).data
        n = jnp.int32(self.num_partitions)
        return jnp.remainder(jnp.remainder(h, n) + n, n).astype(jnp.int32)

    def partition_ids_host(self, hb):
        h = as_host_column(self._hash.eval_host(hb), hb).data
        n = self.num_partitions
        return (((h.astype(np.int64) % n) + n) % n).astype(np.int32)


class RoundRobinPartitioning(Partitioning):
    """Position-based distribution (GpuRoundRobinPartitioning — the
    reference starts at a random partition per task; we start at 0 for
    determinism, which only shifts which partition gets which rows)."""

    def __init__(self, num_partitions: int, start: int = 0):
        self.num_partitions = num_partitions
        self.start = start

    def partition_ids(self, batch):
        return jnp.remainder(self.start +
                             jnp.arange(batch.capacity, dtype=jnp.int32),
                             self.num_partitions).astype(jnp.int32)

    def partition_ids_host(self, hb):
        return ((self.start + np.arange(hb.num_rows)) %
                self.num_partitions).astype(np.int32)


class RangePartitioning(Partitioning):
    """Range partitioning by sort orders against sampled bounds
    (GpuRangePartitioning.scala: CPU reservoir sample picks bounds, device
    does the upper-bound search). ``bounds`` is a HostBatch of the key
    columns with num_partitions-1 rows, ascending."""

    def __init__(self, orders: Sequence[SortOrder], num_partitions: int,
                 bounds: Optional[HostBatch] = None):
        self.orders = list(orders)
        self.num_partitions = num_partitions
        self.bounds = bounds

    @property
    def jittable(self) -> bool:
        return all(o.child.jittable for o in self.orders)

    @staticmethod
    def compute_bounds(sample: HostBatch, orders,
                       num_partitions: int) -> HostBatch:
        """Pick num_partitions-1 bounds from a host sample of the keys
        (the reservoir-sample half of GpuRangePartitioner.scala:33)."""
        from spark_rapids_tpu.ops.sort import sort_host_batch
        sorted_sample = sort_host_batch(sample, orders)
        n = sorted_sample.num_rows
        idxs = [min(n - 1, max(0, (i + 1) * n // num_partitions))
                for i in range(num_partitions - 1)] if n else []
        cols = []
        for c in sorted_sample.columns:
            cols.append(HostColumn(c.dtype, c.data[idxs],
                                   c.validity[idxs]))
        return HostBatch(sorted_sample.names, cols)

    def _key_words(self, batch_like, device: bool):
        """Orderable word arrays for the key exprs over a data batch."""
        words = []
        for o in self.orders:
            if device:
                col = as_device_column(o.child.eval(batch_like), batch_like)
            else:
                hc = as_host_column(o.child.eval_host(batch_like),
                                    batch_like)
                col = _host_as_device_like(hc)
            words.extend(kernels.sort_key_passes(col, o.ascending,
                                                 o.nulls_first))
        return words

    def _bound_words(self):
        """Orderable words of the bounds rows — the bounds batch holds the
        key columns positionally (k0, k1, ...), no exprs involved."""
        words = []
        for i, o in enumerate(self.orders):
            col = _host_as_device_like(self.bounds.columns[i])
            words.extend(kernels.sort_key_passes(col, o.ascending,
                                                 o.nulls_first))
        return words

    def partition_ids(self, batch):
        assert self.bounds is not None, "range bounds not computed"
        row_words = self._key_words(batch, device=True)
        bound_words = self._bound_words()
        nb = self.bounds.num_rows
        cap = batch.capacity
        pid = jnp.zeros((cap,), jnp.int32)
        for bi in range(nb):
            # row > bound  <=> lexicographic compare over word passes.
            gt = jnp.zeros((cap,), jnp.bool_)
            eq = jnp.ones((cap,), jnp.bool_)
            for rw, bw in zip(row_words, bound_words):
                b = bw[bi]
                gt = gt | (eq & (rw > b))
                eq = eq & (rw == b)
            # Spark RangePartitioner: keys equal to a bound stay in the
            # lower partition (bounds are inclusive upper bounds).
            pid = pid + gt.astype(jnp.int32)
        return jnp.minimum(pid, self.num_partitions - 1)

    def partition_ids_host(self, hb):
        assert self.bounds is not None
        row_words = [np.asarray(w) for w in self._key_words(hb, device=False)]
        bound_words = [np.asarray(w) for w in self._bound_words()]
        n = hb.num_rows
        pid = np.zeros(n, np.int32)
        for bi in range(self.bounds.num_rows):
            gt = np.zeros(n, np.bool_)
            eq = np.ones(n, np.bool_)
            for rw, bw in zip(row_words, bound_words):
                b = bw[bi]
                gt = gt | (eq & (rw > b))
                eq = eq & (rw == b)
            pid += gt.astype(np.int32)
        return np.minimum(pid, self.num_partitions - 1)


def _host_as_device_like(hc: HostColumn):
    """View a host column with jnp-compatible arrays for the shared kernels
    (numpy arrays duck-type fine through sort_key_passes)."""
    from spark_rapids_tpu.columnar.host import StringMatrixView
    if hc.dtype.is_string:
        v = StringMatrixView.of(hc)
        return DeviceColumn(hc.dtype, jnp.asarray(v.data),
                            jnp.asarray(v.validity), jnp.asarray(v.lengths))
    return DeviceColumn(hc.dtype, jnp.asarray(hc.data),
                        jnp.asarray(hc.validity))


# ---------------------------------------------------------------------------
# Splitting (Table.contiguousSplit analog)
# ---------------------------------------------------------------------------

def split_batch(batch: DeviceBatch, pids: jnp.ndarray,
                num_partitions: int) -> List[DeviceBatch]:
    """Pack each destination's rows into its own batch (stable order)."""
    out = []
    for p in range(num_partitions):
        keep = (pids == p) & batch.row_mask()
        out.append(batch.compact(keep))
    return out


def split_host_batch(hb: HostBatch, pids: np.ndarray,
                     num_partitions: int) -> List[HostBatch]:
    """ONE stable argsort + one gather per column, then zero-copy slices
    per destination — instead of a boolean-mask scan of the whole batch
    per partition (O(n) x num_partitions). The host engine is a
    first-class placement target now (plan/cost.py), so its shuffle
    split runs the same move-all-rows-once shape as the device split."""
    from spark_rapids_tpu.columnar.host import stable_code_argsort
    order = stable_code_argsort(np.asarray(pids, np.int64))
    counts = np.bincount(pids[order], minlength=num_partitions)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    # take() keeps dense string layouts dense — slicing .data here would
    # materialize object arrays and force every downstream string kernel
    # back through a strings_to_matrix re-encode.
    gathered = [c.take(order) for c in hb.columns]
    out = []
    for p in range(num_partitions):
        lo, hi = int(offsets[p]), int(offsets[p + 1])
        cols = []
        for g in gathered:
            if g.dtype.is_string and g._data is None:
                c = HostColumn(
                    g.dtype, None, g.validity[lo:hi],
                    str_matrix=g.str_matrix[lo:hi],
                    str_lengths=g.str_lengths[lo:hi])
            else:
                c = HostColumn(g.dtype, g.data[lo:hi],
                               g.validity[lo:hi])
            if g._key_codes is not None:
                # Key-code propagation through the shuffle: the reduce
                # side merges per-map-shard code dictionaries instead of
                # re-ranking every received row (columnar/host.py).
                c._key_codes = g._key_codes[lo:hi]
                c._key_uniq = g._key_uniq
            elif g.dtype.is_string:
                c._key_src = (g, slice(lo, hi), None)
            cols.append(c)
        out.append(HostBatch(hb.names, cols))
    return out
