"""Planner-integrated collective shuffle: ShuffleExchangeExec lowered onto
a jax.sharding.Mesh (VERDICT r1 item 4).

When ``spark.rapids.sql.mesh.enabled`` is on, the planner emits
``MeshExchangeExec`` for hash shuffles instead of the single-process
materialized exchange: child partitions become one uniform-shape shard per
mesh device, ONE jitted ``shard_map`` program runs the split +
``jax.lax.all_to_all`` + concat (the ICI collective replacing the
reference's UCX pull protocol — SURVEY.md §2.6 TPU mapping,
GpuShuffleExchangeExec.scala:69,145), and each output partition serves its
device's post-exchange shard to the normal per-partition operator stream
above. Operators (aggregate final stage, shuffled join) compose unchanged.

Single real chip degenerates to n=1; the 8-virtual-CPU-device mesh in
tests/conftest.py exercises the real collective path.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, string_repad)
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.parallel import mesh as M
from spark_rapids_tpu.shims import (shard_map, tree_flatten,
                                    tree_map, tree_unflatten)
from spark_rapids_tpu.parallel.partitioning import Partitioning


def mesh_for(ctx: ExecContext):
    """One mesh per query context (all visible devices)."""
    m = ctx.cache.get("mesh:singleton")
    if m is None:
        m = M.make_mesh()
        ctx.cache["mesh:singleton"] = m
    return m


def mesh_size() -> int:
    return len(jax.devices())


# Shards below this many rows skip the two-phase counts exchange: its
# blocking host pull (~70ms floor on a tunneled link) costs more than the
# worst-case padding it would avoid. Module-level so tests can lower it.
TWO_PHASE_MIN_SHARD_ROWS = 1 << 18


def _uniform_shards(batches_per_dev: List[List[DeviceBatch]],
                    schema: Schema) -> List[DeviceBatch]:
    """Coalesce each device's batches and pad all shards to one common
    capacity + per-column string width (shard_map needs uniform shapes)."""
    from spark_rapids_tpu.ops.sort import coalesce_to_single_batch
    from spark_rapids_tpu.columnar.rowmove import compact_batch
    shards = []
    for blist in batches_per_dev:
        if blist:
            single = coalesce_to_single_batch(blist)
            if single.sel is not None:
                # A lone filtered batch passes through coalesce with its
                # selection vector; shard_map shards are sel-less, so
                # materialize the live rows first.
                single = jax.jit(compact_batch)(single)
            shards.append(single)
        else:
            shards.append(None)
    caps = [s.capacity for s in shards if s is not None]
    cap = bucket_capacity(max(caps)) if caps else 8
    widths = []
    for ci, (_, t) in enumerate(schema):
        if t.is_string:
            ws = [s.columns[ci].string_width
                  for s in shards if s is not None]
            widths.append(max(ws) if ws else 8)
        else:
            widths.append(None)
    out = []
    for s in shards:
        if s is None:
            cols = tuple(
                DeviceColumn.full_null(t, cap, widths[ci] or 8)
                for ci, (_, t) in enumerate(schema))
            out.append(DeviceBatch(cols, jnp.asarray(0, jnp.int32)))
            continue
        cols = []
        for ci, c in enumerate(s.columns):
            if c.dtype.is_string and c.string_width != widths[ci]:
                c = string_repad(c, widths[ci])
            cols.append(c)
        s = DeviceBatch(tuple(cols), s.num_rows)
        if s.capacity != cap:
            idx = jnp.arange(cap, dtype=jnp.int32)
            s = s.gather(idx, s.num_rows)
        out.append(s)
    return out


def _addressable_parts(out, n: int):
    """Device i's post-exchange shard as an ordinary per-device batch.

    Extracts each leaf's per-device shard via ``addressable_shards``
    (device-local data, one tiny local slice per leaf) instead of ``x[i]``
    gathers on the global sharded array — a cross-device lazy gather that
    XLA re-dispatches whenever a consumer (including the range-bounds
    sampling pass re-executing this tree) touches it, and the trigger of
    the r4 SIGABRT inside apply_primitive (VERDICT r4 item 2).

    The downstream operator stream is single-process and mixes partitions
    freely (concat across buckets), so every shard is eagerly
    ``device_put`` onto the default device — an explicit transfer now, not
    a lazy gather later."""
    leaves, treedef = tree_flatten(out)
    per_dev = [[] for _ in range(n)]
    for leaf in leaves:
        by_row = {}
        for s in leaf.addressable_shards:
            row = s.index[0].start or 0 if s.index else 0
            by_row[row] = s.data
        for i in range(n):
            if i in by_row:
                per_dev[i].append(by_row[i][0])
            else:       # replicated / unsharded leaf: plain slice is local
                per_dev[i].append(leaf[i])
    # ONE batched transfer for every shard of every partition (device_put
    # takes pytrees) — not a put per leaf per device.
    per_dev = jax.device_put(per_dev, jax.devices()[0])
    return [tree_unflatten(treedef, ls) for ls in per_dev]


class MeshExchangeExec(Exec):
    """Hash shuffle over the device mesh as one collective program."""

    def __init__(self, child: Exec, partitioning: Partitioning):
        super().__init__(child)
        self.partitioning = partitioning

    def _mesh_key(self, mesh):
        """Cache key part identifying this exchange's collective shape:
        the partitioning structure + the mesh's device layout. Collective
        programs from the process-global kernel cache are shared across
        exec instances (every fresh query otherwise re-traces the
        shard_map programs)."""
        from spark_rapids_tpu.ops import kernel_cache as kc
        fp = getattr(self, "_part_fp", None)
        if fp is None:
            fp = self._part_fp = kc.fingerprint(self.partitioning)
        devs = tuple(int(d.id) for d in mesh.devices.flat)
        return (fp, tuple(mesh.axis_names), devs)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def num_partitions(self, ctx) -> int:
        return self.partitioning.num_partitions

    def _pids_step(self, mesh):
        """Per-shard LOGICAL partition ids, computed ONCE and fed to
        both the counts and data collectives (murmur/bound-compare over
        every row is not free twice). The collectives fold logical ids
        onto device ids themselves (``pid // fold``)."""
        part = self.partitioning

        def local(stacked):
            b = tree_map(lambda x: x[0], stacked)
            return part.partition_ids(b)[None]

        return jax.jit(shard_map(local, mesh, in_specs=(P(M.DATA_AXIS),),
                                 out_specs=P(M.DATA_AXIS)))

    def _build_step(self, mesh, n: int, fold: int, piece_capacity=None):
        def local(stacked, pids):
            b = tree_map(lambda x: x[0], stacked)
            out = M.all_to_all_exchange(b, pids[0] // fold, n,
                                        piece_capacity=piece_capacity)
            return tree_map(lambda x: x[None], out)

        return jax.jit(shard_map(
            local, mesh, in_specs=(P(M.DATA_AXIS), P(M.DATA_AXIS)),
            out_specs=P(M.DATA_AXIS)))

    def _counts_step(self, mesh, n: int, fold: int):
        def local(stacked, pids):
            b = tree_map(lambda x: x[0], stacked)
            return M.exchange_counts(b, pids[0] // fold, n)[None]

        return jax.jit(shard_map(
            local, mesh, in_specs=(P(M.DATA_AXIS), P(M.DATA_AXIS)),
            out_specs=P(M.DATA_AXIS)))

    def _fallback(self):
        """Single-process materialized exchange over the same child and
        partitioning — the demotion target when the mesh collective
        fails. Built lazily, once per exec instance, so its per-context
        materialization caches key stably across retries."""
        fb = getattr(self, "_fallback_exec", None)
        if fb is None:
            from spark_rapids_tpu.parallel.exchange import \
                ShuffleExchangeExec
            fb = self._fallback_exec = ShuffleExchangeExec(
                self.children[0], self.partitioning)
        return fb

    def _degrade(self, ctx, err) -> None:
        """Mesh degrade: the collective failed, so demote THIS QUERY's
        mesh exchanges to the single-process ShuffleExchangeExec path
        instead of killing the query. The flag is context-scoped — every
        other MeshExchangeExec in the plan skips its collective too (a
        sick interconnect rarely fails just one exchange)."""
        import logging
        from spark_rapids_tpu import faults
        logging.getLogger("spark_rapids_tpu").warning(
            "mesh collective failed in %s; degrading this query's "
            "exchanges to the single-process shuffle path: %s",
            self.name, err)
        faults.record("meshDegrades")
        ctx.metrics_for(self).add("meshDegrades", 1)
        ctx.cache["mesh.degraded"] = True

    def _materialize(self, ctx):
        """Run the collective and register each LOGICAL partition's
        post-exchange shard as a durable stage output through the mesh
        transport session (parallel/transport/mesh.py — spillable
        catalog handles). Returns None after a graceful degrade — the
        caller serves from the single-process fallback exchange
        instead.

        Partition count != mesh size no longer degrades: logical
        partitions FOLD onto devices (``device = pid // ceil(np/n)``,
        counter ``meshPartitionFolds``) and each device's received
        shard splits back into its logical partitions after the
        collective, so co-partitioned consumers never see mesh
        geometry. ``meshCollectiveSkipped`` now fires only for
        genuinely unsupported shapes (a non-jittable partitioning —
        nothing the planner emits today)."""
        key = f"meshx:{id(self):x}"
        if key in ctx.cache:
            return ctx.cache[key]
        if ctx.cache.get(f"meshx-skip:{id(self):x}"):
            return None         # unsupported shape already diagnosed
        m = ctx.metrics_for(self)
        mesh = mesh_for(ctx)
        n = mesh.devices.size
        np_parts = self.partitioning.num_partitions
        if np_parts < 1 or not getattr(self.partitioning, "jittable",
                                       False):
            import logging
            from spark_rapids_tpu import faults
            logging.getLogger("spark_rapids_tpu").warning(
                "mesh collective skipped in %s: partitioning %r is not "
                "collective-capable; serving this exchange from the "
                "single-process shuffle path", self.name,
                type(self.partitioning).__name__)
            faults.record("meshCollectiveSkipped")
            m.add("meshCollectiveSkipped", 1)
            ctx.cache[f"meshx-skip:{id(self):x}"] = True
            return None
        fold = -(-np_parts // n)        # ceil: k logical pids per device
        if fold > 1 or np_parts != n:
            from spark_rapids_tpu import faults
            faults.record("meshPartitionFolds")
            m.add("meshPartitionFolds", 1)
        # Deal child partitions onto devices round-robin.
        per_dev: List[List[DeviceBatch]] = [[] for _ in range(n)]
        child = self.children[0]
        for cp in range(child.num_partitions(ctx)):
            for batch in child.execute_device_recovering(ctx, cp):
                per_dev[cp % n].append(batch)
        from spark_rapids_tpu import config as C
        with timed(m, "shuffleTime"):
            try:
                from spark_rapids_tpu import faults
                faults.fault_point("mesh.exchange", owner=id(self))
                shards = _uniform_shards(per_dev, self.schema)
                stacked = M.shard_batches(mesh, shards)
                # Two-phase sizes-then-data (SURVEY §7 hard part 6):
                # exchange per-destination COUNTS first (a (n,n) int32
                # collective + one host pull), size the data collective's
                # static piece capacity to the observed max instead of
                # the worst case — the default padding is an n-fold wire
                # inflation at scale. n == 1 skips the phase: the
                # collective moves nothing, so the counts sync could
                # only cost.
                from spark_rapids_tpu.ops import kernel_cache as kc
                mkey = self._mesh_key(mesh)
                pids_fn = kc.lookup("mesh-pids", mkey,
                                    lambda: self._pids_step(mesh), m)
                pids = pids_fn(stacked)
                piece_cap = None
                if n > 1 and shards[0].capacity >= \
                        TWO_PHASE_MIN_SHARD_ROWS:
                    counts_fn = kc.lookup(
                        "mesh-counts", mkey + (fold,),
                        lambda: self._counts_step(mesh, n, fold), m)
                    counts = np.asarray(counts_fn(stacked, pids))
                    piece_cap = bucket_capacity(max(int(counts.max()), 1))
                    if piece_cap >= shards[0].capacity:
                        piece_cap = None  # padding wouldn't shrink
                step = kc.lookup(
                    "mesh-exchange", mkey + (fold, piece_cap),
                    lambda: self._build_step(mesh, n, fold,
                                             piece_capacity=piece_cap), m)
                out = step(stacked, pids)
                parts = _addressable_parts(out, n)
            except Exception as err:
                if not bool(ctx.conf.get(C.MESH_DEGRADE_ENABLED)):
                    raise
                self._degrade(ctx, err)
                return None
        # Durable stage outputs through the transport SPI: each logical
        # partition's shard registers with the buffer catalog (bounded
        # by the memory ladder; CRC-framed once spilled to disk)
        # instead of pinning raw HBM in ctx.cache.
        from spark_rapids_tpu.parallel import transport as T
        sess = T.get_transport("mesh").open(
            ctx.conf, f"meshx-{id(self):x}", np_parts, owner=id(self),
            catalog=ctx.catalog, metrics=T.metrics_entry(ctx))
        if fold == 1 and np_parts <= n:
            for p in range(np_parts):
                sess.write_shard(p, parts[p])
        else:
            # Unfold: split each device's received shard back into its
            # logical partitions (the pids recompute is one murmur pass
            # over the received rows — received shards are dense, so
            # this is row-proportional, not capacity-proportional).
            for d in range(n):
                lo = d * fold
                cnt = min(np_parts - lo, fold)
                if cnt <= 0:
                    continue
                shard = parts[d]
                shard_pids = self.partitioning.partition_ids(shard)
                live = shard.row_mask()
                for j in range(cnt):
                    keep = (shard_pids == lo + j) & live
                    sess.write_shard(lo + j, shard.compact(keep))
        sess.commit()
        ctx.cache[key] = sess
        return sess

    def execute_device(self, ctx, partition):
        sess = None
        if not ctx.cache.get("mesh.degraded"):
            sess = self._materialize(ctx)
        if sess is None:          # degraded (now or by a prior exchange)
            yield from self._fallback().execute_device(ctx, partition)
            return
        from spark_rapids_tpu.memory.stores import \
            PRIORITY_SHUFFLE_OUTPUT
        for h in sess.fetch_shards(partition):
            batch = h.get()
            try:
                yield batch
            finally:
                h.release(PRIORITY_SHUFFLE_OUTPUT)

    # -- lineage recovery ----------------------------------------------------
    def stage_invalidate(self, ctx) -> None:
        """Drop this exchange's durable shards (stage boundary contract,
        parallel/stages.py)."""
        sess = ctx.cache.pop(f"meshx:{id(self):x}", None)
        ctx.cache.pop(f"meshx-host:{id(self):x}", None)
        ctx.cache.pop(f"meshx-skip:{id(self):x}", None)
        if sess is not None:
            sess.invalidate()
        fb = getattr(self, "_fallback_exec", None)
        if fb is not None:
            fb.stage_invalidate(ctx)

    def execute_host(self, ctx, partition):
        # Host engine has no mesh; fall back to the materialized exchange
        # semantics (same results, used only by the oracle).
        from spark_rapids_tpu.parallel.partitioning import split_host_batch
        key = f"meshx-host:{id(self):x}"
        if key not in ctx.cache:
            n = self.partitioning.num_partitions
            buckets = [[] for _ in range(n)]
            child = self.children[0]
            for cp in range(child.num_partitions(ctx)):
                for hb in child.execute_host(ctx, cp):
                    pids = self.partitioning.partition_ids_host(hb)
                    for p, piece in enumerate(
                            split_host_batch(hb, pids, n)):
                        buckets[p].append(piece)
            ctx.cache[key] = buckets
        yield from iter(ctx.cache[key][partition])
