"""Shuffle & broadcast exchanges (ref: GpuShuffleExchangeExec.scala:69,145,
GpuBroadcastExchangeExec.scala:237, ShuffledBatchRDD.scala).

The exchange materializes the child once per query context (the role
Spark's shuffle files / the reference's RapidsCachingWriter device-store
play — see RapidsShuffleInternalManager write path, SURVEY.md §3.4),
bucketing every batch by partition id. Reduce tasks then stream their
bucket. WHERE the buckets live is the shuffle transport SPI's business
(parallel/transport/, ISSUE 6): ``inprocess`` keeps them as spillable
catalog handles (single process), ``hostfile`` spools CRC-framed shard
blobs to a shared directory so independent worker processes can fetch
each other's map output, and the multi-chip path replaces this
materialization entirely with an ICI all-to-all collective
(parallel/mesh_exchange.py) — a planned collective exchange instead of a
pull protocol, per SURVEY.md §2.6's TPU mapping note.

A sampled range exchange computes bounds from a host sample first, like
GpuRangePartitioner's reservoir sample.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import DeviceBatch, bucket_capacity, \
    concat_batches
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.parallel.partitioning import (
    Partitioning, RangePartitioning, split_batch, split_host_batch)


def _slice_rows(batch: DeviceBatch, start, size: int,
                num_rows) -> DeviceBatch:
    """Rows [start, start+size) of a dense batch as a new batch with
    ``num_rows`` live rows (traced start/num_rows; static size)."""
    from spark_rapids_tpu.columnar.batch import DeviceColumn
    cols = []
    for c in batch.columns:
        data = jax.lax.dynamic_slice_in_dim(c.data, start, size, axis=0)
        validity = jax.lax.dynamic_slice_in_dim(c.validity, start, size,
                                                axis=0)
        if c.dtype.is_string:
            lengths = jax.lax.dynamic_slice_in_dim(c.lengths, start, size,
                                                   axis=0)
            cols.append(DeviceColumn(c.dtype, data, validity, lengths))
        else:
            cols.append(DeviceColumn(c.dtype, data, validity))
    return DeviceBatch(tuple(cols), jnp.asarray(num_rows, jnp.int32))


class ShuffleExchangeExec(Exec):
    """Repartition the child by a Partitioning strategy.

    ``allow_coalesce`` opts this exchange into AQE-lite partition
    coalescing (GpuCustomShuffleReaderExec.scala:132 analog): once the
    map side materializes, the EXACT per-bucket row counts are known, and
    undersized adjacent reduce partitions merge up to the target. The
    planner enables it where partition identity is not load-bearing
    (aggregate/window/sort exchanges) and keeps it off for co-partitioned
    join inputs, whose two sides must stay aligned bucket-for-bucket."""

    def __init__(self, child: Exec, partitioning: Partitioning,
                 allow_coalesce: bool = False):
        super().__init__(child)
        self.partitioning = partitioning
        self.allow_coalesce = allow_coalesce

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _groups(self, ctx) -> Optional[List[List[int]]]:
        """Coalesced bucket groups (device engine only), or None."""
        from spark_rapids_tpu import config as C
        n = self.partitioning.num_partitions
        if not self.allow_coalesce or n <= 1 or \
                ctx.cache.get("engine") != "device" or \
                not bool(ctx.conf.get(C.AQE_COALESCE_PARTITIONS)):
            return None
        gkey = f"shuffle-groups:{id(self):x}"
        groups = ctx.cache.get(gkey)
        if groups is None:
            sess = self._materialize_device(ctx)
            sizes = ctx.cache.get(self._cache_key(True) + ":rows",
                                  [0] * n)
            target = int(ctx.conf.get(C.AQE_COALESCE_TARGET_ROWS))
            # Byte-aware merging from the OBSERVED shard bytes the
            # transport session recorded at materialization: partitions
            # merge while BOTH the row and the byte target hold, so a
            # few fat skewed buckets never collapse into one oversized
            # reduce partition just because their row counts are low.
            tbytes = int(ctx.conf.get(C.AQE_COALESCE_TARGET_BYTES))
            groups = []
            cur: List[int] = []
            cur_rows = 0
            cur_bytes = 0
            for b in range(n):
                b_bytes = sess.observed_bytes(b)
                if cur and (cur_rows + sizes[b] > target or
                            cur_bytes + b_bytes > tbytes):
                    groups.append(cur)
                    cur, cur_rows, cur_bytes = [], 0, 0
                cur.append(b)
                cur_rows += sizes[b]
                cur_bytes += b_bytes
            if cur:
                groups.append(cur)
            m = ctx.metrics_for(self)
            m.add("coalescedPartitions", n - len(groups))
            ctx.cache[gkey] = groups
        return groups

    def num_partitions(self, ctx) -> int:
        groups = self._groups(ctx)
        if groups is not None:
            return len(groups)
        return self.partitioning.num_partitions

    # -- materialization (the "map side") ------------------------------------
    def _cache_key(self, device: bool) -> str:
        return f"shuffle:{id(self):x}:{'dev' if device else 'host'}"

    def _ensure_bounds(self, ctx, device: bool):
        """Range partitioning needs bounds from a sample of the keys."""
        p = self.partitioning
        if not isinstance(p, RangePartitioning) or p.bounds is not None:
            return
        # Sample: pull up to 64 rows per child partition on the host engine
        # (CPU-side sampling, like the reference).
        from spark_rapids_tpu.columnar.batch import sample_rows
        from spark_rapids_tpu.columnar.host import device_to_host
        samples: List[HostBatch] = []
        for cp in range(self.children[0].num_partitions(ctx)):
            it = (self.children[0].execute_device(ctx, cp) if device
                  else self.children[0].execute_host(ctx, cp))
            for b in it:
                if device:
                    # Sample on device; download 64 rows, not the batch.
                    hb = device_to_host(sample_rows(b, 64))
                else:
                    hb = b
                keycols = []
                from spark_rapids_tpu.exprs.base import as_host_column
                for o in p.orders:
                    keycols.append(as_host_column(o.child.eval_host(hb), hb))
                n = min(64, hb.num_rows)
                idx = np.linspace(0, max(hb.num_rows - 1, 0), n,
                                  dtype=np.int64) if n else \
                    np.zeros(0, np.int64)
                cols = [HostColumn(c.dtype, c.data[idx], c.validity[idx])
                        for c in keycols]
                samples.append(HostBatch(
                    tuple(f"k{i}" for i in range(len(cols))), cols))
                break   # one batch per partition is enough for bounds
        if not samples:
            p.bounds = HostBatch((), [])
            return
        merged_cols = []
        for ci in range(samples[0].num_columns):
            data = np.concatenate([s.columns[ci].data for s in samples])
            val = np.concatenate([s.columns[ci].validity for s in samples])
            merged_cols.append(HostColumn(samples[0].columns[ci].dtype,
                                          data, val))
        merged = HostBatch(samples[0].names, merged_cols)
        # Bounds are picked over the key columns themselves, so the sort
        # orders must reference them by ordinal.
        from spark_rapids_tpu.exprs.base import BoundReference
        from spark_rapids_tpu.ops.sort import SortOrder
        bound_orders = [
            SortOrder(BoundReference(i, o.child.data_type()),
                      o.ascending, o.nulls_first)
            for i, o in enumerate(p.orders)]
        # The bounds batch holds the key columns positionally; see
        # RangePartitioning._bound_words.
        p.bounds = RangePartitioning.compute_bounds(
            merged, bound_orders, p.num_partitions)

    def _partitioning_fp(self):
        """Structural cache key for this exchange's partitioning. Range
        partitionings fold their sampled bounds in — bounds are DATA, so
        two queries share a split kernel only when their bounds match."""
        from spark_rapids_tpu.ops import kernel_cache as kc
        fp = getattr(self, "_part_fp", None)
        if fp is None:
            fp = self._part_fp = kc.fingerprint(self.partitioning)
        return fp

    def _pids_counts_fn(self, metrics=None):
        """Jitted (pids, per-partition live counts) for one child batch,
        from the process-global kernel cache."""
        partitioning = self.partitioning
        n = partitioning.num_partitions

        def fn(b: DeviceBatch):
            pids = partitioning.partition_ids(b)
            live = b.row_mask()
            key = jnp.where(live, pids, n)
            counts = jax.ops.segment_sum(
                jnp.ones((b.capacity,), jnp.int32), key,
                num_segments=n + 1)[:n]
            return pids, counts
        if not partitioning.jittable:
            return fn
        from spark_rapids_tpu.ops import kernel_cache as kc
        return kc.lookup("exchange-pids", (self._partitioning_fp(),),
                         lambda: jax.jit(fn), metrics)

    def _split_fn(self, piece_cap: int, metrics=None):
        """Jitted split: ONE pid-stable sort + ONE packed gather, then a
        dynamic slice per piece — replaces the per-partition compaction
        storm (contiguousSplit done the TPU way: gather/scatter cost on
        this chip scales with row-operations, so moving all columns once
        beats moving each partition separately ~n-fold)."""
        partitioning = self.partitioning
        n = partitioning.num_partitions

        def fn(b: DeviceBatch, pids, offsets, counts):
            from spark_rapids_tpu.columnar.rowmove import gather_rows
            live = b.row_mask()
            skey = jnp.where(live, pids, n)
            perm = jnp.argsort(skey, stable=True)
            # Pad the gather so a slice at offset near the end never
            # clamps (dynamic_slice adjusts out-of-range starts).
            idx = jnp.concatenate(
                [perm.astype(jnp.int32),
                 jnp.zeros((piece_cap,), jnp.int32)])
            sorted_b = gather_rows(b, idx, b.live_count())
            pieces = []
            for p in range(n):
                pieces.append(_slice_rows(sorted_b, offsets[p],
                                          piece_cap, counts[p]))
            return pieces
        if not partitioning.jittable:
            return fn
        from spark_rapids_tpu.ops import kernel_cache as kc
        return kc.lookup("exchange-split",
                         (self._partitioning_fp(), piece_cap),
                         lambda: jax.jit(fn), metrics)

    def _open_session(self, ctx):
        """Open this exchange's transport session (parallel/transport/):
        the SPI decides where map-side shards live — catalog handles for
        ``inprocess``, spool files for ``hostfile``. The session is the
        durable stage output; it parks in ctx.cache so re-executions
        serve the committed materialization and ctx.close tears it
        down."""
        import os

        from spark_rapids_tpu.parallel import transport as T
        info = ctx.cache.get("cluster")
        if info is not None:
            # Cluster mode (parallel/cluster/): a dispatchable stage's
            # output lives at its cross-process tag on the query spool,
            # shared by every process of the query. Untagged exchanges
            # (session_for -> None) open their configured transport
            # exactly as before.
            sess = info.session_for(ctx, self)
            if sess is not None:
                return sess
        transport = T.materialization_transport(ctx.conf)
        return transport.open(
            ctx.conf, f"x{os.getpid():x}-{id(self):x}",
            self.partitioning.num_partitions, owner=id(self),
            catalog=ctx.catalog, metrics=T.metrics_entry(ctx))

    def _materialize_device(self, ctx):
        key = self._cache_key(True)
        if key in ctx.cache:
            return ctx.cache[key]
        from spark_rapids_tpu import monitoring
        info = ctx.cache.get("cluster")
        if info is not None and info.is_remote(self):
            # Another process of this query produced (or is assigned)
            # this stage: adopt its committed spool instead of running
            # the map side. The dispatch barrier (QueryRun.run) and the
            # coordinator's deps-done gating guarantee the manifest is
            # committed before any consumer lands here.
            with monitoring.span("exchange-adopt", "shuffle",
                                 args={"op": self.name,
                                       "stage": info.sid_of(self)}):
                sess = info.session_for(ctx, self)
                rows = type(info).adopt_manifest(
                    sess, self.partitioning.num_partitions)
                ctx.cache[key] = sess
                ctx.cache[key + ":rows"] = rows
                return sess
        with monitoring.span("exchange-materialize", "shuffle",
                             args={"op": self.name,
                                   "partitions":
                                   self.partitioning.num_partitions}):
            return self._materialize_device_traced(ctx, key)

    def _materialize_device_traced(self, ctx, key):
        self._ensure_bounds(ctx, device=True)
        n = self.partitioning.num_partitions
        sess = self._open_session(ctx)
        bucket_rows = [0] * n           # exact counts (AQE coalescing)
        from spark_rapids_tpu.columnar.batch import shrink_to_capacity
        pids_fn = self._pids_counts_fn(metrics=ctx.metrics_for(self))
        # Two-phase sizes-then-data (SURVEY §7): dispatch per-batch
        # partition-id counts, pull the whole window's counts in ONE
        # batched device_get (a sync is a full network round trip on a
        # tunneled chip), then split each batch with host-known piece
        # sizes. The window is bounded so pre-split batches never
        # accumulate unboundedly in un-spillable HBM.
        _WINDOW = 32

        def flush_window(window: List[DeviceBatch]):
            from spark_rapids_tpu import faults
            faults.fault_point("exchange.flush", owner=id(self))
            if n == 1:
                # Single destination: no pids, no sort, no slices — shrink
                # each batch to its live bucket (using hints when known)
                # and bucket it directly.
                from spark_rapids_tpu.columnar.batch import shrink_all
                pieces, counts1 = shrink_all(window)
                for piece, cnt in zip(pieces, counts1):
                    if cnt == 0:
                        continue
                    bucket_rows[0] += cnt
                    piece.rows_hint = cnt
                    sess.write_shard(0, piece)
                return
            metas = [(b,) + tuple(pids_fn(b)) for b in window]
            pulled = jax.device_get([m[2] for m in metas])
            for (batch, pids, _), counts in zip(metas, pulled):
                counts = [int(c) for c in counts]
                total = sum(counts)
                if total == 0:
                    continue
                # Mostly-dead batches (selective filters, tiny partial
                # aggregates) shrink to their live bucket first so the
                # split's gather moves live rows, not capacity.
                small = bucket_capacity(max(total, 1))
                if small < batch.capacity:
                    batch = shrink_to_capacity(batch, small)
                    pids, _ = pids_fn(batch)
                piece_cap = bucket_capacity(max(max(counts), 1))
                offsets = np.concatenate(
                    [[0], np.cumsum(counts[:-1])]).astype(np.int32)
                pieces = self._split_fn(
                    piece_cap, metrics=ctx.metrics_for(self))(
                    batch, pids, jnp.asarray(offsets),
                    jnp.asarray(counts, jnp.int32))
                for p, piece in enumerate(pieces):
                    if counts[p] == 0:
                        continue
                    piece.rows_hint = counts[p]
                    bucket_rows[p] += counts[p]
                    # Shuffle output is durable (RapidsCachingWriter
                    # inserts into the device store; shuffle spills FIRST
                    # per SpillPriorities) — the transport session holds
                    # a handle (spillable catalog entry or spool file),
                    # not a pinned device batch.
                    sess.write_shard(p, piece)

        # The window is bounded by BYTES as well as count: pre-split
        # batches are pinned un-spillable HBM, so a window must never
        # hold more than a fraction of the device budget (out-of-core
        # sorts/aggregations stream through here at multiples of HBM).
        max_window_bytes = max(ctx.catalog.device_budget // 4, 1 << 20)
        window: List[DeviceBatch] = []
        window_bytes = 0
        # Map-side partition loop through the pipelined executor: the
        # child's host half (scan decode + wire encode) runs
        # prefetchPartitions ahead on host threads while THIS (single,
        # ordered) consumer uploads and splits — the overlap that makes
        # scans below an exchange pipeline (parallel/pipeline.py). The
        # serial pipeline is a no-op passthrough, streaming exactly as
        # before.
        from spark_rapids_tpu.parallel import pipeline as PL
        nchild = self.children[0].num_partitions(ctx)
        pipe = PL.open_pipeline(ctx, self.children[0], nchild)
        try:
            for cp in range(nchild):
                # Child pull through the recovery wrapper: an
                # OOM-exhausted child subtree degrades to the host engine
                # per operator instead of failing the exchange.
                for b in pipe.consume(
                        cp, lambda cp=cp:
                        self.children[0].execute_device_recovering(
                            ctx, cp)):
                    window.append(b)
                    window_bytes += b.device_size_bytes()
                    if len(window) >= _WINDOW or \
                            window_bytes >= max_window_bytes:
                        flush_window(window)
                        window = []
                        window_bytes = 0
            if window:
                flush_window(window)
        except BaseException:
            # Partial materialization must not leak catalog entries or
            # spool files: the planner's retry ladder (stage recompute /
            # transient retry on the same context) re-runs this
            # materialization from scratch, so whatever was written so
            # far is garbage.
            sess.abort()
            raise
        finally:
            pipe.close()
        sess.commit()
        ctx.cache[key] = sess
        ctx.cache[key + ":rows"] = bucket_rows
        return sess

    def _materialize_host(self, ctx) -> List[List[HostBatch]]:
        key = self._cache_key(False)
        if key in ctx.cache:
            return ctx.cache[key]
        self._ensure_bounds(ctx, device=False)
        n = self.partitioning.num_partitions
        buckets: List[List[HostBatch]] = [[] for _ in range(n)]
        for cp in range(self.children[0].num_partitions(ctx)):
            for hb in self.children[0].execute_host(ctx, cp):
                pids = self.partitioning.partition_ids_host(hb)
                for p, piece in enumerate(split_host_batch(hb, pids, n)):
                    buckets[p].append(piece)
        ctx.cache[key] = buckets
        return buckets

    # -- serving (the "reduce side") -----------------------------------------
    def execute_device(self, ctx, partition):
        # Buckets stay registered (not freed) until ctx.close(): a plan can
        # legitimately re-execute a partition (range-bounds sampling,
        # broadcast probe re-runs). Consumed buckets carry the lowest spill
        # priority, so they are the first evicted under pressure.
        #
        # Post-shuffle COALESCE (GpuCoalesceBatches after an exchange,
        # GpuCoalesceBatches.scala:643): a reduce partition receives one
        # piece per map batch — typically many small batches. Serving them
        # individually makes every downstream per-batch host sync (agg
        # shrink, join size read) pay a device round trip PER PIECE; concat
        # groups of pieces up to batchSizeRows into one batch first. The
        # grouping keys off host-known static capacities — zero syncs.
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.columnar.batch import jit_concat_batches
        from spark_rapids_tpu.memory.stores import PRIORITY_SHUFFLE_OUTPUT
        sess = self._materialize_device(ctx)
        # Serve toward the (possibly OOM-degraded) batch target: after a
        # shrink escalation, reduce-side concats re-dispatch smaller.
        from spark_rapids_tpu.memory.oom import effective_batch_target
        target = effective_batch_target(int(ctx.conf.get(C.BATCH_SIZE_ROWS)))
        group: List = []
        group_cap = 0

        def flush(sbs):
            """Returns (batch_to_yield, handles_to_release_after_consume).
            A concat produces a NEW batch, so the source handles release
            immediately (jax keeps their buffers alive for the in-flight
            concat); a passed-through single batch IS the catalog-resident
            batch and must stay ACTIVE until the consumer is done with it,
            or it becomes the top spill victim mid-use."""
            if len(sbs) == 1:
                return sbs[0].get(), sbs
            batches = [sb.get() for sb in sbs]
            cap = bucket_capacity(sum(b.capacity for b in batches))
            out = jit_concat_batches(batches, cap)
            # Pieces carry exact live counts from the split's sizes pull;
            # their sum lets the consumer (final aggregate, download) skip
            # its own device sync entirely.
            hints = [b.rows_hint for b in batches]
            if all(h is not None for h in hints):
                out.rows_hint = sum(hints)
            for sb in sbs:
                sb.release(PRIORITY_SHUFFLE_OUTPUT)
            return out, []

        def serve(sbs):
            from spark_rapids_tpu import faults, monitoring
            from spark_rapids_tpu.columnar.wire import WireCorruptionError
            faults.fault_point("exchange.serve", owner=id(self))
            try:
                with monitoring.span("exchange-serve", "shuffle",
                                     args={"partition": partition,
                                           "shards": len(sbs)}):
                    out, pending = flush(sbs)
            except WireCorruptionError as err:
                # A durable stage output failed its CRC even after the
                # re-read: the data at rest is gone. Tag the loss with
                # this exchange so lineage recovery recomputes just this
                # stage instead of failing the query.
                err.fault_owner = id(self)
                raise
            try:
                yield out
            finally:
                # Runs when the consumer resumes (or abandons) the stream,
                # so the served batch is never evictable while in use.
                for sb in pending:
                    sb.release(PRIORITY_SHUFFLE_OUTPUT)

        from spark_rapids_tpu import monitoring
        groups = self._groups(ctx)
        mine = groups[partition] if groups is not None else [partition]
        try:
            for b in mine:
              with monitoring.span("fetch-shards", "shuffle",
                                   level=monitoring.LEVEL_KERNEL,
                                   args={"bucket": b}):
                  fetched = sess.fetch_shards(b)
              for sb in fetched:
                if group and group_cap + sb.capacity > target:
                    yield from serve(group)
                    group, group_cap = [], 0
                group.append(sb)
                group_cap += sb.capacity
            if group:
                yield from serve(group)
                group = []
        finally:
            # Early generator close before serve() ran: release anything
            # still grouped so no batch stays pinned ACTIVE.
            for sb in group:
                sb.release(PRIORITY_SHUFFLE_OUTPUT)

    def execute_host(self, ctx, partition):
        buckets = self._materialize_host(ctx)
        yield from iter(buckets[partition])

    # -- runtime adaptive re-planning ----------------------------------------
    def observed_total_bytes(self, ctx) -> int:
        """Materialize (idempotent) and return the EXACT total bytes the
        transport session observed across all map shards — the number
        runtime re-planning (parallel/replan.py) demotes joins on."""
        sess = self._materialize_device(ctx)
        return sess.observed_bytes()

    # -- pipelined execution -------------------------------------------------
    def stage_prematerialize(self, ctx) -> None:
        """Materialize this stage's durable output now (idempotent vs
        the context cache) — the hook parallel/pipeline.py uses to run
        independent sibling stages concurrently. A runtime re-plan that
        demoted this exchange's join to a broadcast skips the probe-side
        materialization entirely (parallel/replan.py flags it): shuffling
        a side the demoted join will stream unshuffled is pure waste."""
        if ctx.cache.get(f"replan-skip:{id(self):x}"):
            return
        if ctx.cache.get("engine") == "device":
            self._materialize_device(ctx)

    # -- lineage recovery ----------------------------------------------------
    def stage_invalidate(self, ctx) -> None:
        """Drop this exchange's durable stage output (parallel/stages.py
        boundary contract): the transport session releases every shard
        it holds — catalog registrations, spool files — and the next
        execution recomputes this stage from its parents' still-cached
        outputs. Applies identically to a lost REMOTE shard: the
        hostfile fetch raises owner-tagged, the planner lands here, and
        the recompute rewrites the spool."""
        dev_key = self._cache_key(True)
        sess = ctx.cache.pop(dev_key, None)
        ctx.cache.pop(dev_key + ":rows", None)
        ctx.cache.pop(self._cache_key(False), None)
        ctx.cache.pop(f"shuffle-groups:{id(self):x}", None)
        if sess is not None:
            sess.invalidate()


class BroadcastExchangeExec(Exec):
    """Collect the whole child into ONE batch replicated to every consumer
    (GpuBroadcastExchangeExec: collect-to-driver + re-upload becomes, on a
    pod, a one-time all-gather; single-host it is a concat + cache)."""

    def __init__(self, child: Exec):
        super().__init__(child)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def num_partitions(self, ctx) -> int:
        return 1

    def _cache_key(self, device: bool) -> str:
        return f"broadcast:{id(self):x}:{'dev' if device else 'host'}"

    def collect_single_device(self, ctx) -> DeviceBatch:
        # The merged single is a durable stage output: registered with
        # the buffer catalog (spillable under the memory ladder, CRC
        # framed once it reaches disk) instead of pinned raw in
        # ctx.cache, and re-acquired from whatever tier it sits on.
        from spark_rapids_tpu.memory.stores import (PRIORITY_BROADCAST,
                                                    SpillableBatch)
        key = self._cache_key(True)
        handle = ctx.cache.get(key)
        if handle is not None:
            batch = handle.get()
            handle.release(PRIORITY_BROADCAST)
            return batch
        # Cluster broadcast artifact cache (parallel/broadcast_cache.py):
        # another process of this query may have already built and
        # published this single — adopt it instead of re-collecting the
        # child. The fetched handle satisfies the same get/release
        # protocol as the SpillableBatch below. No-op outside cluster
        # mode.
        from spark_rapids_tpu.parallel import broadcast_cache as BC
        hit = BC.maybe_fetch(ctx, self)
        if hit is not None:
            ctx.cache[key] = hit[0]
            return hit[1]
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.parallel import pipeline as PL
        nchild = self.children[0].num_partitions(ctx)
        pipe = PL.open_pipeline(ctx, self.children[0], nchild)
        batches = []
        try:
            with monitoring.span("broadcast-collect", "shuffle",
                                 args={"partitions": nchild}):
                for cp in range(nchild):
                    batches.extend(pipe.consume(
                        cp, lambda cp=cp:
                        self.children[0].execute_device_recovering(ctx,
                                                                   cp)))
        finally:
            pipe.close()
        if not batches:
            raise ValueError("broadcast of empty child needs a schema batch")
        # One batched sizes pull, then shrink members to live scale: the
        # broadcast build side's capacity bounds the build-side sort and
        # (on the slow path) probe expansion. SMALL batches skip the pull
        # entirely — a dimension table's shrink can't repay a ~100ms
        # round trip, and the join kernels handle selection vectors.
        from spark_rapids_tpu.columnar.batch import (MIN_SHRINK_BYTES,
                                                      shrink_all)
        if any(b.device_size_bytes() >= MIN_SHRINK_BYTES
               for b in batches):
            batches, _ = shrink_all(batches)
        total = sum(b.capacity for b in batches)
        single = batches[0] if len(batches) == 1 else \
            concat_batches(batches, bucket_capacity(total))
        ctx.cache[key] = SpillableBatch(ctx.catalog, single,
                                        PRIORITY_BROADCAST)
        # Publish the freshly-built single for the query's OTHER
        # processes (best-effort; no-op outside cluster mode).
        BC.maybe_publish(ctx, self, single)
        return single

    def collect_single_host(self, ctx) -> HostBatch:
        key = self._cache_key(False)
        if key in ctx.cache:
            return ctx.cache[key]
        hbs = []
        for cp in range(self.children[0].num_partitions(ctx)):
            hbs.extend(self.children[0].execute_host(ctx, cp))
        assert hbs, "broadcast of empty child"
        from spark_rapids_tpu.columnar.host import concat_host_batches
        merged = concat_host_batches(hbs)
        ctx.cache[key] = merged
        # Host path while a device copy exists = the host-fallback rung
        # degraded an operator subtree over this broadcast. The degraded
        # consumer reads the host copy; keeping the device single too
        # would pin BOTH for the query's lifetime, so free the device
        # side (a later device consumer rebuilds it).
        dev = ctx.cache.pop(self._cache_key(True), None)
        if dev is not None:
            dev.close()
        return merged

    def stage_prematerialize(self, ctx) -> None:
        """Build the broadcast single now (idempotent) so sibling stages
        can materialize concurrently (parallel/pipeline.py)."""
        if ctx.cache.get("engine") == "device":
            self.collect_single_device(ctx)

    def stage_invalidate(self, ctx) -> None:
        """Drop the broadcast's durable output (stage boundary contract,
        parallel/stages.py)."""
        dev = ctx.cache.pop(self._cache_key(True), None)
        ctx.cache.pop(self._cache_key(False), None)
        if dev is not None:
            dev.close()

    def execute_device(self, ctx, partition):
        yield self.collect_single_device(ctx)

    def execute_host(self, ctx, partition):
        yield self.collect_single_host(ctx)
