"""Parallelism & distribution (SURVEY.md §2.5/§2.6): partitioning
strategies, shuffle/broadcast exchanges, device-mesh collectives, and
the stage-graph lineage recovery layer (parallel/stages.py)."""

from spark_rapids_tpu.parallel.partitioning import (   # noqa: F401
    HashPartitioning, Partitioning, RangePartitioning,
    RoundRobinPartitioning, SinglePartitioning, split_batch)
from spark_rapids_tpu.parallel.exchange import (       # noqa: F401
    BroadcastExchangeExec, ShuffleExchangeExec)
from spark_rapids_tpu.parallel.stages import (         # noqa: F401
    Stage, StageGraph, build_stage_graph)
