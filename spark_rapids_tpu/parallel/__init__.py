"""Parallelism & distribution (SURVEY.md §2.5/§2.6): partitioning
strategies, shuffle/broadcast exchanges, device-mesh collectives."""

from spark_rapids_tpu.parallel.partitioning import (   # noqa: F401
    HashPartitioning, Partitioning, RangePartitioning,
    RoundRobinPartitioning, SinglePartitioning, split_batch)
from spark_rapids_tpu.parallel.exchange import (       # noqa: F401
    BroadcastExchangeExec, ShuffleExchangeExec)
