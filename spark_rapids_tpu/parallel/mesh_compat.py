"""Back-compat re-export: the shard_map shim moved into the shims SPI
(spark_rapids_tpu.shims, the SparkShims.scala:61 analog)."""

from spark_rapids_tpu.shims import shard_map  # noqa: F401
