"""shard_map import shim across jax versions."""

from __future__ import annotations

import jax


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-tolerant shard_map: newer jax exposes jax.shard_map; older
    versions use jax.experimental.shard_map.shard_map with check_rep."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    try:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)
    except TypeError:
        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
