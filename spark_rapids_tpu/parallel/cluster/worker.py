"""Cluster worker process: executes assigned stages of a query's DAG.

Run as a standalone process (scripts/cluster.py launches N of them):

    python -m spark_rapids_tpu.parallel.cluster.worker \
        --coordinator 127.0.0.1:40123 --worker-id w0

Lifecycle: register with the coordinator's rendezvous (``CREG``, with
the hardened bounded-retry connect), heartbeat from a daemon thread
(``CBEAT``), and pull stage tasks in the main loop (``CPOLL``). For
each task the worker unpickles the query's physical plan ONCE per
query (the deterministic DFS stage numbering of
parallel/stages.build_stage_graph makes its local stage ids agree with
the driver's), installs a :class:`ClusterExecInfo` marking the
assigned stage as LOCAL (write session) and every other dispatchable
stage as REMOTE (fetch-only adoption of the committed spool), and
drives the boundary exchange's ``stage_prematerialize`` — exactly the
code path the single-process pipelined executor runs, pointed at the
shared spool. Success reports ``CDONE`` with the observed output
bytes (the coordinator's locality scores); failure reports ``CFAIL``,
owner-tagged with the lost dep stage when the error carries a
``fault_owner``, so the coordinator recomputes the dep instead of
blindly retrying the consumer.

Chaos: arming ``SRT_FAULTS=workerdeath@cluster.stage:1`` in ONE
worker's environment SIGKILLs that worker at the injection site just
before it executes a stage — the coordinator's heartbeat monitor
detects the death and requeues the task on a survivor (exactly one
stage recompute, never a dead query).
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__":          # bare-script env hygiene, before jax
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))))

import argparse
import base64
import hashlib
import json
import logging
import pickle
import signal
import threading
import time
from typing import Dict, Optional, Tuple

_LOG = logging.getLogger("spark_rapids_tpu.cluster.worker")


def _drop_remote_plugins() -> None:
    """CPU-pinned worker hygiene (mirrors tests/conftest.py): the
    environment may register a remote-TPU PJRT plugin whose tunnel
    claim costs seconds — a CPU worker must not initialize it."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    try:
        import jax
        import jax._src.xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:                          # pragma: no cover - env
        pass


class _QueryState:
    """One query's cached plan + execution context on this worker:
    unpickled once, reused across every task of the query."""

    __slots__ = ("root", "conf", "graph", "info", "ctx", "gens")

    def __init__(self, root, conf, graph, info, ctx):
        self.root = root
        self.conf = conf
        self.graph = graph
        self.info = info
        self.ctx = ctx
        self.gens: Dict[int, int] = {}     # sid -> last generation seen


class Worker:
    def __init__(self, coordinator: Tuple[str, int], worker_id: str,
                 poll_ms: int = 25, heartbeat_ms: int = 2000,
                 max_idle_s: float = 0.0, reconnect_s: float = 120.0):
        self.addr = coordinator
        self.wid = worker_id
        self.poll_ms = max(int(poll_ms), 1)
        self.heartbeat_ms = max(int(heartbeat_ms), 1)
        self.max_idle_s = float(max_idle_s)
        self.reconnect_s = float(reconnect_s)
        self.queries: Dict[int, _QueryState] = {}
        self._stop = threading.Event()
        self.tasks_done = 0
        # Incarnation token: one value per PROCESS, sent with every
        # CREG. The supervisor restarts a dead worker under the SAME
        # wid (HRW placement re-converges), and on a loaded host the
        # replacement can register BEFORE the heartbeat sweep notices
        # the silence — without the token the coordinator would read
        # that CREG as a beat from the old incarnation and its RUNNING
        # stage would stay assigned forever. A token mismatch is proof
        # of death; a reconnect after a coordinator outage reuses the
        # same token and stays a no-op.
        self.token = "%x.%x" % (os.getpid(),
                                int(time.time() * 1000.0) & 0xFFFFFF)
        # Self-retirement handshake (ISSUE 20 satellite): --max-idle-s
        # expiry sends CDRAIN and waits for the coordinator's CRETIRE
        # instead of silently exiting, so membership drops NOW rather
        # than after heartbeatTimeoutMs of ghost liveness.
        self._retiring = False
        self._retire_deadline = 0.0

    # -- control plane --------------------------------------------------------
    def _call(self, line: str, timeout_s: float = 10.0) -> str:
        from spark_rapids_tpu.parallel.transport import rendezvous as RV
        if not line.endswith("\n"):
            line += "\n"
        return RV._roundtrip(self.addr, line, timeout_s=timeout_s,
                             retries=3, backoff_ms=50)

    def _call_persistent(self, line: str, deadline_s: float) -> bool:
        """Deliver a must-arrive verb (CDONE/CFAIL) across a
        coordinator outage: keep retrying with capped backoff until the
        deadline. A restarted coordinator replays its journal, restores
        the task RUNNING under this worker's generation, and the
        retried report lands exactly as if nothing happened."""
        from spark_rapids_tpu.parallel.transport.rendezvous import \
            RendezvousUnavailableError
        end = time.monotonic() + deadline_s
        delay = 0.1
        while True:
            try:
                self._call(line, timeout_s=5.0)
                return True
            except RendezvousUnavailableError:
                if self._stop.is_set() or time.monotonic() >= end:
                    _LOG.warning("worker %s: gave up delivering %r "
                                 "after %.0fs", self.wid,
                                 line.split()[0], deadline_s)
                    return False
                time.sleep(delay)
                delay = min(delay * 2, 2.0)

    def _reconnect(self) -> bool:
        """Ride out a coordinator outage (THE fix for the old
        die-on-refused behavior): back off with a 2s cap inside the
        reconnect window, then re-register. Loaded queries, their
        warm execution contexts, spooled stage state, and kernel
        caches all survive — a coordinator restart costs this worker
        one CREG, not its whole state."""
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.parallel.transport.rendezvous import \
            RendezvousUnavailableError
        end = time.monotonic() + self.reconnect_s
        delay = 0.1
        _LOG.warning("worker %s: coordinator unreachable — "
                     "reconnecting for up to %.0fs", self.wid,
                     self.reconnect_s)
        while not self._stop.is_set() and time.monotonic() < end:
            time.sleep(delay)
            delay = min(delay * 2, 2.0)
            try:
                self._call(f"CREG {self.wid} {self.token}",
                           timeout_s=5.0)
            except RendezvousUnavailableError:
                continue
            monitoring.instant("worker-reconnect", "recovery",
                               args={"worker": self.wid})
            _LOG.warning("worker %s: re-registered with coordinator "
                         "(queries kept warm: %s)", self.wid,
                         sorted(self.queries) or "none")
            return True
        return False

    def register(self, deadline_s: float = 30.0) -> None:
        """CREG with retry-until-deadline: the launcher may start
        workers before the coordinator binds (elastic join is the same
        code path — a worker registering mid-run just starts winning
        polls)."""
        from spark_rapids_tpu.parallel.transport.rendezvous import \
            RendezvousUnavailableError
        end = time.monotonic() + deadline_s
        while True:
            try:
                self._call(f"CREG {self.wid} {self.token}")
                return
            except RendezvousUnavailableError:
                if time.monotonic() >= end:
                    raise
                time.sleep(0.1)

    def _heartbeat_loop(self) -> None:
        from spark_rapids_tpu.parallel.transport.rendezvous import \
            RendezvousUnavailableError
        interval = self.heartbeat_ms / 3000.0
        while not self._stop.wait(interval):
            line = f"CBEAT {self.wid}"
            try:
                # Telemetry piggyback: the flattened local registry
                # (cumulative absolutes, so a lost beat costs nothing)
                # rides the heartbeat — the coordinator feeds it into
                # the driver's fleet view with a worker label.
                from spark_rapids_tpu.monitoring import telemetry
                if telemetry.enabled():
                    # Memory-pressure score first, so every beat carries
                    # THIS worker's current catalog watermarks (the max
                    # over loaded queries: one hot query is enough to
                    # shed placement here).
                    from spark_rapids_tpu.memory import stores
                    score = 0.0
                    for st in list(self.queries.values()):
                        cat = getattr(st.ctx, "_catalog", None)
                        if cat is not None:
                            score = max(score,
                                        stores.pressure_score(cat))
                    telemetry.set_gauge("srt_pressure_score", score)
                    blob = base64.b64encode(json.dumps(
                        telemetry.export_cluster_blob(),
                        default=str).encode()).decode()
                    line = f"CBEAT {self.wid} {blob}"
            except Exception:          # a beat must never die on stats
                pass
            try:
                self._call(line, timeout_s=5.0)
            except RendezvousUnavailableError:
                # The main loop owns the exit decision; a missed beat
                # on a live coordinator merely looks slow.
                pass

    # -- task execution -------------------------------------------------------
    def _load_query(self, qid: int, pkl_path: str) -> _QueryState:
        st = self.queries.get(qid)
        if st is not None:
            return st
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu import faults, monitoring
        from spark_rapids_tpu.ops.base import ExecContext
        from spark_rapids_tpu.parallel.cluster.coordinator import (
            ClusterCoordinator, ClusterExecInfo, cluster_store_kind,
            stage_plan)
        with open(pkl_path, "rb") as f:
            blob = f.read()
        root, raw, binds = pickle.loads(blob)
        conf = C.TpuConf(raw)
        monitoring.maybe_configure(conf)
        monitoring.telemetry.maybe_configure(conf)
        faults.maybe_configure(conf)
        graph, dispatchable, deps = stage_plan(root)
        tags = {id(graph.stages[sid].boundary): (sid, f"s{sid}")
                for sid in dispatchable}
        # Store coordinates ride IN the shipped conf (submit pins
        # them), so every worker publishes/fetches through the same
        # endpoint + key prefix the driver resolved. The spool dir
        # fallback: remote submissions park the plan under <dir>/plans,
        # so derive the query spool from the cluster dir, not the
        # pickle's parent.
        kind = cluster_store_kind(conf)
        endpoint = prefix = ""
        if kind == "objectstore":
            endpoint = str(conf.get(
                C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT) or "")
            prefix = str(conf.get(
                C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX) or "")
        pkl_dir = os.path.dirname(pkl_path)
        if os.path.basename(pkl_dir) == "plans":
            spool = os.path.join(os.path.dirname(pkl_dir), f"q{qid}")
        else:
            spool = pkl_dir
        bcast_tags, bcast_deps = \
            ClusterCoordinator._broadcast_maps(graph, deps)
        st_holder: list = []
        info = ClusterExecInfo(
            spool, self.wid, tags, local_sid=None, store_kind=kind,
            store_endpoint=endpoint, store_prefix=prefix,
            bcast_tags=bcast_tags, bcast_deps=bcast_deps,
            plan_fp=hashlib.sha256(blob).hexdigest()[:12],
            gen_source=lambda: dict(st_holder[0].gens)
            if st_holder else {})
        ctx = ExecContext(conf)
        ctx.cache["engine"] = "device"
        ctx.cache["cluster"] = info
        if binds is not None:
            # Parameterized plan-cache template: the driver's bound
            # literals ride along in the plan blob so bind slots
            # resolve to THIS collect's values in every process.
            ctx.cache["plan_binds"] = tuple(binds[0])
            ctx.cache["plan_bind_dtypes"] = tuple(binds[1])
        st = _QueryState(root, conf, graph, info, ctx)
        st_holder.append(st)
        self.queries[qid] = st
        _LOG.info("worker %s: loaded query %d (%d dispatchable stages)",
                  self.wid, qid, len(dispatchable))
        return st

    def _close_query(self, qid: int) -> None:
        st = self.queries.pop(qid, None)
        if st is not None:
            try:
                st.ctx.close()
            except Exception:                  # pragma: no cover - teardown
                _LOG.exception("worker %s: context close of query %d",
                               self.wid, qid)

    def _sync_gens(self, st: _QueryState, sid: int, gen: int,
                   depgens: str) -> None:
        """Invalidate locally-cached stage state whose generation moved
        on: a requeued/recomputed stage's old spool is gone, so this
        worker's cached sessions and bucket caches for it are stale."""
        want = {sid: gen}
        if depgens and depgens != "-":
            for ent in depgens.split(","):
                d, _, g = ent.partition(":")
                want[int(d)] = int(g)
        for s, g in want.items():
            seen = st.gens.get(s)
            if seen is not None and seen != g:
                boundary = st.graph.stages[s].boundary
                if boundary is not None:
                    boundary.stage_invalidate(st.ctx)
                _LOG.info("worker %s: stage s%d moved gen %d -> %d; "
                          "dropped cached state", self.wid, s, seen, g)
            st.gens[s] = g

    def execute(self, qid: int, sid: int, gen: int, depgens: str,
                pkl_path: str) -> None:
        from spark_rapids_tpu import faults, monitoring
        st = self._load_query(qid, pkl_path)
        self._sync_gens(st, sid, gen, depgens)
        st.info.set_local(sid)
        try:
            # The workerdeath chaos site: a SIGKILL here leaves the
            # task RUNNING at the coordinator until the heartbeat
            # timeout declares this worker dead — real process death,
            # not a simulated exception.
            if faults.check_fault("cluster.stage",
                                  ("workerdeath",)) is not None:
                _LOG.warning("worker %s: injected workerdeath — "
                             "SIGKILL", self.wid)
                logging.shutdown()
                os.kill(os.getpid(), signal.SIGKILL)
            boundary = st.graph.stages[sid].boundary
            with monitoring.span("cluster-stage", "cluster",
                                 args={"query": qid, "stage": sid,
                                       "worker": self.wid}):
                boundary.stage_prematerialize(st.ctx)
            sess = st.ctx.cache.get(boundary._cache_key(True))
            nbytes = sess.observed_bytes() if sess is not None else 0
        except Exception as e:
            lost = self._lost_dep(st, sid, e)
            msg = base64.b64encode(
                f"{type(e).__name__}: {e}"[:512].encode()).decode()
            _LOG.warning("worker %s: stage s%d of query %d failed "
                         "(lost dep: %s): %s", self.wid, sid, qid,
                         lost, e, exc_info=True)
            self._call_persistent(
                f"CFAIL {self.wid} {qid} {sid} {gen} "
                f"{'-' if lost is None else lost} {msg}",
                deadline_s=self.reconnect_s)
            return
        finally:
            st.info.set_local(None)
        self.tasks_done += 1
        extra = self._stage_report(st)
        self._call_persistent(
            f"CDONE {self.wid} {qid} {sid} {gen} {nbytes}"
            + (f" {extra}" if extra else ""),
            deadline_s=self.reconnect_s)

    def _stage_report(self, st: _QueryState) -> Optional[str]:
        """b64(JSON) CDONE piggyback: this query's per-node observed
        metrics in the shared DFS-preorder indexing (the driver merges
        them into its own ctx so a cluster ``explain_analyze`` shows
        worker-stage rows/bytes), plus — when the flight recorder is on
        — this worker's trace ring and thread names for the driver's
        merged one-file Perfetto export. Cumulative per query: each
        CDONE supersedes the last, so the coordinator keeps only the
        latest report per worker."""
        try:
            from spark_rapids_tpu import monitoring
            from spark_rapids_tpu.monitoring import history
            payload: dict = {}
            nodes = [n for n in history.node_stats(st.root, st.ctx)
                     if n["rows"] is not None or n["bytes"] is not None
                     or n["batches"] or n["wall_ms"]]
            if nodes:
                payload["nodes"] = nodes
            if monitoring.enabled():
                payload["events"] = [list(e) for e in monitoring.events()]
                payload["threads"] = {
                    str(k): v
                    for k, v in monitoring.thread_names().items()}
                payload["tag"] = (monitoring.process_tag()
                                  or f"worker {self.wid}")
            if not payload:
                return None
            return base64.b64encode(
                json.dumps(payload, default=str).encode()).decode()
        except Exception:              # stats must never fail the task
            _LOG.warning("worker %s: stage report build failed",
                         self.wid, exc_info=True)
            return None

    def _lost_dep(self, st: _QueryState, sid: int,
                  e: BaseException) -> Optional[int]:
        """Map an owner-tagged failure (ShardLostError, persistent CRC
        loss) to the DEP stage whose spool is gone — the coordinator
        recomputes it before requeueing this task. The failing stage's
        OWN id is not a lost dep (its output was never committed)."""
        owner = getattr(e, "fault_owner", None)
        if owner is None:
            return None
        lost = st.graph.by_exchange.get(owner)
        if lost is None or lost == sid:
            return None
        # A lost dep's local fetch state is stale the moment the
        # coordinator recomputes it; drop it now so the retried task
        # re-adopts the rewritten manifest.
        boundary = st.graph.stages[lost].boundary
        if boundary is not None:
            boundary.stage_invalidate(st.ctx)
        st.gens.pop(lost, None)
        return lost

    # -- main loop ------------------------------------------------------------
    def run(self) -> int:
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.parallel.transport.rendezvous import \
            RendezvousUnavailableError
        _drop_remote_plugins()
        # Trace exports from this process name their tracks after the
        # worker, so side-by-side per-process traces stay attributable.
        monitoring.set_process_tag(f"worker {self.wid}")
        self.register()
        hb = threading.Thread(target=self._heartbeat_loop,
                              name=f"srt-worker-hb-{self.wid}",
                              daemon=True)
        hb.start()
        _LOG.info("worker %s: registered with %s:%d", self.wid,
                  self.addr[0], self.addr[1])
        idle_since = time.monotonic()
        # Hot-poll backoff: right after finishing a stage the next
        # dispatch is usually imminent (the downstream stage just
        # unblocked), so poll tightly; every consecutive empty poll
        # doubles the sleep up to the configured interval, so workers
        # sitting out a long foreign stage don't burn the core the
        # busy worker needs. Fully idle workers (no loaded query) stay
        # at the configured interval.
        hot_s = min(self.poll_ms, 2) / 1000.0
        poll_s = self.poll_ms / 1000.0
        delay_s = poll_s
        try:
            while not self._stop.is_set():
                known = ",".join(str(q) for q in self.queries) or "-"
                try:
                    resp = self._call(f"CPOLL {self.wid} {known}")
                except RendezvousUnavailableError:
                    if self._reconnect():
                        idle_since = time.monotonic()
                        continue
                    _LOG.warning("worker %s: coordinator unreachable "
                                 "past the %.0fs reconnect window — "
                                 "exiting", self.wid, self.reconnect_s)
                    return 1
                parts = resp.split()
                if parts and parts[0] == "CRETIRE":
                    # Clean retirement: the coordinator already dropped
                    # this worker from membership (no heartbeat-timeout
                    # wait, no death counter) — exit for real.
                    monitoring.instant("worker-retire-ack", "cluster",
                                       args={"worker": self.wid})
                    _LOG.info("worker %s: retired by coordinator — "
                              "exiting cleanly", self.wid)
                    return 0
                if parts and parts[0] == "CTASK":
                    qid, sid, gen = (int(parts[1]), int(parts[2]),
                                     int(parts[3]))
                    pkl_path = base64.b64decode(parts[5]).decode()
                    self.execute(qid, sid, gen, parts[4], pkl_path)
                    idle_since = time.monotonic()
                    delay_s = hot_s
                    continue
                if parts and parts[0] == "CIDLE" and parts[1] != "-":
                    for q in parts[1].split(","):
                        if q:
                            self._close_query(int(q))
                if self.max_idle_s and not self._retiring and \
                        time.monotonic() - idle_since > self.max_idle_s:
                    # Deregister-then-exit (NOT a silent return): the
                    # CDRAIN/CRETIRE handshake retires this worker at
                    # the coordinator immediately; silently exiting
                    # left a ghost member other dispatches waited
                    # heartbeatTimeoutMs to bury.
                    self._retiring = True
                    self._retire_deadline = time.monotonic() + 10.0
                    try:
                        self._call(f"CDRAIN {self.wid}", timeout_s=5.0)
                    except RendezvousUnavailableError:
                        _LOG.info("worker %s: idle %.0fs and the "
                                  "coordinator is gone — exiting",
                                  self.wid, self.max_idle_s)
                        return 0
                    _LOG.info("worker %s: idle %.0fs — draining for "
                              "clean retirement", self.wid,
                              self.max_idle_s)
                    delay_s = hot_s       # the CRETIRE is imminent
                    continue
                if self._retiring and \
                        time.monotonic() > self._retire_deadline:
                    # The CRETIRE never came (coordinator restarted
                    # without its journal?): fall back to the old
                    # silent exit rather than polling forever.
                    _LOG.warning("worker %s: no CRETIRE within 10s of "
                                 "CDRAIN — exiting anyway", self.wid)
                    return 0
                if self.queries:
                    time.sleep(delay_s)
                    delay_s = min(delay_s * 2, poll_s)
                else:
                    delay_s = poll_s
                    time.sleep(poll_s)
            return 0
        finally:
            self._stop.set()
            for qid in list(self.queries):
                self._close_query(qid)

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="spark-rapids-tpu cluster worker")
    ap.add_argument("--coordinator", required=True,
                    help="host:port of the driver's cluster rendezvous")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--poll-ms", type=int, default=25)
    ap.add_argument("--heartbeat-ms", type=int, default=2000)
    ap.add_argument("--max-idle-s", type=float, default=0.0,
                    help="exit after this long without a task (0=never)")
    ap.add_argument("--reconnect-s", type=float, default=120.0,
                    help="how long to ride out a coordinator outage "
                         "before exiting")
    ap.add_argument("--log-level", default="INFO")
    a = ap.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, a.log_level.upper(), logging.INFO),
        format=f"%(asctime)s {a.worker_id} %(levelname)s %(message)s")
    host, _, port = a.coordinator.rpartition(":")
    w = Worker((host or "127.0.0.1", int(port)), a.worker_id,
               poll_ms=a.poll_ms, heartbeat_ms=a.heartbeat_ms,
               max_idle_s=a.max_idle_s, reconnect_s=a.reconnect_s)
    signal.signal(signal.SIGTERM, lambda *_: w.stop())
    return w.run()


if __name__ == "__main__":
    sys.exit(main())
