"""Self-healing worker supervision (ISSUE 20 tentpole).

The cluster runtime could already *tolerate* a worker death (the
heartbeat sweep requeues its RUNNING stage, coordinator.py) — but
nothing ever brought the worker back, so a SIGKILL'd pool shrank
monotonically and a crash-looping worker flapped forever. The
:class:`Supervisor` owns the worker pool and closes that loop:

- **restart with exponential backoff**: a dead worker respawns after
  ``restartBackoffBaseMs * 2**(deaths-1)`` (capped), under the SAME
  worker id and environment, so locality/HRW placement re-converges;
- **crash-loop quarantine**: ``crashLoopThreshold`` deaths inside
  ``crashLoopWindowMs`` quarantine the worker — held out with a typed
  reason, surfaced as the ``srt_quarantined_workers`` gauge, a
  ``worker-quarantined`` event-log instant and a fleet record —
  instead of being respawned forever;
- **straggler demotion**: per-worker CBEAT heartbeat gaps and
  per-stage walls (coordinator CSTATS) feed a median-outlier detector;
  a worker whose medians exceed ``stragglerFactor`` × the fleet median
  is demoted below steal-delay placement preference (``CDEMO`` — the
  same tier pressure shedding uses) and promoted back on recovery;
- **clean drain on scale-down**: ``drain(wid)`` sends ``CDRAIN``; the
  coordinator stops dispatching to the worker, its in-flight stages
  commit their manifests, its next idle poll answers ``CRETIRE`` and
  the process exits 0 — scale-down never costs a stage recompute.

The policy arithmetic (backoff schedule, quarantine window, outlier
detection, drain ordering) is pure functions so tests/test_supervisor.py
pins it without processes. Everything here is inert unless a
supervisor is actually constructed (``scripts/cluster.py --supervise``
or the autoscaler): the default pool behaviour is byte-identical.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from spark_rapids_tpu import config as C

_LOG = logging.getLogger("spark_rapids_tpu.cluster.supervisor")

# Managed-worker states.
RUNNING = "running"          # process alive (or believed alive)
BACKOFF = "backoff"          # died; restart scheduled
QUARANTINED = "quarantined"  # crash-looped; held out, never respawned
DRAINING = "draining"        # CDRAIN sent; waiting for clean exit
RETIRED = "retired"          # drained and exited 0 — a non-death


# -- policy units (pure; pinned by tests/test_supervisor.py) -----------------

def restart_backoff_ms(deaths: int, base_ms: float,
                       cap_ms: float) -> float:
    """Delay before restart number ``deaths`` (1-based): deterministic
    exponential ``base * 2**(deaths-1)`` capped at ``cap_ms``. No
    jitter on purpose — one supervisor restarts its own pool, there is
    no thundering herd to spread, and determinism keeps the schedule
    assertable."""
    if deaths <= 0:
        return 0.0
    return min(float(base_ms) * (2.0 ** (min(deaths, 63) - 1)),
               float(cap_ms))


def is_crash_looping(death_ts: Sequence[float], now: float,
                     window_ms: float, threshold: int) -> bool:
    """Quarantine arithmetic: ``threshold`` deaths whose timestamps
    fall inside the trailing ``window_ms`` window ending at ``now``."""
    if threshold <= 0:
        return True
    cutoff = now - window_ms / 1000.0
    return sum(1 for t in death_ts if t >= cutoff) >= int(threshold)


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def straggler_verdicts(samples: Dict[str, Sequence[float]],
                       factor: float, min_samples: int,
                       demoted: Optional[Set[str]] = None
                       ) -> Dict[str, bool]:
    """Median-outlier straggler detection over per-worker latency
    samples (CBEAT gaps or stage walls, ms). A worker is judged only
    once it has ``min_samples`` observations and at least one OTHER
    worker is judgeable (an outlier needs a fleet to be an outlier
    of). Returns wid -> should-be-demoted; hysteresis: an
    already-demoted worker (``demoted``) is only promoted back once
    its median drops under ``factor/2`` × the fleet median, so a
    worker hovering at the threshold doesn't flap."""
    demoted = demoted or set()
    meds = {w: _median(v) for w, v in samples.items()
            if len(v) >= max(int(min_samples), 1)}
    if len(meds) < 2:
        return {w: (w in demoted) for w in samples}
    fleet = _median([m for w, m in sorted(meds.items())])
    out: Dict[str, bool] = {}
    for w in samples:
        m = meds.get(w)
        if m is None or fleet <= 0:
            out[w] = w in demoted
        elif w in demoted:
            out[w] = m > (factor / 2.0) * fleet
        else:
            out[w] = m > factor * fleet
    return out


def drain_order(stats_workers: Dict[str, dict]) -> List[str]:
    """Which worker to drain first on scale-down: demoted stragglers,
    then the least useful (fewest completed stages), idlest last-seen
    breaking ties — deterministic by wid at the end."""
    def key(item):
        wid, w = item
        return (0 if w.get("demoted") else 1,
                int(w.get("completed", 0)),
                -int(w.get("idle_ms", 0)),
                wid)
    return [wid for wid, _ in sorted(stats_workers.items(), key=key)]


# -- the supervisor proper ---------------------------------------------------

class ManagedWorker:
    """One supervised worker: the live process handle plus the policy
    state the restart/quarantine machinery folds over."""

    __slots__ = ("wid", "proc", "state", "extra_env", "deaths",
                 "death_ts", "restarts", "next_restart_at",
                 "drain_deadline", "reason")

    def __init__(self, wid: str, proc=None, extra_env=None):
        self.wid = wid
        self.proc = proc
        self.state = RUNNING if proc is not None else BACKOFF
        self.extra_env = dict(extra_env or {})
        self.deaths = 0
        self.death_ts: List[float] = []
        self.restarts = 0
        self.next_restart_at = 0.0
        self.drain_deadline = 0.0
        self.reason = ""


class Supervisor:
    """Owns a pool of worker processes against one coordinator address
    and drives the observe→decide→act loop: reap deaths, restart with
    backoff, quarantine crash-loopers, demote stragglers, drain on
    scale-down. Usable in-process (tests, the autoscaler, bench) or
    standalone via ``scripts/cluster.py --supervise``."""

    def __init__(self, addr: str, conf=None, prefix: str = "sw",
                 heartbeat_ms: Optional[int] = None,
                 spawn_fn: Optional[Callable] = None,
                 stats_fn: Optional[Callable[[], dict]] = None,
                 verb_fn: Optional[Callable[[str], str]] = None):
        conf = conf if conf is not None else C.TpuConf({})
        self.addr = addr
        self.prefix = prefix
        self.heartbeat_ms = heartbeat_ms
        self.poll_ms = max(int(conf.get(C.CLUSTER_SUPERVISOR_POLL_MS)),
                           10)
        self.backoff_base_ms = float(
            conf.get(C.CLUSTER_SUPERVISOR_BACKOFF_BASE_MS))
        self.backoff_cap_ms = float(
            conf.get(C.CLUSTER_SUPERVISOR_BACKOFF_CAP_MS))
        self.crash_window_ms = float(
            conf.get(C.CLUSTER_SUPERVISOR_CRASH_LOOP_WINDOW_MS))
        self.crash_threshold = int(
            conf.get(C.CLUSTER_SUPERVISOR_CRASH_LOOP_THRESHOLD))
        self.straggler_factor = float(
            conf.get(C.CLUSTER_SUPERVISOR_STRAGGLER_FACTOR))
        self.straggler_min_samples = int(
            conf.get(C.CLUSTER_SUPERVISOR_STRAGGLER_MIN_SAMPLES))
        self.drain_timeout_ms = float(
            conf.get(C.CLUSTER_SUPERVISOR_DRAIN_TIMEOUT_MS))
        self._spawn_fn = spawn_fn or self._spawn_proc
        self._stats_fn = stats_fn
        self._verb_fn = verb_fn
        self._lock = threading.RLock()
        self.workers: Dict[str, ManagedWorker] = {}
        self._demoted: Set[str] = set()
        self._next_idx = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Decision/action counters bench.py's autoscale block reports.
        self.counters = {"restarts": 0, "quarantines": 0, "drains": 0,
                         "retirements": 0, "demotions": 0,
                         "promotions": 0}

    # -- plumbing ------------------------------------------------------------
    def _spawn_proc(self, wid: str, extra_env: Dict[str, str]):
        import spark_rapids_tpu
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(spark_rapids_tpu.__file__)))
        cmd = [sys.executable, "-m",
               "spark_rapids_tpu.parallel.cluster.worker",
               "--coordinator", self.addr, "--worker-id", wid]
        if self.heartbeat_ms:
            cmd += ["--heartbeat-ms", str(self.heartbeat_ms)]
        env = dict(os.environ)
        # Fault schedules are per-worker: never inherit one into the
        # pool — a seeded crash-looper gets its schedule EXPLICITLY
        # via extra_env (and keeps it across restarts, which is what
        # makes it loop).
        env.pop("SRT_FAULTS", None)
        env.update(extra_env)
        return subprocess.Popen(cmd, env=env, cwd=root)

    def _verb(self, line: str) -> Optional[str]:
        """One control-plane verb to the coordinator (CDRAIN/CDEMO/
        CSTATS); best-effort — a coordinator outage degrades a tick,
        never kills the supervisor."""
        try:
            if self._verb_fn is not None:
                return self._verb_fn(line)
            from spark_rapids_tpu.parallel.transport import \
                rendezvous as RV
            host, _, port = self.addr.rpartition(":")
            return RV._roundtrip((host or "127.0.0.1", int(port)),
                                 line + "\n", timeout_s=5.0, retries=1,
                                 backoff_ms=50)
        except Exception:
            _LOG.warning("supervisor: verb %r failed",
                         line.split()[0], exc_info=True)
            return None

    def _coordinator_stats(self) -> Optional[dict]:
        if self._stats_fn is not None:
            try:
                return self._stats_fn()
            except Exception:
                return None
        resp = self._verb("CSTATS")
        if not resp or not resp.startswith("OK "):
            return None
        try:
            return json.loads(base64.b64decode(resp[3:]).decode())
        except Exception:
            return None

    # -- pool management ------------------------------------------------------
    def add_worker(self, wid: Optional[str] = None,
                   extra_env: Optional[Dict[str, str]] = None) -> str:
        with self._lock:
            if wid is None:
                wid = f"{self.prefix}{self._next_idx}"
                self._next_idx += 1
            mw = ManagedWorker(wid, extra_env=extra_env)
            mw.proc = self._spawn_fn(wid, mw.extra_env)
            mw.state = RUNNING
            self.workers[wid] = mw
        from spark_rapids_tpu import monitoring
        monitoring.instant("worker-spawn", "cluster",
                           args={"worker": wid})
        self._log_fleet("worker-spawn", worker=wid)
        return wid

    def active_count(self) -> int:
        """Workers the pool can count on: running or pending restart.
        Draining/retired are on their way out, quarantined are out."""
        with self._lock:
            return sum(1 for w in self.workers.values()
                       if w.state in (RUNNING, BACKOFF))

    def scale_to(self, target: int) -> int:
        """Spawn or drain towards ``target`` active workers; returns
        the delta actually requested (positive = spawned)."""
        target = max(int(target), 0)
        with self._lock:
            active = [w for w in self.workers.values()
                      if w.state in (RUNNING, BACKOFF)]
            delta = target - len(active)
        if delta > 0:
            for _ in range(delta):
                self.add_worker()
        elif delta < 0:
            stats = self._coordinator_stats() or {}
            order = drain_order(stats.get("workers", {}))
            now = time.monotonic()
            with self._lock:
                # Capacity scale-down only picks STABLE workers: one
                # with a death inside the crash-loop window belongs to
                # the supervision plane (restart-or-quarantine), and
                # draining it would launder a crash-looper into a
                # clean-looking retirement before it burns its budget.
                drainable = [w.wid for w in self.workers.values()
                             if w.state == RUNNING
                             and not any(now - t <
                                         self.crash_window_ms / 1000.0
                                         for t in w.death_ts)]
            ranked = [w for w in order if w in drainable] + \
                [w for w in sorted(drainable) if w not in order]
            for wid in ranked[:-delta]:
                self.drain(wid)
        return delta

    def drain(self, wid: str) -> bool:
        """Clean scale-down of one worker: CDRAIN at the coordinator,
        then wait (in tick) for the process to exit 0."""
        with self._lock:
            mw = self.workers.get(wid)
            if mw is None or mw.state not in (RUNNING,):
                return False
            mw.state = DRAINING
            mw.drain_deadline = time.monotonic() + \
                self.drain_timeout_ms / 1000.0
            self.counters["drains"] += 1
        self._verb(f"CDRAIN {wid}")
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.monitoring import telemetry
        monitoring.instant("worker-drain-request", "cluster",
                           args={"worker": wid})
        if telemetry.enabled():
            telemetry.inc("srt_worker_drains")
        self._log_fleet("worker-drain", worker=wid)
        return True

    def quarantined(self) -> Dict[str, str]:
        with self._lock:
            return {w.wid: w.reason for w in self.workers.values()
                    if w.state == QUARANTINED}

    # -- the control loop -----------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One observe→decide→act pass. Deterministic given process
        exits and coordinator stats; the run() loop just repeats it."""
        now = time.monotonic() if now is None else now
        self._reap_and_restart(now)
        self._scan_stragglers()
        self._publish_gauges()

    def _reap_and_restart(self, now: float) -> None:
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.monitoring import telemetry
        with self._lock:
            items = list(self.workers.values())
        for mw in items:
            rc = mw.proc.poll() if mw.proc is not None else None
            if mw.state == RUNNING and rc is not None:
                if rc == 0:
                    # Self-retirement (--max-idle-s drain handshake):
                    # a clean exit is not a death.
                    with self._lock:
                        mw.state = RETIRED
                        self.counters["retirements"] += 1
                    self._log_fleet("worker-retired", worker=mw.wid,
                                    clean=True)
                    continue
                with self._lock:
                    mw.deaths += 1
                    mw.death_ts.append(now)
                    del mw.death_ts[:-16]
                    looping = is_crash_looping(
                        mw.death_ts, now, self.crash_window_ms,
                        self.crash_threshold)
                    if looping:
                        mw.state = QUARANTINED
                        mw.reason = (
                            f"crash-loop: {len(mw.death_ts)} deaths, "
                            f"last {self.crash_threshold} within "
                            f"{int(self.crash_window_ms)}ms "
                            f"(rc={rc})")
                        self.counters["quarantines"] += 1
                    else:
                        mw.state = BACKOFF
                        backoff = restart_backoff_ms(
                            mw.deaths, self.backoff_base_ms,
                            self.backoff_cap_ms)
                        mw.next_restart_at = now + backoff / 1000.0
                if looping:
                    _LOG.warning("supervisor: worker %s QUARANTINED "
                                 "(%s)", mw.wid, mw.reason)
                    monitoring.instant(
                        "worker-quarantined", "recovery",
                        args={"worker": mw.wid, "reason": mw.reason})
                    if telemetry.enabled():
                        telemetry.inc("srt_worker_quarantines")
                    self._log_fleet("worker-quarantined",
                                    worker=mw.wid, reason=mw.reason)
                else:
                    _LOG.warning(
                        "supervisor: worker %s died (rc=%s, death "
                        "%d) — restart in %.0fms", mw.wid, rc,
                        mw.deaths,
                        (mw.next_restart_at - now) * 1000.0)
                    self._log_fleet("worker-death", worker=mw.wid,
                                    rc=rc, deaths=mw.deaths)
            elif mw.state == BACKOFF and now >= mw.next_restart_at:
                with self._lock:
                    mw.proc = self._spawn_fn(mw.wid, mw.extra_env)
                    mw.state = RUNNING
                    mw.restarts += 1
                    self.counters["restarts"] += 1
                monitoring.instant("worker-restart", "recovery",
                                   args={"worker": mw.wid,
                                         "restarts": mw.restarts})
                if telemetry.enabled():
                    telemetry.inc("srt_worker_restarts")
                self._log_fleet("worker-restart", worker=mw.wid,
                                restarts=mw.restarts)
            elif mw.state == DRAINING:
                if rc is not None:
                    with self._lock:
                        mw.state = RETIRED
                        self.counters["retirements"] += 1
                    self._log_fleet("worker-retired", worker=mw.wid,
                                    clean=rc == 0)
                elif now > mw.drain_deadline:
                    # The drain never completed (stuck stage?): the
                    # heartbeat sweep will requeue whatever it held.
                    _LOG.warning("supervisor: drain of %s timed out "
                                 "— terminating", mw.wid)
                    try:
                        mw.proc.terminate()
                    except Exception:
                        pass
                    with self._lock:
                        mw.drain_deadline = now + 5.0

    def _scan_stragglers(self) -> None:
        stats = self._coordinator_stats()
        if not stats:
            return
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.monitoring import telemetry
        workers = stats.get("workers", {})
        with self._lock:
            managed = {wid for wid, w in self.workers.items()
                       if w.state == RUNNING}
        eligible = {wid: w for wid, w in workers.items()
                    if wid in managed and w.get("alive")}
        for kind in ("beat_ms", "stage_wall_ms"):
            samples = {wid: w.get(kind) or []
                       for wid, w in eligible.items()}
            verdicts = straggler_verdicts(
                samples, self.straggler_factor,
                self.straggler_min_samples, demoted=self._demoted)
            for wid, slow in sorted(verdicts.items()):
                if slow and wid not in self._demoted:
                    self._demoted.add(wid)
                    self.counters["demotions"] += 1
                    self._verb(f"CDEMO {wid} 1")
                    monitoring.instant(
                        "worker-straggler-demoted", "recovery",
                        args={"worker": wid, "signal": kind})
                    if telemetry.enabled():
                        telemetry.inc("srt_stragglers_demoted")
                    self._log_fleet("worker-straggler", worker=wid,
                                    signal=kind)
                elif not slow and wid in self._demoted and \
                        kind == "stage_wall_ms":
                    # Promotion needs BOTH signals healthy; checking on
                    # the second kind keeps one pass per tick simple.
                    beats = straggler_verdicts(
                        {w: eligible[w].get("beat_ms") or []
                         for w in eligible},
                        self.straggler_factor,
                        self.straggler_min_samples,
                        demoted=self._demoted)
                    if not beats.get(wid, False):
                        self._demoted.discard(wid)
                        self.counters["promotions"] += 1
                        self._verb(f"CDEMO {wid} 0")
                        self._log_fleet("worker-promoted", worker=wid)

    def _publish_gauges(self) -> None:
        from spark_rapids_tpu.monitoring import telemetry
        if not telemetry.enabled():
            return
        with self._lock:
            states: Dict[str, int] = {}
            for w in self.workers.values():
                states[w.state] = states.get(w.state, 0) + 1
        telemetry.set_gauge("srt_fleet_workers",
                            states.get(RUNNING, 0) +
                            states.get(BACKOFF, 0))
        telemetry.set_gauge("srt_quarantined_workers",
                            states.get(QUARANTINED, 0))

    def _log_fleet(self, event: str, **fields) -> None:
        from spark_rapids_tpu.monitoring import history
        with self._lock:
            workers = sum(1 for w in self.workers.values()
                          if w.state in (RUNNING, BACKOFF))
            quarantined = sum(1 for w in self.workers.values()
                              if w.state == QUARANTINED)
        history.log_fleet(event, workers=workers,
                          quarantined=quarantined, **fields)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self.run, name="srt-supervisor", daemon=True)
        self._thread.start()

    def run(self) -> None:
        while not self._stop.wait(self.poll_ms / 1000.0):
            try:
                self.tick()
            except Exception:      # the loop must survive any tick
                _LOG.exception("supervisor tick failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self, timeout_s: float = 15.0) -> None:
        """Stop the loop and reap every managed process."""
        self.stop()
        with self._lock:
            procs = [w.proc for w in self.workers.values()
                     if w.proc is not None]
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except Exception:
                    pass
        for p in procs:
            try:
                p.wait(timeout=timeout_s)
            except Exception:
                try:
                    p.kill()
                except Exception:
                    pass
