"""SLO-driven autoscaler (ISSUE 20 tentpole, second half).

The :class:`Supervisor` keeps a pool *healthy*; this module decides how
BIG the pool should be. One control loop scrapes the fleet signals the
scheduler already publishes —

- ``srt_admission_queued_ms`` (how long admitted queries waited),
- admission queue depth (:meth:`QueryManager.queued_count`),
- the dispatch pressure score (the brownout input), and
- coordinator per-worker busyness (CSTATS ``inflight``)

— and compares them against the ``cluster.autoscale.*`` SLO knobs.
Above target it spawns ``scaleUpStep`` workers through the supervisor;
once the fleet has been comfortably under target for
``scaleDownIdleS`` it drains ONE worker (CDRAIN → manifests commit →
CRETIRE), so scale-down never costs a stage recompute. ``cooldownMs``
gates consecutive decisions and the idle clock restarts after every
action, giving the loop classic hysteresis: fast up, slow down.

Brownout interplay: while an autoscaler is live it registers itself as
the scheduler's *scale probe* (:func:`scheduler.register_scale_probe`),
so sustained pressure first defers brownout by one window and triggers
a scale-up attempt; load shedding only engages once the fleet is
already at ``maxWorkers`` (or the probe declines). Capacity before
degradation.

The decision function is pure (:func:`decide`) so the policy is
unit-testable without processes; the :class:`Autoscaler` is the thin
wall-clock loop around it. Nothing here runs unless
``spark.rapids.sql.cluster.autoscale.enabled`` is flipped on.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.parallel.cluster.supervisor import Supervisor

_LOG = logging.getLogger("spark_rapids_tpu.cluster.autoscaler")

SCALE_UP = "up"
SCALE_DOWN = "down"
HOLD = "hold"


class ScalerState:
    """Mutable hysteresis state :func:`decide` folds over: when the
    last action fired (cooldown) and since when the fleet has been
    continuously under target (the scale-down idle clock)."""

    __slots__ = ("last_action_at", "under_target_since")

    def __init__(self):
        self.last_action_at: Optional[float] = None
        self.under_target_since: Optional[float] = None


def decide(now: float, current: int, signals: Dict[str, float],
           state: ScalerState, *, min_workers: int, max_workers: int,
           target_queued_ms: float, scale_up_step: int,
           scale_down_idle_s: float, cooldown_ms: float) -> dict:
    """One pure scaling decision.

    ``signals``: ``queued_ms`` (recent admission-wait quantile, ms),
    ``queue_depth`` (queries waiting for a slot), ``busy`` (workers
    with an in-flight stage), ``pressure`` (dispatch pressure score).
    Returns ``{"action": up|down|hold, "target": int, "reason": str}``
    with ``target == current`` on hold. The caller owns acting on it
    AND stamping ``state.last_action_at`` only when it really acted.
    """
    current = max(int(current), 0)
    queued_ms = float(signals.get("queued_ms", 0.0) or 0.0)
    depth = int(signals.get("queue_depth", 0) or 0)
    busy = int(signals.get("busy", 0) or 0)
    pressure = float(signals.get("pressure", 0.0) or 0.0)

    over = (queued_ms > target_queued_ms
            or (depth > 0 and busy >= current)
            or pressure >= 1.0)
    if over:
        # Any overload sign resets the idle clock even when the
        # cooldown (or the ceiling) blocks acting on it.
        state.under_target_since = None

    in_cooldown = (state.last_action_at is not None
                   and (now - state.last_action_at) * 1000.0
                   < cooldown_ms)
    if in_cooldown:
        return {"action": HOLD, "target": current,
                "reason": "cooldown"}

    if over:
        target = min(current + max(int(scale_up_step), 1),
                     int(max_workers))
        if target > current:
            return {"action": SCALE_UP, "target": target,
                    "reason": (f"queued_ms={queued_ms:.0f} "
                               f"depth={depth} busy={busy}/{current} "
                               f"pressure={pressure:.2f}")}
        return {"action": HOLD, "target": current,
                "reason": "at-max-workers"}

    if current > int(min_workers):
        if state.under_target_since is None:
            state.under_target_since = now
            return {"action": HOLD, "target": current,
                    "reason": "idle-clock-started"}
        idle_s = now - state.under_target_since
        if idle_s >= float(scale_down_idle_s):
            return {"action": SCALE_DOWN, "target": current - 1,
                    "reason": f"under-target {idle_s:.1f}s"}
        return {"action": HOLD, "target": current,
                "reason": f"idle {idle_s:.1f}s/"
                          f"{scale_down_idle_s:.0f}s"}
    return {"action": HOLD, "target": current,
            "reason": "at-min-workers"}


class Autoscaler:
    """Wall-clock loop: gather signals → :func:`decide` → act through
    the supervisor. Also the scheduler's scale probe while alive, so
    brownout defers to a scale-up attempt when headroom remains."""

    def __init__(self, supervisor: Supervisor, conf=None,
                 signals_fn=None):
        conf = conf if conf is not None else C.TpuConf({})
        self.sup = supervisor
        self.conf = conf
        self.min_workers = max(
            int(conf.get(C.CLUSTER_AUTOSCALE_MIN_WORKERS)), 0)
        self.max_workers = max(
            int(conf.get(C.CLUSTER_AUTOSCALE_MAX_WORKERS)),
            self.min_workers)
        self.target_queued_ms = float(
            conf.get(C.CLUSTER_AUTOSCALE_TARGET_QUEUED_MS))
        self.scale_up_step = int(
            conf.get(C.CLUSTER_AUTOSCALE_SCALE_UP_STEP))
        self.scale_down_idle_s = float(
            conf.get(C.CLUSTER_AUTOSCALE_SCALE_DOWN_IDLE_S))
        self.cooldown_ms = float(
            conf.get(C.CLUSTER_AUTOSCALE_COOLDOWN_MS))
        self._signals_fn = signals_fn
        self.state = ScalerState()
        self.decisions = {"up": 0, "down": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal scraping -----------------------------------------------------
    def gather_signals(self) -> Dict[str, float]:
        if self._signals_fn is not None:
            return dict(self._signals_fn())
        sig: Dict[str, float] = {"queued_ms": 0.0, "queue_depth": 0,
                                 "busy": 0, "pressure": 0.0}
        try:
            from spark_rapids_tpu.parallel import scheduler as S
            qm = S.get_query_manager(self.conf)._current()
            sig["queue_depth"] = int(qm.queued_count)
            sig["pressure"] = float(qm._pressure_score)
        except Exception:
            pass
        try:
            from spark_rapids_tpu.monitoring import telemetry
            if telemetry.enabled():
                snap = telemetry.snapshot()
                m = snap["metrics"].get("srt_admission_queued_ms")
                if m:
                    p95s = [s.get("p95") for s in m["series"]
                            if s.get("p95") == s.get("p95")]  # no NaN
                    if p95s:
                        sig["queued_ms"] = max(p95s)
        except Exception:
            pass
        stats = self.sup._coordinator_stats()
        if stats:
            sig["busy"] = sum(
                1 for w in stats.get("workers", {}).values()
                if w.get("alive") and w.get("inflight", 0) > 0)
        return sig

    # -- brownout scale probe ------------------------------------------------
    def scale_probe(self, score: float) -> bool:
        """Called by the scheduler when pressure has sustained long
        enough to brown out. Returns True (defer brownout one window)
        when a scale-up was possible and has been requested; False
        (shed load now) once the fleet is at max."""
        current = self.sup.active_count()
        if current >= self.max_workers:
            return False
        self._act({"action": SCALE_UP,
                   "target": min(current + max(self.scale_up_step, 1),
                                 self.max_workers),
                   "reason": f"brownout-probe pressure={score:.2f}"},
                  current)
        return True

    # -- loop ----------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        current = self.sup.active_count()
        if current < self.min_workers:
            # The floor is not a scaling decision: quarantines or
            # failed restarts dropping the fleet under minWorkers are
            # replaced immediately, cooldown or not.
            self.sup.scale_to(self.min_workers)
            return {"action": SCALE_UP, "target": self.min_workers,
                    "reason": "below-min-workers"}
        d = decide(now, current, self.gather_signals(), self.state,
                   min_workers=self.min_workers,
                   max_workers=self.max_workers,
                   target_queued_ms=self.target_queued_ms,
                   scale_up_step=self.scale_up_step,
                   scale_down_idle_s=self.scale_down_idle_s,
                   cooldown_ms=self.cooldown_ms)
        if d["action"] != HOLD:
            self._act(d, current, now=now)
        return d

    def _act(self, d: dict, current: int,
             now: Optional[float] = None) -> None:
        from spark_rapids_tpu import monitoring
        from spark_rapids_tpu.monitoring import telemetry
        self.state.last_action_at = \
            time.monotonic() if now is None else now
        self.state.under_target_since = None
        self.decisions[d["action"]] = \
            self.decisions.get(d["action"], 0) + 1
        _LOG.info("autoscale %s: %d -> %d (%s)", d["action"], current,
                  d["target"], d["reason"])
        monitoring.instant(f"autoscale-{d['action']}", "cluster",
                           args={"from": current, "to": d["target"],
                                 "reason": d["reason"]})
        if telemetry.enabled():
            telemetry.inc(f"srt_autoscale_{d['action']}")
            telemetry.set_gauge("srt_fleet_target", d["target"])
        self.sup._log_fleet(f"autoscale-{d['action']}",
                            current=current, target=d["target"],
                            reason=d["reason"])
        self.sup.scale_to(d["target"])

    def start(self) -> None:
        if self._thread is not None:
            return
        from spark_rapids_tpu.parallel import scheduler as S
        S.register_scale_probe(self.scale_probe)
        self._thread = threading.Thread(
            target=self._run, name="srt-autoscaler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.sup.poll_ms / 1000.0):
            try:
                self.tick()
            except Exception:
                _LOG.exception("autoscaler tick failed")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        from spark_rapids_tpu.parallel import scheduler as S
        S.register_scale_probe(None)
