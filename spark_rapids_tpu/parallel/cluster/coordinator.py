"""Driver-side cluster coordinator: stage-task scheduling + membership.

One :class:`ClusterCoordinator` per driver process owns the control
plane — a :class:`ClusterServer` extending the rendezvous wire protocol
(parallel/transport/rendezvous.py) with stage-task verbs — and the
shared spool directory. Each eligible query submits a :class:`QueryRun`
whose physical plan is pickled to the spool once; workers unpickle it,
rebuild the (deterministically numbered) stage DAG, and execute their
assigned stages, publishing each stage output as an exclusive-manifest
hostfile exchange under ``<spool>/q<qid>/s<sid>/``.

Control-plane verbs (one UTF-8 line per connection, like the base
rendezvous grammar):

    CREG <wid>                                   -> OK
    CBEAT <wid>                                  -> OK
    CPOLL <wid> <known-qids|->                   -> CTASK <qid> <sid> <gen>
                                                      <depgens|-> <b64 path>
                                                  | CIDLE <stale-qids|->
    CDONE <wid> <qid> <sid> <gen> <bytes>        -> OK
    CFAIL <wid> <qid> <sid> <gen> <lost|-> <b64> -> OK
    CSTATS                                       -> OK <b64 json>
    CDRAIN <wid>                                 -> OK
    CDEMO <wid> <0|1>                            -> OK

Self-healing verbs (ISSUE 20): ``CDRAIN`` marks a worker draining —
the coordinator stops offering it stage tasks, lets its in-flight
stages commit their manifests, and answers its next idle ``CPOLL``
with ``CRETIRE`` so the worker exits cleanly (scale-down and
``--max-idle-s`` self-retirement never cost a stage recompute or a
heartbeat-timeout wait). ``CDEMO`` toggles the supervisor's straggler
demotion: a demoted worker drops below steal-delay placement
preference exactly like a pressure-shed worker (scheduler.pressure.*)
until the supervisor promotes it back.

Scheduling is pull-based: an idle worker polls and the coordinator
picks, among the READY tasks (all deps committed, dispatch gate of
``cluster.minWorkers`` open), the one this worker has the most input
bytes for — the locality-aware placement of the ISSUE (prefer the
worker already holding the largest input shards); ties break to the
smallest stage id, so placement is deterministic. Elastic membership
falls out of the same pull loop: a worker registering mid-run simply
starts winning polls for queued tasks.

Failure story:

- a worker whose heartbeat goes silent past ``heartbeatTimeoutMs`` is
  declared dead; its RUNNING task's partial spool is cleared, its
  generation bumps (a zombie's late commit with a stale generation is
  ignored), and the task requeues onto a survivor — ONE stage
  recompute, counted exactly like a lineage recompute, never a dead
  query;
- a worker that loses a DEP shard mid-fetch reports the owning stage
  (``CFAIL ... <lost-sid>``): the dep recomputes and the task requeues
  behind it;
- the driver's own post-fetch loss (``ShardLostError`` in the reduce)
  flows through the planner's rung-1 recompute, which calls
  :meth:`QueryRun.recompute` so the REMOTE stage rewrites its spool.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import pickle
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu import config as C
from spark_rapids_tpu.parallel.scheduler import QueryRejectedError
from spark_rapids_tpu.parallel.transport.rendezvous import RendezvousServer

_LOG = logging.getLogger("spark_rapids_tpu.cluster")

_PENDING, _RUNNING, _DONE = "pending", "running", "done"

# How long a retired wid's stray heartbeats are ignored before the id
# may be reused by a fresh CREG (the retiring worker's daemon beat
# thread may land one more CBEAT after its CRETIRE).
_RETIRE_GRACE_S = 10.0


class ClusterDispatchError(QueryRejectedError):
    """A query's stage-task set could not be completed (dispatch
    timeout, task retry budget exhausted, or a worker-reported
    non-recoverable stage failure).

    Subclasses :class:`QueryRejectedError` so the dispatch-timeout
    variant participates in the PR 16 backpressure contract: the
    coordinator barrier raises it with ``kind='dispatch-timeout'`` and
    a ``retry_after_ms`` hint, so after the planner's transient ladder
    is exhausted ``collect_with_retry`` backs off and resubmits instead
    of re-raising. Every other variant (retry budget exhausted, worker
    stage error) ships no hint — retrying as-is cannot help. The
    message is NOT rewritten into the ``REJECTED:`` shape: dispatch
    timeouts keep their ``UNAVAILABLE:`` marker so
    ``is_transient_error`` still routes them into the recovery ladder
    first."""

    def __init__(self, message: str, kind: str = "dispatch",
                 queue_depth: Optional[int] = None,
                 retry_after_ms: Optional[float] = None):
        RuntimeError.__init__(self, message)
        self.reason = message
        self.kind = kind
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms


def dispatch_timeout_error(message: str,
                           queue_depth: Optional[int] = None,
                           retry_after_ms: Optional[float] = None
                           ) -> ClusterDispatchError:
    """The dispatch-timeout rejection (ISSUE 20 satellite): built with
    ``kind='dispatch-timeout'`` and a ``retry_after_ms`` hint — never
    hintless — and recorded in the same structured shed-load telemetry
    as every scheduler rejection, so ``srt_queries_rejected`` and the
    retry-hint gauges cover coordinator-barrier sheds too."""
    hint = float(retry_after_ms) if retry_after_ms and \
        retry_after_ms > 0 else 250.0
    try:
        from spark_rapids_tpu.parallel.scheduler import _telemetry_reject
        _telemetry_reject("dispatch-timeout", int(queue_depth or 0),
                          hint)
    except Exception:                  # telemetry must never mask the
        pass                           # rejection itself
    return ClusterDispatchError(message, kind="dispatch-timeout",
                                queue_depth=queue_depth,
                                retry_after_ms=hint)


def cluster_enabled(conf) -> bool:
    return bool(conf.get(C.CLUSTER_ENABLED))


def stage_plan(root, graph=None) -> Tuple[object, Set[int],
                                          Dict[int, Set[int]]]:
    """(stage graph, dispatchable stage ids, dispatchable-dep map).

    Dispatchable = stages whose boundary is a shuffle exchange: their
    durable output lives in the transport spool, so ANY process can
    produce or consume it. Broadcast stages are NOT dispatchable — a
    broadcast single materializes into the consuming process's catalog
    (Spark broadcast semantics: every executor holds the value), so
    each process computes broadcast stages locally; the dep map
    therefore flows THROUGH them transitively to the shuffle stages
    they consume."""
    from spark_rapids_tpu.parallel import stages as S
    from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
    g = graph if graph is not None else S.build_stage_graph(root)
    dispatchable = {sid for sid, st in g.stages.items()
                    if isinstance(st.boundary, ShuffleExchangeExec)}
    deps: Dict[int, Set[int]] = {}

    def ddeps(sid: int) -> Set[int]:
        got = deps.get(sid)
        if got is not None:
            return got
        out: Set[int] = set()
        for p in g.stages[sid].parents:
            if p in dispatchable:
                out.add(p)
            out |= ddeps(p)
        deps[sid] = out
        return out

    for sid in g.stages:
        ddeps(sid)
    return g, dispatchable, deps


def _hrw_owner(wids: List[str], sid: int) -> Optional[str]:
    """Highest-random-weight (rendezvous hash) owner of stage ``sid``
    among worker ids ``wids``: deterministic for a given worker set
    (md5, not the salted builtin hash), and removing one worker only
    reassigns that worker's stages."""
    if not wids:
        return None
    return max(wids, key=lambda w: hashlib.md5(
        f"{w}|{sid}".encode()).digest())


def cluster_store_kind(conf) -> str:
    """Which transport cluster stage outputs publish through: the
    objectstore when the session runs on it, the hostfile spool for
    everything else (inprocess/hostfile — the shared directory is the
    DCN stand-in either way)."""
    from spark_rapids_tpu.parallel import transport as T
    return "objectstore" if T.transport_name(conf) == "objectstore" \
        else "hostfile"


class ClusterExecInfo:
    """Per-process cluster execution marker, parked at
    ``ctx.cache["cluster"]``: maps each dispatchable boundary exchange
    (by its in-process identity) to its cross-process stage tag and
    builds the exclusive-manifest transport sessions the exchange layer
    opens instead of its default transport — hostfile on the shared
    spool, or the objectstore under the query's key prefix when the
    session runs on that transport. ``local_sid`` is the stage THIS
    process is currently producing (None on the driver): its boundary
    gets a write session; every other tagged exchange gets a fetch-only
    session that adopts the committed manifest.

    Broadcast artifact cache (ISSUE 17 tentpole leg c): ``bcast_tags``
    maps each broadcast-boundary exchange to its stage id and
    ``broadcast_tag`` derives its cluster-wide cache key — plan
    fingerprint + the GENERATIONS of its dispatchable upstream stages,
    so a recomputed input invalidates the key and a stale cached build
    can never be adopted."""

    def __init__(self, spool_dir: str, worker_id: str,
                 tags: Dict[int, Tuple[int, str]],
                 local_sid: Optional[int] = None,
                 store_kind: str = "hostfile",
                 store_endpoint: str = "", store_prefix: str = "",
                 bcast_tags: Optional[Dict[int, int]] = None,
                 bcast_deps: Optional[Dict[int, List[int]]] = None,
                 plan_fp: str = "", gen_source=None):
        self.spool_dir = spool_dir
        self.worker_id = worker_id
        self.tags = tags                  # id(exchange) -> (sid, tag)
        self.local_sid = local_sid
        self.store_kind = store_kind
        self.store_endpoint = store_endpoint
        self.store_prefix = store_prefix
        self.bcast_tags = bcast_tags or {}    # id(exchange) -> sid
        self.bcast_deps = bcast_deps or {}    # sid -> dispatchable deps
        self.plan_fp = plan_fp
        self.gen_source = gen_source          # callable -> {sid: gen}

    def set_local(self, sid: Optional[int]) -> None:
        self.local_sid = sid

    def sid_of(self, exchange) -> Optional[int]:
        ent = self.tags.get(id(exchange))
        return None if ent is None else ent[0]

    def is_remote(self, exchange) -> bool:
        ent = self.tags.get(id(exchange))
        return ent is not None and ent[0] != self.local_sid

    def open_session(self, ctx, tag: str, num_partitions: int,
                     owner: Optional[int] = None,
                     fetch_timeout_ms: Optional[int] = None):
        """One exclusive-manifest cluster session on the query's store
        (hostfile spool or objectstore prefix); keep_on_close because
        the COORDINATOR owns query-end store cleanup, not any one
        context's teardown."""
        from spark_rapids_tpu.parallel import transport as T
        raw = dict(ctx.conf.raw)
        if self.store_kind == "objectstore":
            from spark_rapids_tpu.parallel.transport.objectstore import \
                ObjectStoreTransport
            raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT.key] = \
                self.store_endpoint
            raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX.key] = \
                self.store_prefix
            raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_WORKER_ID.key] = \
                self.worker_id
            raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_EXCLUSIVE_MANIFEST.key] \
                = True
            if fetch_timeout_ms is not None:
                raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_FETCH_TIMEOUT_MS
                    .key] = int(fetch_timeout_ms)
            sess = ObjectStoreTransport().open(
                C.TpuConf(raw), tag, num_partitions, owner=owner,
                catalog=ctx.catalog, metrics=T.metrics_entry(ctx))
        else:
            from spark_rapids_tpu.parallel.transport.hostfile import \
                HostFileTransport
            raw[C.SHUFFLE_TRANSPORT_HOSTFILE_DIR.key] = self.spool_dir
            raw[C.SHUFFLE_TRANSPORT_HOSTFILE_WORKER_ID.key] = \
                self.worker_id
            raw[C.SHUFFLE_TRANSPORT_HOSTFILE_EXCLUSIVE_MANIFEST.key] = \
                True
            raw[C.SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS.key] = ""
            if fetch_timeout_ms is not None:
                raw[C.SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS.key] \
                    = int(fetch_timeout_ms)
            sess = HostFileTransport().open(
                C.TpuConf(raw), tag, num_partitions, owner=owner,
                catalog=ctx.catalog, metrics=T.metrics_entry(ctx))
        sess.keep_on_close = True
        return sess

    def session_for(self, ctx, exchange):
        """The cluster transport session for a tagged exchange, or None
        (untagged — the exchange opens its configured transport as
        before)."""
        ent = self.tags.get(id(exchange))
        if ent is None:
            return None
        sid, tag = ent
        sess = self.open_session(
            ctx, tag, exchange.partitioning.num_partitions,
            owner=id(exchange))
        sess.fetch_only = sid != self.local_sid
        return sess

    def broadcast_tag(self, exchange) -> Optional[str]:
        """The broadcast artifact cache key for a broadcast-boundary
        exchange, or None (not a tagged broadcast stage / no plan
        fingerprint). Generation-keyed: a recomputed upstream shuffle
        stage changes the key, so a cached build of stale inputs is
        simply never found — the gen sum is defense-in-depth on top of
        bit-identical recomputes."""
        sid = self.bcast_tags.get(id(exchange))
        if sid is None or not self.plan_fp:
            return None
        gens: Dict[int, int] = {}
        if callable(self.gen_source):
            try:
                gens = self.gen_source() or {}
            except Exception:
                gens = {}
        gsum = sum(int(gens.get(d, 0))
                   for d in self.bcast_deps.get(sid, ()))
        return f"bc-{self.plan_fp}-s{sid}-g{gsum}"

    @staticmethod
    def adopt_manifest(sess, num_partitions: int) -> List[int]:
        """Reconstruct the map-side observations (exact per-bucket row
        counts + shard bytes) from the committed manifest, so the
        reduce side's AQE coalescing and the replanner's byte
        observations are IDENTICAL to the process that produced the
        stage — the bit-identity keystone."""
        rows = [0] * num_partitions
        for m in sess._load_manifests():
            for p_s, entries in m.get("shards", {}).items():
                p = int(p_s)
                for e in entries:
                    rows[p] += int(e.get("rows") or 0)
                    sess.record_shard_bytes(p, int(e.get("bytes") or 0))
        return rows


def merge_worker_reports(ctx, root, reports: Dict[str, dict]) -> None:
    """Fold the workers' CDONE stats blobs into the driver's view:
    per-node observed rows/bytes/wall land in ``ctx.metrics`` under
    the driver's own operator instances (matched by the shared DFS
    preorder index — both processes unpickled the same plan, so the
    walk agrees), and each worker's shipped trace ring is stashed in
    ``ctx.cache`` for the merged Perfetto export. The driver's own
    observations always win; among workers, the report that saw the
    most rows for a node wins (the producer saw the full output, a
    stage that merely fetched it saw a fetch-side partial). Shared by
    the in-process :class:`QueryRun` and the remote-coordinator client
    (parallel/cluster/remote.py)."""
    if not reports:
        return
    from spark_rapids_tpu.ops.base import Metrics
    ops: List = []

    def walk(op):
        ops.append(op)
        for c in op.children:
            walk(c)

    walk(root)
    filled: Dict[str, float] = {}   # key -> best worker row count
    events: Dict[str, tuple] = {}
    for wid in sorted(reports):
        rep = reports[wid]
        for n in rep.get("nodes") or []:
            i = n.get("idx")
            if not isinstance(i, int) or i >= len(ops):
                continue
            op = ops[i]
            if op.name != n.get("name"):
                continue    # plan-shape mismatch: refuse to mislabel
            vals: Dict[str, float] = {}
            if n.get("rows") is not None:
                vals["numOutputRows"] = float(n["rows"])
            if n.get("bytes") is not None:
                vals["numOutputBytes"] = float(n["bytes"])
            if n.get("batches"):
                vals["numOutputBatches"] = float(n["batches"])
            if n.get("wall_ms"):
                vals["totalTime"] = float(n["wall_ms"]) * 1e6
            if not vals:
                continue
            key = f"{op.name}@{id(op):x}"
            m = ctx.metrics.get(key)
            if m is not None and key not in filled:
                continue    # the driver observed this node itself
            score = vals.get("numOutputRows",
                             vals.get("totalTime", 0.0) / 1e9)
            if key in filled and filled[key] >= score:
                continue
            filled[key] = score
            m = ctx.metrics.setdefault(key, Metrics(owner=op.name))
            with m._lock:
                m.values.clear()
                m.values.update(vals)
        if rep.get("events"):
            threads = {int(k): v for k, v in
                       (rep.get("threads") or {}).items()}
            events[wid] = (rep["events"], threads,
                           rep.get("tag") or f"worker {wid}")
    if events:
        ctx.cache["cluster_worker_events"] = events


class _StageTask:
    __slots__ = ("sid", "deps", "status", "worker", "gen", "retries",
                 "bytes", "producer", "ready_ts", "started_ts")

    def __init__(self, sid: int, deps: Set[int]):
        self.sid = sid
        self.deps = deps
        self.status = _PENDING
        self.worker: Optional[str] = None
        self.gen = 0
        self.retries = 0
        self.bytes = 0
        self.producer: Optional[str] = None
        self.ready_ts: Optional[float] = None   # first observed ready
        self.started_ts: Optional[float] = None  # last dispatch time


class _WorkerInfo:
    __slots__ = ("wid", "last_seen", "alive", "completed", "pressure",
                 "draining", "demoted", "last_beat", "beat_ms",
                 "stage_wall_ms", "incarnation")

    def __init__(self, wid: str, now: float):
        self.wid = wid
        self.last_seen = now
        self.alive = True
        self.completed = 0
        # Latest memory-pressure score off this worker's CBEAT
        # telemetry piggyback (0.0 until it reports one): the signal
        # shed-aware placement demotes loaded workers on.
        self.pressure = 0.0
        # Self-healing plane (ISSUE 20): draining workers get no new
        # stage tasks and retire on their next idle CPOLL; demoted
        # (straggler) workers drop below steal-delay preference like
        # pressure-shed ones until the supervisor promotes them back.
        self.draining = False
        self.demoted = False
        # Straggler evidence the supervisor's outlier detector pulls
        # through CSTATS: recent CBEAT inter-arrival gaps and per-stage
        # dispatch->commit walls, both in ms (bounded ring).
        self.last_beat: Optional[float] = None
        self.beat_ms: List[float] = []
        self.stage_wall_ms: List[float] = []
        # Per-PROCESS token off CREG: a re-register under the same wid
        # with a DIFFERENT token is proof the previous incarnation died
        # (supervisor restart racing the heartbeat sweep) — its RUNNING
        # stages requeue immediately instead of orphaning.
        self.incarnation: Optional[str] = None

    def sample(self, ring: List[float], value_ms: float,
               cap: int = 32) -> None:
        ring.append(round(value_ms, 3))
        if len(ring) > cap:
            del ring[:len(ring) - cap]


class QueryRun:
    """One query's dispatch state: the pickled plan, its stage tasks,
    and the driver-side wait/recovery surface the planner drives."""

    def __init__(self, co: "ClusterCoordinator", qid: int, conf,
                 tasks: Dict[int, _StageTask], driver_tags):
        self.co = co
        self.qid = qid
        self.qdir = os.path.join(co.base_dir, f"q{qid}")
        self.pkl_path = os.path.join(self.qdir, "query.pkl")
        self.tasks = tasks
        self._driver_tags = driver_tags
        self.min_workers = max(int(conf.get(C.CLUSTER_MIN_WORKERS)), 1)
        self.poll_ms = max(int(conf.get(C.CLUSTER_POLL_MS)), 1)
        self.hb_timeout_ms = max(
            int(conf.get(C.CLUSTER_HEARTBEAT_TIMEOUT_MS)), 1)
        self.dispatch_timeout_ms = max(
            int(conf.get(C.CLUSTER_DISPATCH_TIMEOUT_MS)), 1)
        self.max_retries = max(int(conf.get(C.CLUSTER_MAX_TASK_RETRIES)),
                               0)
        self.steal_delay_s = max(
            int(conf.get(C.CLUSTER_STEAL_DELAY_MS)), 0) / 1000.0
        # Memory-pressure shedding (scheduler.pressure.*): a worker at
        # or past shedScore is demoted below steal-delay preference so
        # it sheds NEW stages instead of spilling under more of them.
        self.pressure_enabled = bool(conf.get(C.PRESSURE_ENABLED))
        self.shed_score = float(conf.get(C.PRESSURE_SHED_SCORE))
        self.error: Optional[BaseException] = None
        self._ctx = None
        self._root = None       # driver's unpickled plan root (submit)
        self._trace_qid = 0
        self.finished = False
        # Stage-output store: hostfile spool (default) or objectstore
        # (kind, endpoint, key prefix) — set by submit()/replay.
        self.store_kind = "hostfile"
        self.store_endpoint = ""
        self.store_prefix = ""
        self.plan_fp = ""                 # sha256 of the plan pickle
        self._bcast_tags: Dict[int, int] = {}
        self._bcast_deps: Dict[int, List[int]] = {}
        # Counted recomputes (requeues that bumped stageRecomputes):
        # surfaced through CWAIT so a REMOTE driver can mirror them
        # into its own fault counters.
        self.recomputes = 0
        # Remote submissions write the plan pickle AFTER the qid comes
        # back; submit_remote clears this and dispatch holds until the
        # file lands (checked once in _pick_locked).
        self._pkl_ready = True
        # Latest per-worker CDONE stats blob (node stats + trace ring).
        # Each report is cumulative for this query on that worker, so
        # last-writer-wins per wid is the correct merge discipline.
        self.worker_reports: Dict[str, dict] = {}
        # QoS class rank (parallel/qos/): CPOLL offers ready stages of
        # higher-priority queries first, BEFORE byte-score locality. The
        # default class ("batch", rank 1) keeps the (rank, qid) sort
        # identical to the plain qid sort when no query sets a class.
        from spark_rapids_tpu.parallel import qos as Q
        try:
            cls = Q.resolve_class(str(conf.get(C.QOS_PRIORITY_CLASS)))
        except ValueError:
            cls = Q.DEFAULT_CLASS
        self.qos_rank = Q.CLASS_RANK[cls]

    # -- driver side (planner hooks) -----------------------------------------
    def install(self, ctx) -> None:
        """Mark ``ctx`` as this query's cluster execution context: the
        exchanges of the plan resolve their cross-process tags and
        fetch-only roles through the installed ClusterExecInfo."""
        self._ctx = ctx
        self._trace_qid = ctx.cache.get("trace_query", 0)
        ctx.cache["cluster"] = ClusterExecInfo(
            self.qdir, f"drv{os.getpid()}", self._driver_tags,
            local_sid=None, store_kind=self.store_kind,
            store_endpoint=self.store_endpoint,
            store_prefix=self.store_prefix,
            bcast_tags=self._bcast_tags, bcast_deps=self._bcast_deps,
            plan_fp=self.plan_fp,
            gen_source=lambda: {sid: t.gen
                                for sid, t in self.tasks.items()})

    def _metrics(self):
        from spark_rapids_tpu.ops.base import query_metrics_entry
        return query_metrics_entry(self._ctx, "Cluster")

    def run(self, ctx) -> None:
        """Dispatch-and-wait barrier: returns once every stage task of
        this query is committed to the spool (requeueing through worker
        deaths and reported shard losses on the way), so the local
        collect that follows only ever FETCHES remote stage outputs."""
        from spark_rapids_tpu import faults, monitoring
        t0 = time.monotonic()
        deadline = t0 + self.dispatch_timeout_ms / 1000.0
        while True:
            faults.check_cancelled()
            with self.co._lock:
                self.co._check_workers_locked()
                err = self.error
                done = all(t.status == _DONE
                           for t in self.tasks.values())
            if err is not None:
                raise err
            if done:
                break
            if time.monotonic() > deadline:
                with self.co._lock:
                    depth = sum(1 for t in self.tasks.values()
                                if t.status != _DONE)
                    hint = self.co._dispatch_retry_hint_locked(depth)
                raise dispatch_timeout_error(
                    f"UNAVAILABLE: cluster dispatch of query {self.qid} "
                    f"incomplete after {self.dispatch_timeout_ms}ms "
                    f"({self._progress()})",
                    queue_depth=depth, retry_after_ms=hint)
            time.sleep(self.poll_ms / 1000.0)
        m = self._metrics()
        m.add("dispatchWaitMs", (time.monotonic() - t0) * 1000.0)
        with self.co._lock:
            workers = {t.producer for t in self.tasks.values()
                       if t.producer}
        with m._lock:
            m.values["workersUsed"] = max(
                m.values.get("workersUsed", 0), len(workers))
        monitoring.instant(
            "cluster-dispatch-complete", "cluster",
            args={"query": self.qid, "stages": len(self.tasks),
                  "workers": len(workers)}, qid=self._trace_qid)
        self._merge_worker_reports()

    def _merge_worker_reports(self) -> None:
        ctx, root = self._ctx, self._root
        if ctx is None or root is None:
            return
        with self.co._lock:
            reports = dict(self.worker_reports)
        merge_worker_reports(ctx, root, reports)

    def _progress(self) -> str:
        by = {}
        for t in self.tasks.values():
            by[t.status] = by.get(t.status, 0) + 1
        return ", ".join(f"{k}={v}" for k, v in sorted(by.items()))

    def recompute(self, sid: int) -> None:
        """Planner rung-1 hook: the driver lost stage ``sid``'s durable
        output post-dispatch (ShardLostError / persistent CRC failure
        on the reduce fetch). Clear the stage's spool and requeue it;
        the planner's continue re-enters :meth:`run`, which waits for
        the rewritten manifest."""
        with self.co._lock:
            t = self.tasks.get(sid)
            if t is None:
                return
            self._requeue_locked(t, "driver-observed shard loss")

    def reset(self) -> None:
        """Planner rung-3 hook (fresh-context retry): every stage task
        redispatches from a clean store."""
        with self.co._lock:
            for t in self.tasks.values():
                t.gen += 1
                t.status = _PENDING
                t.worker = None
                t.ready_ts = None
            shutil.rmtree(self.qdir, ignore_errors=True)
            os.makedirs(self.qdir, exist_ok=True)
            if getattr(self, "_blob", None) is not None:
                self.co._write_plan(self)
        if self.store_kind == "objectstore" and self.store_prefix:
            self.co._objectstore_delete(self.store_endpoint,
                                        self.store_prefix + "/")
        self.co._jlog({"t": "reset", "qid": self.qid})

    def finish(self) -> None:
        """Query end (success or failure): retire the run and remove
        the query's store state (spool tree and/or objectstore prefix)
        — the coordinator owns this cleanup, so worker/driver context
        teardowns never race each other over live shard files (their
        sessions are keep_on_close)."""
        with self.co._lock:
            self.finished = True
            self.co.queries.pop(self.qid, None)
            none_active = not self.co.queries
            wids = self.co._alive_wids_locked()
            draining = [w.wid for w in self.co.workers.values()
                        if w.alive and w.draining]
        shutil.rmtree(self.qdir, ignore_errors=True)
        if not self.pkl_path.startswith(self.qdir + os.sep):
            try:                 # remote submissions park the plan
                os.remove(self.pkl_path)    # under <dir>/plans/
            except OSError:
                pass
        if self.store_kind == "objectstore" and self.store_prefix:
            self.co._objectstore_delete(self.store_endpoint,
                                        self.store_prefix + "/")
        self.co._jlog({"t": "finish", "qid": self.qid})
        if none_active and self.co.journal is not None:
            # Compaction: with no in-flight query, only the live
            # membership (plus the replay audit trail — the evidence
            # that past restarts recovered) is worth keeping —
            # atomically shrink the journal instead of growing it
            # forever.
            replays = [r for r in self.co.journal.records()
                       if r.get("t") == "replay"]
            self.co.journal.rewrite(
                replays[-8:] +
                [{"t": "reg", "wid": w, "ts": time.time()}
                 for w in wids] +
                [{"t": "drain", "wid": w} for w in draining])

    # -- coordinator side (lock held) ----------------------------------------
    def _clear_stage_store_locked(self, sid: int) -> None:
        """Drop one stage's durable output ahead of its recompute (the
        rewritten generation must never merge with partial leftovers)."""
        shutil.rmtree(os.path.join(self.qdir, f"s{sid}"),
                      ignore_errors=True)
        if self.store_kind == "objectstore" and self.store_prefix:
            self.co._objectstore_delete(
                self.store_endpoint, f"{self.store_prefix}/s{sid}/")

    def _requeue_locked(self, t: _StageTask, why: str,
                        count_recompute: bool = True) -> None:
        from spark_rapids_tpu import faults, monitoring
        t.gen += 1
        t.status = _PENDING
        t.worker = None
        t.ready_ts = None
        t.retries += 1
        self._clear_stage_store_locked(t.sid)
        if count_recompute:
            self.recomputes += 1
        self.co._jlog({"t": "requeue", "qid": self.qid, "sid": t.sid,
                       "gen": t.gen, "retries": t.retries,
                       "counted": count_recompute, "why": why})
        if t.retries > self.max_retries:
            self.error = ClusterDispatchError(
                f"stage task s{t.sid} of query {self.qid} exhausted its "
                f"{self.max_retries} requeue(s): {why}")
            return
        if count_recompute:
            faults.record("stageRecomputes")
            faults.record(f"stageRecomputes.stage{t.sid}")
            if self._ctx is not None:
                self._metrics().add("tasksRequeued", 1)
        monitoring.instant("cluster-task-requeue", "recovery",
                           args={"query": self.qid, "stage": t.sid,
                                 "why": why}, qid=self._trace_qid)
        _LOG.warning("cluster: requeueing stage s%d of query %d "
                     "(gen %d): %s", t.sid, self.qid, t.gen, why)

    def _ready_locked(self) -> List[_StageTask]:
        now = time.monotonic()
        out = []
        for t in self.tasks.values():
            if t.status == _PENDING and all(
                    self.tasks[d].status == _DONE
                    for d in t.deps if d in self.tasks):
                if t.ready_ts is None:
                    t.ready_ts = now    # starts the steal-delay clock
                out.append(t)
        return out

    def _pick_locked(self, wid: str) -> Optional[Tuple[str, _StageTask]]:
        """The stage task worker ``wid`` should run next: the ready
        task it holds the most input-shard bytes for (locality), ties
        to the task whose rendezvous-hash owner this worker is
        (stable placement), then to the smallest stage id
        (determinism). None = nothing ready or the minWorkers dispatch
        gate is closed.

        The affinity tier matters for repeated queries: score ties
        (every leaf stage — no input shards yet) would otherwise land
        on whichever worker polls first, scattering the same stage
        onto a different process each query and re-paying its
        per-process kernel traces. Highest-random-weight hashing over
        the live worker set keeps the split deterministic across
        queries AND work-conserving — a worker whose owned tasks are
        all taken still picks up anything ready.

        Delay scheduling closes the remaining race: a ready task is
        reserved for its *preferred* worker — the alive worker with
        the best (score, owned) pair — for ``stealDelayMs``. Without
        the reservation a momentarily busy worker loses its stages to
        whichever idle process polls first, so the stage→worker map
        flips between otherwise identical queries and the thief pays
        a fresh per-process kernel trace. The delay keeps stealing
        (and so work conservation) for genuinely stuck owners while
        making hot-path placement deterministic."""
        if self.error is not None or self.finished:
            return None
        if not self._pkl_ready:
            # Remote submission: the driver writes the plan pickle just
            # after CSUB returns — hold dispatch until it lands so a
            # fast-polling worker never opens a missing file.
            if not os.path.exists(self.pkl_path):
                return None
            self._pkl_ready = True
        # Draining workers are not placement targets: they fall out of
        # the dispatch gate, the locality ranking AND rendezvous-hash
        # ownership, so their remaining work commits and they retire.
        alive = self.co._placeable_wids_locked()
        if wid not in alive or len(alive) < self.min_workers:
            return None
        ready = self._ready_locked()
        if not ready:
            return None

        def score(t: _StageTask, w: str) -> int:
            return sum(self.tasks[d].bytes for d in t.deps
                       if d in self.tasks
                       and self.tasks[d].producer == w)

        def owned(t: _StageTask, w: str) -> int:
            return 1 if _hrw_owner(alive, t.sid) == w else 0

        def unpressured(w: str) -> int:
            """Shed-aware demotion tier (scheduler.pressure.*): an
            unpressured worker outranks a pressured one for BOTH the
            steal-delay reservation and the pick itself, so a loaded
            worker sheds new stages to its peers instead of spilling
            under them. All-pressured (or the gate off) collapses the
            tier to a constant — placement is exactly the old
            (locality, affinity) order. Supervisor straggler demotion
            (CDEMO) rides the SAME tier: a demoted worker only gets a
            stage when every healthy peer is busy past the
            reservation window."""
            info = self.co.workers.get(w)
            if info is not None and info.demoted:
                return 0
            if not self.pressure_enabled:
                return 1
            if info is None or info.pressure < self.shed_score:
                return 1
            return 0

        def rank(t: _StageTask, w: str) -> Tuple[int, int, int]:
            return (unpressured(w), score(t, w), owned(t, w))

        now = time.monotonic()

        def eligible(t: _StageTask) -> bool:
            if self.steal_delay_s <= 0 or \
                    now - (t.ready_ts or now) >= self.steal_delay_s:
                return True     # reservation expired: anyone may take it
            mine = rank(t, wid)
            return all(rank(t, w) <= mine for w in alive if w != wid)

        ready = [t for t in ready if eligible(t)]
        if not ready:
            if self.pressure_enabled and not unpressured(wid):
                # This poll was shed purely by pressure demotion (a
                # less-loaded peer holds the reservation): visible in
                # telemetry + the event log, like every other rung.
                from spark_rapids_tpu import monitoring
                from spark_rapids_tpu.monitoring import telemetry
                telemetry.inc("srt_pressure_sheds")
                monitoring.instant(
                    "pressure-shed", "recovery",
                    args={"worker": wid,
                          "pressure": self.co.workers[wid].pressure})
            return None         # reserved for others — poll again shortly
        best = max(ready, key=lambda t: rank(t, wid) + (-t.sid,))
        best.status = _RUNNING
        best.worker = wid
        best.started_ts = now
        depgens = ",".join(f"{d}:{self.tasks[d].gen}"
                           for d in sorted(best.deps)) or "-"
        line = (f"CTASK {self.qid} {best.sid} {best.gen} {depgens} "
                f"{base64.b64encode(self.pkl_path.encode()).decode()}\n")
        self.co._jlog({"t": "dispatch", "qid": self.qid,
                       "sid": best.sid, "gen": best.gen, "wid": wid})
        return line, best

    def _on_done_locked(self, wid: str, sid: int, gen: int,
                        nbytes: int, report: Optional[dict] = None) -> None:
        t = self.tasks.get(sid)
        if t is None or t.gen != gen or t.status != _RUNNING or \
                t.worker != wid:
            return          # stale generation (zombie worker) — ignored
        if report is not None:
            self.worker_reports[wid] = report
        t.status = _DONE
        t.bytes = nbytes
        t.producer = wid
        self.co._jlog({"t": "done", "qid": self.qid, "sid": sid,
                       "gen": gen, "wid": wid, "bytes": nbytes})
        w = self.co.workers.get(wid)
        if w is not None:
            w.completed += 1
            if t.started_ts is not None:
                # Dispatch->commit wall sample: the supervisor's
                # straggler detector compares these across the fleet.
                w.sample(w.stage_wall_ms,
                         (time.monotonic() - t.started_ts) * 1000.0)
        if self._ctx is not None:
            self._metrics().add("stagesCompleted", 1)

    def _on_fail_locked(self, wid: str, sid: int, gen: int,
                        lost_sid: Optional[int], msg: str) -> None:
        t = self.tasks.get(sid)
        if t is None or t.gen != gen or t.worker != wid or \
                t.status != _RUNNING:
            return
        if lost_sid is not None and lost_sid in self.tasks:
            lost = self.tasks[lost_sid]
            if lost.status == _DONE:
                self._requeue_locked(lost,
                                     f"shard loss reported by {wid}")
        # The failed task itself retries behind the recomputed dep; a
        # loss-free failure (a real stage error) still retries — a
        # persistent bug exhausts the budget and surfaces the message.
        self._requeue_locked(t, f"{wid} reported: {msg}",
                             count_recompute=lost_sid is not None)


class ClusterServer(RendezvousServer):
    """The rendezvous server + the cluster control-plane verbs: workers
    literally 'register with the rendezvous' (ISSUE wording) — one
    socket, one wire grammar, shard-commit announcements and stage-task
    scheduling side by side."""

    def __init__(self, co: "ClusterCoordinator", host: str, port: int):
        self._co = co
        super().__init__(host, port)

    def dispatch_extra(self, parts: List[str]) -> Optional[bytes]:
        return self._co.dispatch(parts)


class ClusterCoordinator:
    """Driver-side membership + scheduling authority (one per driver
    process in practice; instantiable standalone for tests/bench)."""

    def __init__(self, conf):
        self._lock = threading.Lock()
        self.workers: Dict[str, _WorkerInfo] = {}
        self.queries: Dict[int, QueryRun] = {}
        # Cleanly retired wids (CDRAIN -> CRETIRE) with the deadline
        # until which their stray daemon-thread heartbeats are ignored;
        # an explicit CREG re-admits the id immediately.
        self._retired: Dict[str, float] = {}
        self._next_qid = 1
        self.base_dir = str(conf.get(C.CLUSTER_DIR) or "") or \
            os.path.join(tempfile.gettempdir(),
                         f"srt_cluster_{os.getpid()}")
        os.makedirs(self.base_dir, exist_ok=True)
        # Store namespace for objectstore-backed queries: distinct
        # clusters sharing one store stay out of each other's keys.
        self.ns = os.path.basename(os.path.normpath(self.base_dir))
        self.hb_timeout_ms = max(
            int(conf.get(C.CLUSTER_HEARTBEAT_TIMEOUT_MS)), 1)
        self._backends: Dict[str, object] = {}
        # Write-ahead journal + replay BEFORE the server accepts
        # connections: a restarted coordinator re-adopts committed
        # stage outputs and re-learns its membership from the journal,
        # so reconnecting workers and a waiting driver resume instead
        # of restarting from zero.
        self.journal = None
        self.journal_replay_ms = 0.0
        if bool(conf.get(C.CLUSTER_JOURNAL_ENABLED)):
            from spark_rapids_tpu.parallel.cluster.journal import Journal
            self.journal = Journal(
                os.path.join(self.base_dir, "journal", "journal.jsonl"),
                fsync=bool(conf.get(C.CLUSTER_JOURNAL_FSYNC)))
            t0 = time.monotonic()
            self._replay()
            self.journal_replay_ms = (time.monotonic() - t0) * 1000.0
        spec = str(conf.get(C.CLUSTER_COORDINATOR) or "")
        if spec:
            host, _, port = spec.rpartition(":")
            self.server = ClusterServer(self, host or "127.0.0.1",
                                        int(port))
        else:
            self.server = ClusterServer(self, "127.0.0.1", 0)
        self.addr = self.server.addr

    # -- journal / failover ---------------------------------------------------
    def _jlog(self, rec: dict) -> None:
        if self.journal is not None:
            self.journal.append(rec)

    def _backend(self, endpoint: str):
        """Process-cached objectstore backend for coordinator-side
        manifest checks and store cleanup."""
        b = self._backends.get(endpoint)
        if b is None:
            from spark_rapids_tpu.parallel.transport.objectstore import \
                make_backend
            b = self._backends[endpoint] = make_backend(endpoint,
                                                        timeout_s=2.0)
        return b

    def _objectstore_delete(self, endpoint: str, prefix: str) -> None:
        """Best-effort key-prefix cleanup: a store outage during
        cleanup degrades to garbage, never to a failed query."""
        try:
            b = self._backend(endpoint)
            for k in b.list_keys(prefix):
                try:
                    b.delete(k)
                except Exception:
                    pass
        except Exception as e:
            _LOG.warning("objectstore cleanup of %s skipped: %s",
                         prefix, e)

    def _stage_committed(self, q: QueryRun, sid: int) -> bool:
        """Is stage ``sid``'s durable output still published (a valid
        committed manifest on the query's store)? The replay path uses
        this to RE-ADOPT outputs that survived the coordinator crash
        instead of recomputing them."""
        from spark_rapids_tpu.parallel.transport.hostfile import \
            valid_manifest
        if q.store_kind == "objectstore":
            try:
                b = self._backend(q.store_endpoint)
                m = json.loads(b.get(
                    f"{q.store_prefix}/s{sid}/exchange.manifest.json"
                ).decode("utf-8"))
                return valid_manifest(m)
            except Exception:
                return False
        path = os.path.join(q.qdir, f"s{sid}", "exchange.manifest.json")
        try:
            with open(path, encoding="utf-8") as f:
                return valid_manifest(json.load(f))
        except (OSError, ValueError):
            return False

    def _replay(self) -> None:
        """Rebuild membership and per-query stage state from the
        journal (coordinator failover): committed stage outputs whose
        manifests still exist are re-adopted as DONE; tasks that were
        in flight are restored RUNNING so the executing worker's
        retried CDONE lands (its generation still matches) — if that
        worker is truly gone, the heartbeat sweep requeues the task,
        which is the ≤1-recompute bound the failover contract
        promises."""
        from spark_rapids_tpu.parallel.cluster import journal as J
        recs = self.journal.records()
        if not recs:
            return
        state = J.replay_state(recs)
        now = time.monotonic()
        for wid in state["workers"]:
            self.workers[wid] = _WorkerInfo(wid, now)
        for wid in state.get("draining", ()):
            w = self.workers.get(wid)
            if w is not None:
                w.draining = True
        recovered: List[int] = []
        for qid in sorted(state["queries"]):
            qs = state["queries"][qid]
            sub = qs["submit"]
            try:
                conf = C.TpuConf(dict(sub.get("conf") or {}))
                q = QueryRun(self, qid, conf, {}, {})
                store = sub.get("store") or ["hostfile", "", ""]
                q.store_kind, q.store_endpoint, q.store_prefix = \
                    str(store[0]), str(store[1]), str(store[2])
                q.plan_fp = str(sub.get("fp") or "")
                if sub.get("pkl"):
                    q.pkl_path = str(sub["pkl"])
                # Re-verify the plan file on first dispatch: the crash
                # may have landed between admission and the plan write.
                q._pkl_ready = False
                deps = {int(k): {int(x) for x in v} for k, v in
                        (sub.get("deps") or {}).items()}
                q.tasks = {int(s): _StageTask(int(s),
                                              deps.get(int(s), set()))
                           for s in sub["stages"]}
                q.recomputes = int(qs.get("recomputes", 0))
                for sid, ts in qs["tasks"].items():
                    t = q.tasks.get(int(sid))
                    if t is None:
                        continue
                    t.gen = int(ts["gen"])
                    t.retries = int(ts["retries"])
                    if ts["status"] == "done":
                        if self._stage_committed(q, t.sid):
                            t.status = _DONE
                            t.bytes = int(ts["bytes"])
                            t.producer = ts.get("wid")
                        else:
                            # The journaled output did not survive the
                            # crash: one recompute, counted.
                            q._requeue_locked(
                                t, "journal replay: committed manifest"
                                   " missing")
                    elif ts["status"] == "running":
                        t.status = _RUNNING
                        t.worker = ts.get("wid")
            except Exception:
                _LOG.warning("journal replay: dropping unreadable "
                             "query %s", qid, exc_info=True)
                continue
            self.queries[qid] = q
            recovered.append(qid)
        self._next_qid = max(self._next_qid, int(state["next_qid"]))
        from spark_rapids_tpu import monitoring
        monitoring.instant(
            "coordinator-replay", "recovery",
            args={"queries": recovered, "workers": state["workers"]})
        self._jlog({"t": "replay", "queries": recovered,
                    "workers": state["workers"]})
        if recovered or state["workers"]:
            _LOG.warning("cluster: journal replay recovered %d "
                         "worker(s), %d in-flight quer%s",
                         len(state["workers"]), len(recovered),
                         "y" if len(recovered) == 1 else "ies")

    # -- membership/scheduling (socket threads) ------------------------------
    def _alive_count_locked(self) -> int:
        return sum(1 for w in self.workers.values() if w.alive)

    def _alive_wids_locked(self) -> List[str]:
        return [w.wid for w in self.workers.values() if w.alive]

    def _placeable_wids_locked(self) -> List[str]:
        """Workers new stage tasks may land on: alive and NOT
        draining. Draining workers keep finishing their in-flight
        stages but drop out of the dispatch gate, locality ranking and
        rendezvous-hash ownership."""
        return [w.wid for w in self.workers.values()
                if w.alive and not w.draining]

    def _dispatch_retry_hint_locked(self, pending: int) -> float:
        """retry_after_ms for a dispatch-timeout rejection: the
        not-yet-done stage count drained at the fleet's observed mean
        stage wall (250ms prior before any stage has committed),
        spread over the live workers."""
        walls = [v for w in self.workers.values()
                 for v in w.stage_wall_ms]
        base = sum(walls) / len(walls) if walls else 250.0
        alive = max(self._alive_count_locked(), 1)
        return round(max(250.0, base * max(pending, 1) / alive), 1)

    def _inflight_locked(self, wid: str) -> int:
        return sum(1 for q in self.queries.values()
                   for t in q.tasks.values()
                   if t.status == _RUNNING and t.worker == wid)

    def _retire_locked(self, w: _WorkerInfo) -> None:
        """Drain completion: drop the worker from membership as a
        CLEAN retirement — no death counter, no requeue (it has no
        RUNNING task by construction) — and shield its id against the
        stray heartbeat race."""
        from spark_rapids_tpu import faults, monitoring
        self.workers.pop(w.wid, None)
        self._retired[w.wid] = time.monotonic() + _RETIRE_GRACE_S
        faults.record("clusterWorkerRetirements")
        monitoring.instant("worker-retired", "cluster",
                           args={"worker": w.wid,
                                 "completed": w.completed})
        self._jlog({"t": "retire", "wid": w.wid})
        _LOG.info("cluster: worker %s drained and retired cleanly "
                  "(%d stage task(s) completed)", w.wid, w.completed)

    def _touch_locked(self, wid: str) -> Optional[_WorkerInfo]:
        now = time.monotonic()
        exp = self._retired.get(wid)
        if exp is not None:
            if now < exp:
                # A retired worker's daemon beat thread may land one
                # last CBEAT after its CRETIRE: swallowing it keeps the
                # id from being resurrected as a ghost member.
                return None
            del self._retired[wid]
        w = self.workers.get(wid)
        if w is None or not w.alive:
            from spark_rapids_tpu import monitoring
            fresh = w is None
            w = self.workers[wid] = _WorkerInfo(wid, now)
            monitoring.instant("worker-join", "cluster",
                               args={"worker": wid, "rejoin": not fresh})
            self._jlog({"t": "reg", "wid": wid})
            _LOG.info("cluster: worker %s %sjoined", wid,
                      "" if fresh else "re")
        w.last_seen = now
        return w

    def _check_workers_locked(self) -> None:
        """Heartbeat monitor (driven from QueryRun.run's wait loop): a
        silent worker is declared dead and every RUNNING task it held —
        across all active queries — requeues onto survivors."""
        now = time.monotonic()
        for w in self.workers.values():
            if not w.alive or \
                    (now - w.last_seen) * 1000.0 < self.hb_timeout_ms:
                continue
            self._declare_dead_locked(
                w, f"heartbeat silent for >{self.hb_timeout_ms}ms")

    def _declare_dead_locked(self, w: _WorkerInfo, why: str) -> None:
        """Declare one worker dead and requeue every RUNNING task it
        held across all active queries. Shared by the heartbeat sweep
        and the CREG incarnation check."""
        w.alive = False
        from spark_rapids_tpu import faults, monitoring
        faults.record("clusterWorkerDeaths")
        monitoring.instant("worker-death", "recovery",
                           args={"worker": w.wid})
        _LOG.warning("cluster: worker %s %s — declared dead; "
                     "requeueing its tasks", w.wid, why)
        for q in self.queries.values():
            for t in q.tasks.values():
                if t.status == _RUNNING and t.worker == w.wid:
                    if q._ctx is not None:
                        q._metrics().add("workerDeaths", 1)
                    q._requeue_locked(
                        t, f"worker {w.wid} died mid-stage")

    def dispatch(self, parts: List[str]) -> Optional[bytes]:
        try:
            return self._dispatch(parts)
        except Exception:                      # a torn request must not
            _LOG.exception("cluster verb failed: %r", parts)
            return b"ERR\n"                    # kill the handler thread

    def _dispatch(self, parts: List[str]) -> Optional[bytes]:
        cmd = parts[0].upper()
        if cmd == "CREG" and len(parts) in (2, 3):
            wid = parts[1]
            token = parts[2] if len(parts) == 3 else None
            with self._lock:
                # An explicit re-register always re-admits the id —
                # retirement only shields against STRAY beats.
                self._retired.pop(wid, None)
                w = self.workers.get(wid)
                if (token is not None and w is not None and w.alive
                        and w.incarnation is not None
                        and w.incarnation != token):
                    # Same wid, different process: the supervisor's
                    # replacement registered before the heartbeat sweep
                    # noticed the old incarnation's silence. Without
                    # this the dead process's RUNNING stages would stay
                    # assigned to a wid that keeps beating — a
                    # permanent dispatch stall.
                    self._declare_dead_locked(
                        w, "re-registered under a new incarnation "
                           f"({w.incarnation} -> {token})")
                w = self._touch_locked(wid)
                if token is not None and w is not None:
                    w.incarnation = token
            return b"OK\n"
        if cmd == "CBEAT" and len(parts) in (2, 3):
            with self._lock:
                w = self._touch_locked(parts[1])
                if w is not None:
                    # Heartbeat inter-arrival ring: the supervisor's
                    # straggler detector reads these through CSTATS.
                    now = time.monotonic()
                    if w.last_beat is not None:
                        w.sample(w.beat_ms,
                                 (now - w.last_beat) * 1000.0)
                    w.last_beat = now
            if len(parts) == 3:
                # Telemetry piggyback (monitoring/telemetry.py): the
                # worker's flattened registry feeds the driver's fleet
                # view — every series re-renders with worker=<wid>.
                # Old-format beats (2 parts) stay valid forever.
                try:
                    from spark_rapids_tpu.monitoring import telemetry
                    blob = json.loads(
                        base64.b64decode(parts[2]).decode())
                    telemetry.fleet_update(parts[1], blob)
                    # Memory-pressure piggyback: the worker's catalog
                    # watermark score rides the same heartbeat; CPOLL
                    # placement demotes pressured workers below
                    # steal-delay preference (_pick_locked).
                    score = blob.get("series", {}).get(
                        "srt_pressure_score|")
                    if score is not None:
                        with self._lock:
                            w = self.workers.get(parts[1])
                            if w is not None:
                                w.pressure = float(score)
                except Exception:
                    _LOG.warning("cluster: bad CBEAT telemetry blob "
                                 "from %s", parts[1], exc_info=True)
            return b"OK\n"
        if cmd == "CPOLL" and len(parts) == 3:
            wid, known = parts[1], parts[2]
            with self._lock:
                w = self._touch_locked(wid)
                if w is None:
                    # Still inside the retire grace window: repeat the
                    # retire answer (idempotent) instead of ghosting.
                    return b"CRETIRE\n"
                if w.draining:
                    if self._inflight_locked(wid) == 0:
                        self._retire_locked(w)
                        return b"CRETIRE\n"
                    return b"CIDLE -\n"   # finish in-flight, no new work
                stale = [q for q in known.split(",")
                         if q and q != "-"
                         and int(q) not in self.queries]
                # Priority classes first (QoS rank, interactive < batch
                # < background), stage-id/locality order within a query
                # unchanged; qid tiebreak keeps the scan deterministic.
                for qid in sorted(self.queries,
                                  key=lambda q:
                                  (self.queries[q].qos_rank, q)):
                    picked = self.queries[qid]._pick_locked(wid)
                    if picked is not None:
                        line, _ = picked
                        return line.encode()
            return f"CIDLE {','.join(stale) or '-'}\n".encode()
        if cmd == "CDONE" and len(parts) in (6, 7):
            _, wid, qid, sid, gen, nbytes = parts[:6]
            report = None
            if len(parts) == 7:
                # Per-node observed stats + trace ring piggyback (the
                # cluster explain_analyze / merged-Perfetto plumbing).
                # Old-format CDONEs (6 parts) stay valid forever.
                try:
                    report = json.loads(
                        base64.b64decode(parts[6]).decode())
                except Exception:
                    _LOG.warning("cluster: bad CDONE report blob from "
                                 "%s", wid, exc_info=True)
            with self._lock:
                self._touch_locked(wid)
                q = self.queries.get(int(qid))
                if q is not None:
                    q._on_done_locked(wid, int(sid), int(gen),
                                      int(nbytes), report=report)
            return b"OK\n"
        if cmd == "CFAIL" and len(parts) == 7:
            _, wid, qid, sid, gen, lost, b64 = parts
            msg = base64.b64decode(b64).decode("utf-8", "replace")
            with self._lock:
                self._touch_locked(wid)
                q = self.queries.get(int(qid))
                if q is not None:
                    q._on_fail_locked(
                        wid, int(sid), int(gen),
                        None if lost == "-" else int(lost), msg)
            return b"OK\n"
        if cmd == "CDRAIN" and len(parts) == 2:
            # Clean scale-down / self-retirement (ISSUE 20): stop
            # dispatching to the worker; its in-flight stages commit,
            # then its next idle CPOLL answers CRETIRE. Idempotent,
            # and a no-op for unknown or already-retired ids.
            wid = parts[1]
            with self._lock:
                w = self.workers.get(wid)
                if w is not None and w.alive and not w.draining:
                    w.draining = True
                    from spark_rapids_tpu import monitoring
                    monitoring.instant(
                        "worker-drain", "cluster",
                        args={"worker": wid,
                              "inflight": self._inflight_locked(wid)})
                    self._jlog({"t": "drain", "wid": wid})
                    _LOG.info("cluster: worker %s draining (%d stage "
                              "task(s) in flight)", wid,
                              self._inflight_locked(wid))
            return b"OK\n"
        if cmd == "CDEMO" and len(parts) == 3:
            # Straggler demotion toggle (supervisor): a demoted worker
            # ranks below every non-demoted peer in CPOLL placement —
            # the same tier pressure shedding uses — until promoted.
            wid, flag = parts[1], parts[2] not in ("0", "false")
            with self._lock:
                w = self.workers.get(wid)
                changed = w is not None and w.demoted != flag
                if changed:
                    w.demoted = flag
            if changed:
                from spark_rapids_tpu import monitoring
                monitoring.instant(
                    "worker-straggler" if flag else "worker-promoted",
                    "cluster", args={"worker": wid})
                _LOG.warning("cluster: worker %s %s steal-delay "
                             "preference (straggler %s)", wid,
                             "demoted below" if flag else
                             "promoted back into",
                             "demotion" if flag else "recovery")
            return b"OK\n"
        if cmd == "CSTATS" and len(parts) == 1:
            blob = base64.b64encode(
                json.dumps(self.stats()).encode()).decode()
            return f"OK {blob}\n".encode()
        # -- remote-driver verbs (cluster.coordinator.remote) ----------------
        if cmd == "CSUB" and len(parts) == 2:
            spec = json.loads(base64.b64decode(parts[1]).decode())
            qid, resp = self.submit_remote(spec)
            blob = base64.b64encode(json.dumps(resp).encode()).decode()
            return f"OK {qid} {blob}\n".encode()
        if cmd == "CWAIT" and len(parts) == 2:
            with self._lock:
                self._check_workers_locked()
                q = self.queries.get(int(parts[1]))
                if q is None:
                    payload = {"state": "unknown"}
                else:
                    if q.error is not None:
                        state = "error"
                    elif all(t.status == _DONE
                             for t in q.tasks.values()):
                        state = "done"
                    else:
                        state = "running"
                    payload = {
                        "state": state,
                        "progress": q._progress(),
                        "recomputes": q.recomputes,
                        "gens": {str(t.sid): t.gen
                                 for t in q.tasks.values()},
                        "bytes": {str(t.sid): t.bytes
                                  for t in q.tasks.values()
                                  if t.status == _DONE},
                        "error": str(q.error) if q.error else None}
            blob = base64.b64encode(
                json.dumps(payload).encode()).decode()
            return f"OK {blob}\n".encode()
        if cmd == "CREC" and len(parts) == 3:
            with self._lock:
                q = self.queries.get(int(parts[1]))
            if q is not None:
                q.recompute(int(parts[2]))
            return b"OK\n"
        if cmd == "CRESET" and len(parts) == 2:
            with self._lock:
                q = self.queries.get(int(parts[1]))
            if q is not None:
                q.reset()
            return b"OK\n"
        if cmd == "CFIN" and len(parts) == 2:
            with self._lock:
                q = self.queries.get(int(parts[1]))
            if q is not None:
                q.finish()
            return b"OK\n"
        if cmd == "CREPT" and len(parts) == 2:
            with self._lock:
                q = self.queries.get(int(parts[1]))
                reports = dict(q.worker_reports) if q is not None else {}
            blob = base64.b64encode(
                json.dumps({"reports": reports}).encode()).decode()
            return f"OK {blob}\n".encode()
        return None

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "workers": {
                    w.wid: {"alive": w.alive, "completed": w.completed,
                            "idle_ms": int((now - w.last_seen) * 1000),
                            "draining": w.draining,
                            "demoted": w.demoted,
                            "pressure": round(w.pressure, 4),
                            "inflight": self._inflight_locked(w.wid),
                            "beat_ms": list(w.beat_ms),
                            "stage_wall_ms": list(w.stage_wall_ms)}
                    for w in self.workers.values()},
                "retired": sorted(self._retired),
                "queries": {
                    str(qid): {
                        str(t.sid): {"status": t.status,
                                     "worker": t.worker, "gen": t.gen,
                                     "retries": t.retries,
                                     "producer": t.producer,
                                     "bytes": t.bytes}
                        for t in q.tasks.values()}
                    for qid, q in self.queries.items()},
            }

    # -- query submission (driver thread) ------------------------------------
    def _store_params(self, conf) -> Tuple[str, str]:
        """(store kind, endpoint) for a new query's stage outputs."""
        kind = cluster_store_kind(conf)
        endpoint = ""
        if kind == "objectstore":
            from spark_rapids_tpu.parallel.transport.objectstore import \
                resolve_endpoint
            endpoint = resolve_endpoint(conf)
        return kind, endpoint

    @staticmethod
    def _broadcast_maps(graph, deps) -> Tuple[Dict[int, int],
                                              Dict[int, List[int]]]:
        """(bcast_tags, bcast_deps) for the broadcast artifact cache:
        each broadcast-boundary stage keyed by its exchange identity,
        plus the dispatchable upstream stages whose generations key the
        cache entry."""
        from spark_rapids_tpu.parallel.exchange import \
            BroadcastExchangeExec
        tags = {id(st.boundary): sid for sid, st in graph.stages.items()
                if isinstance(st.boundary, BroadcastExchangeExec)}
        bdeps = {sid: sorted(deps.get(sid, ()))
                 for sid in tags.values()}
        return tags, bdeps

    def submit(self, phys, conf, graph=None,
               binds=None) -> Optional[QueryRun]:
        """Partition ``phys``'s stage DAG into dispatchable tasks and
        open a QueryRun, or None when the plan has no dispatchable
        stage or cannot cross a process boundary (unpicklable)."""
        from spark_rapids_tpu.parallel import stages as S
        if graph is None:
            graph = S.build_stage_graph(phys.root)
        _, dispatchable, deps = stage_plan(phys.root, graph)
        if not dispatchable:
            return None
        with self._lock:
            qid = self._next_qid
            self._next_qid += 1
        kind, endpoint = self._store_params(conf)
        prefix = f"{self.ns}/q{qid}" if kind == "objectstore" else ""
        worker_raw = {
            k: v for k, v in phys.conf.raw.items()
            # Conf-armed fault schedules stay driver-side: a spec
            # shipped to every worker would fire the same injection N
            # times (once per process). Worker-scoped chaos arms via
            # each worker's SRT_FAULTS environment instead.
            if not k.startswith("spark.rapids.sql.test.faults")
            and k != C.CLUSTER_ENABLED.key}
        if kind == "objectstore":
            # Pin the query's store coordinates into the shipped conf:
            # every worker (and the driver's fetch sessions) resolves
            # the SAME endpoint + key prefix regardless of its local
            # env, so the store is part of the plan, not the ambiance.
            worker_raw[C.SHUFFLE_TRANSPORT.key] = "objectstore"
            worker_raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT.key] = \
                endpoint
            worker_raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX.key] = \
                prefix
        try:
            blob = pickle.dumps((phys.root, worker_raw, binds))
        except Exception as e:
            _LOG.warning("cluster: plan not picklable (%s: %s) — "
                         "standing down to local execution",
                         type(e).__name__, e)
            return None
        bcast_tags, bcast_deps = self._broadcast_maps(graph, deps)
        with self._lock:
            tasks = {sid: _StageTask(sid, deps.get(sid, set())
                                     & dispatchable)
                     for sid in dispatchable}
            driver_tags = {id(graph.stages[sid].boundary):
                           (sid, f"s{sid}")
                           for sid in dispatchable}
            q = QueryRun(self, qid, conf, tasks, driver_tags)
            q._blob = blob
            q._root = phys.root
            q.store_kind, q.store_endpoint, q.store_prefix = \
                kind, endpoint, prefix
            q.plan_fp = hashlib.sha256(blob).hexdigest()[:12]
            q._bcast_tags = bcast_tags
            q._bcast_deps = bcast_deps
            os.makedirs(q.qdir, exist_ok=True)
            self._write_plan(q)
            q._pkl_ready = True
            self.queries[qid] = q
        self._jlog({
            "t": "submit", "qid": qid,
            "stages": sorted(dispatchable),
            "deps": {str(s): sorted(deps.get(s, set()) & dispatchable)
                     for s in dispatchable},
            "conf": worker_raw, "pkl": q.pkl_path,
            "store": [kind, endpoint, prefix], "fp": q.plan_fp})
        from spark_rapids_tpu import monitoring
        monitoring.instant("cluster-submit", "cluster",
                           args={"query": qid,
                                 "stages": len(dispatchable)})
        return q

    def submit_remote(self, spec: dict) -> Tuple[int, dict]:
        """CSUB: admit a query whose PLAN stays driver-side — the
        remote driver ships only the stage DAG metadata (stage ids,
        dispatchable deps, worker conf, store coordinates, plan
        fingerprint) and then writes the plan pickle to the returned
        path itself. Dispatch holds until that file lands
        (``_pkl_ready`` gate in ``_pick_locked``)."""
        stages = [int(s) for s in spec["stages"]]
        deps = {int(k): {int(x) for x in v}
                for k, v in (spec.get("deps") or {}).items()}
        conf = C.TpuConf(dict(spec.get("conf") or {}))
        kind = str(spec.get("store_kind") or "hostfile")
        endpoint = str(spec.get("endpoint") or "")
        with self._lock:
            qid = self._next_qid
            self._next_qid += 1
            prefix = f"{self.ns}/q{qid}" if kind == "objectstore" \
                else ""
            tasks = {sid: _StageTask(sid, deps.get(sid, set())
                                     & set(stages))
                     for sid in stages}
            q = QueryRun(self, qid, conf, tasks, {})
            q._pkl_ready = False
            q.pkl_path = os.path.join(self.base_dir, "plans",
                                      f"q{qid}.pkl")
            q.store_kind, q.store_endpoint, q.store_prefix = \
                kind, endpoint, prefix
            q.plan_fp = str(spec.get("fp") or "")
            os.makedirs(q.qdir, exist_ok=True)
            os.makedirs(os.path.dirname(q.pkl_path), exist_ok=True)
            self.queries[qid] = q
        worker_raw = dict(spec.get("conf") or {})
        if kind == "objectstore":
            worker_raw[C.SHUFFLE_TRANSPORT.key] = "objectstore"
            worker_raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT.key] = \
                endpoint
            worker_raw[C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX.key] = \
                prefix
        self._jlog({
            "t": "submit", "qid": qid, "stages": sorted(stages),
            "deps": {str(s): sorted(deps.get(s, set()) & set(stages))
                     for s in stages},
            "conf": worker_raw, "pkl": q.pkl_path,
            "store": [kind, endpoint, prefix], "fp": q.plan_fp})
        from spark_rapids_tpu import monitoring
        monitoring.instant("cluster-submit", "cluster",
                           args={"query": qid, "stages": len(stages),
                                 "remote": True})
        return qid, {"pkl": q.pkl_path, "prefix": prefix,
                     "conf": worker_raw}

    def _write_plan(self, q: QueryRun) -> None:
        tmp = q.pkl_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(q._blob)
        os.replace(tmp, q.pkl_path)

    def close(self, remove_dir: bool = True) -> None:
        """Stop the server; ``remove_dir=False`` keeps the cluster dir
        (journal + plans + spool) — the standalone coordinator uses it
        so a SIGKILL'd-then-restarted process can replay."""
        self.server.close()
        if remove_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)


# -- process-global coordinator (driver side) --------------------------------

_CO: Optional[ClusterCoordinator] = None
_CO_LOCK = threading.Lock()


def get_coordinator(conf) -> ClusterCoordinator:
    """The driver process's coordinator, created on first use from
    ``conf``'s cluster.* keys (later calls return the same instance —
    one control plane per driver, like the query manager)."""
    global _CO
    with _CO_LOCK:
        if _CO is None:
            _CO = ClusterCoordinator(conf)
        return _CO


def shutdown_coordinator() -> None:
    """Tear down the process-global coordinator (tests/bench)."""
    global _CO
    with _CO_LOCK:
        co, _CO = _CO, None
    if co is not None:
        co.close()


def maybe_prepare(phys, ctx, graph=None) -> Optional[QueryRun]:
    """The planner's prepare hook: a QueryRun for this collect, or None
    when the query must run locally. Stand-downs keep cluster mode
    CORRECT rather than clever — any query the dispatch model cannot
    represent simply executes exactly as before."""
    conf = ctx.conf
    if not cluster_enabled(conf):
        return None
    if not phys.root_on_device or phys.host_fallback_nodes():
        return None             # host islands run the oracle engine
    from spark_rapids_tpu.parallel import transport as T
    if T.transport_name(conf) == "mesh":
        return None             # collective exchange owns the shuffle
    binds = None
    if "plan_binds" in ctx.cache:
        # A plan-cache template executes against per-collect bound
        # literals; workers need them to resolve bind slots.
        binds = (ctx.cache["plan_binds"], ctx.cache["plan_bind_dtypes"])
    if bool(conf.get(C.CLUSTER_COORDINATOR_REMOTE)):
        # Out-of-process coordinator (failover mode): the driver is a
        # CLIENT — it submits over the wire and survives coordinator
        # restarts. See parallel/cluster/remote.py.
        from spark_rapids_tpu.parallel.cluster.remote import \
            remote_prepare
        return remote_prepare(phys, ctx, conf, graph)
    co = get_coordinator(conf)
    q = co.submit(phys, conf, graph, binds)
    if q is None:
        return None
    q.install(ctx)
    m = q._metrics()
    m.add("stagesDispatched", len(q.tasks))
    return q


# -- standalone coordinator process ------------------------------------------

def main(argv=None) -> int:
    """``python -m spark_rapids_tpu.parallel.cluster.coordinator`` — a
    coordinator that outlives any one driver process. Pairs with
    ``cluster.coordinator.remote=true`` drivers; the journal (on by
    default here) makes it SIGKILL-restartable in place: restart with
    the same ``--dir`` and ``--listen`` and in-flight queries resume
    with at most one recompute per interrupted stage."""
    import argparse
    ap = argparse.ArgumentParser(
        description="standalone srt cluster coordinator")
    ap.add_argument("--listen", required=True,
                    help="host:port to bind (workers + drivers connect "
                         "here)")
    ap.add_argument("--dir", required=True,
                    help="cluster state dir (journal, plans, spool) — "
                         "reuse it across restarts to recover")
    ap.add_argument("--heartbeat-timeout-ms", type=int, default=None)
    args = ap.parse_args(argv)
    raw = {C.CLUSTER_COORDINATOR.key: args.listen,
           C.CLUSTER_DIR.key: args.dir,
           C.CLUSTER_JOURNAL_ENABLED.key: True}
    if args.heartbeat_timeout_ms is not None:
        raw[C.CLUSTER_HEARTBEAT_TIMEOUT_MS.key] = \
            args.heartbeat_timeout_ms
    conf = C.TpuConf(raw)
    co = ClusterCoordinator(conf)
    host, port = co.addr
    print(f"coordinator listening at {host}:{port}", flush=True)
    if co.journal_replay_ms:
        print(f"journal replayed in {co.journal_replay_ms:.1f}ms",
              flush=True)
    try:
        # The monitor loop replaces QueryRun.run's driver-side
        # heartbeat sweep: with only REMOTE drivers there is no local
        # run() loop, so dead workers must be detected here.
        while True:
            time.sleep(co.hb_timeout_ms / 3000.0)
            with co._lock:
                co._check_workers_locked()
    except KeyboardInterrupt:
        pass
    finally:
        co.close(remove_dir=False)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
