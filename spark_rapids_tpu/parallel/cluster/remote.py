"""Remote-coordinator driver client (coordinator failover, ISSUE 17).

In the default cluster mode the COORDINATOR lives inside the driver
process, so a driver crash takes the control plane with it. This module
is the other arrangement: a standalone coordinator process
(``python -m spark_rapids_tpu.parallel.cluster.coordinator``) owns
membership, scheduling, and the write-ahead journal, while the driver
is a mere CLIENT (``cluster.coordinator.remote=true``):

- :func:`remote_prepare` submits the stage DAG over the wire (``CSUB``
  ships only metadata — stage ids, deps, worker conf, store
  coordinates; the plan pickle is written by the driver to the path the
  coordinator returns, and dispatch holds until it lands);
- :class:`RemoteQueryRun` mirrors the in-process ``QueryRun`` driver
  surface the planner drives (run/recompute/reset/install/finish) with
  one wire verb each, and its ``run`` loop RIDES OUT coordinator
  outages: an unreachable coordinator is polled again with backoff
  until the dispatch deadline, so a SIGKILL'd-and-restarted coordinator
  (which replays its journal and re-adopts committed stage outputs)
  resumes the query with at most one recompute per interrupted stage —
  the driver never sees an error, only a longer wait.

Recompute accounting: the coordinator counts stage recomputes in ITS
process; ``CWAIT`` carries the cumulative count and the driver mirrors
positive deltas into its local fault counters, so chaos tests assert
the ≤1-recompute bound against the driver exactly as in-process runs
do.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import pickle
import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import config as C
from spark_rapids_tpu.parallel.cluster.coordinator import (
    ClusterDispatchError, ClusterExecInfo, cluster_store_kind,
    merge_worker_reports, stage_plan)
from spark_rapids_tpu.parallel.transport.rendezvous import (
    RendezvousUnavailableError, _roundtrip, parse_addr)

_LOG = logging.getLogger("spark_rapids_tpu.cluster")


class RemoteQueryRun:
    """Driver-side handle on a query dispatched through a REMOTE
    coordinator. Implements the planner's QueryRun protocol (install /
    run / recompute / reset / finish); every verb is one rendezvous
    round trip."""

    def __init__(self, addr: Tuple[str, int], qid: int, conf,
                 base_dir: str, pkl_path: str, stages: List[int],
                 store: Tuple[str, str, str], plan_fp: str,
                 driver_tags, bcast_tags, bcast_deps, root):
        self.addr = addr
        self.qid = qid
        self.base_dir = base_dir
        self.qdir = os.path.join(base_dir, f"q{qid}")
        self.pkl_path = pkl_path
        self.stages = stages
        self.store_kind, self.store_endpoint, self.store_prefix = store
        self.plan_fp = plan_fp
        self._driver_tags = driver_tags
        self._bcast_tags = bcast_tags
        self._bcast_deps = bcast_deps
        self._root = root
        self._ctx = None
        self._trace_qid = 0
        self.finished = False
        self._gens: Dict[int, int] = {sid: 0 for sid in stages}
        self._last_recomputes = 0
        self.poll_ms = max(int(conf.get(C.CLUSTER_POLL_MS)), 1)
        self.dispatch_timeout_ms = max(
            int(conf.get(C.CLUSTER_DISPATCH_TIMEOUT_MS)), 1)

    # -- wire ----------------------------------------------------------------
    def _call(self, line: str, timeout_s: float = 10.0,
              retries: int = 3) -> str:
        if not line.endswith("\n"):
            line += "\n"
        return _roundtrip(self.addr, line, timeout_s=timeout_s,
                          retries=retries, backoff_ms=50)

    def _call_resilient(self, line: str, what: str) -> Optional[str]:
        """Best-effort control verb: a coordinator mid-restart loses
        nothing by missing it (recompute/reset re-derive from the
        journal + store state), so log and move on."""
        try:
            return self._call(line, retries=5)
        except RendezvousUnavailableError as e:
            _LOG.warning("cluster: %s for query %d not delivered "
                         "(coordinator unreachable): %s", what,
                         self.qid, e)
            return None

    # -- planner hooks -------------------------------------------------------
    def install(self, ctx) -> None:
        self._ctx = ctx
        self._trace_qid = ctx.cache.get("trace_query", 0)
        ctx.cache["cluster"] = ClusterExecInfo(
            self.qdir, f"drv{os.getpid()}", self._driver_tags,
            local_sid=None, store_kind=self.store_kind,
            store_endpoint=self.store_endpoint,
            store_prefix=self.store_prefix,
            bcast_tags=self._bcast_tags, bcast_deps=self._bcast_deps,
            plan_fp=self.plan_fp,
            gen_source=lambda: dict(self._gens))

    def _metrics(self):
        from spark_rapids_tpu.ops.base import query_metrics_entry
        return query_metrics_entry(self._ctx, "Cluster")

    def run(self, ctx) -> None:
        """Dispatch-and-wait barrier over the wire. An unreachable
        coordinator does NOT fail the query — this loop keeps polling
        until the dispatch deadline, which is exactly the failover
        window: kill the coordinator, restart it against the same
        ``--dir``, and the journal replay puts the query back where it
        was."""
        from spark_rapids_tpu import faults, monitoring
        t0 = time.monotonic()
        deadline = t0 + self.dispatch_timeout_ms / 1000.0
        was_unreachable = False
        while True:
            faults.check_cancelled()
            if time.monotonic() > deadline:
                from spark_rapids_tpu.parallel.cluster.coordinator \
                    import dispatch_timeout_error
                raise dispatch_timeout_error(
                    f"UNAVAILABLE: cluster dispatch of query {self.qid} "
                    f"incomplete after {self.dispatch_timeout_ms}ms "
                    f"(remote coordinator)",
                    queue_depth=len(self.stages))
            try:
                resp = self._call(f"CWAIT {self.qid}", timeout_s=5.0,
                                  retries=1)
            except RendezvousUnavailableError:
                if not was_unreachable:
                    was_unreachable = True
                    monitoring.instant(
                        "coordinator-unreachable", "recovery",
                        args={"query": self.qid}, qid=self._trace_qid)
                    _LOG.warning("cluster: coordinator %s:%d "
                                 "unreachable — riding out the outage "
                                 "(query %d)", self.addr[0],
                                 self.addr[1], self.qid)
                time.sleep(0.2)
                continue
            if was_unreachable:
                was_unreachable = False
                monitoring.instant("coordinator-reconnected",
                                   "recovery",
                                   args={"query": self.qid},
                                   qid=self._trace_qid)
                _LOG.warning("cluster: coordinator back — resuming "
                             "wait for query %d", self.qid)
            if not resp.startswith("OK "):
                raise ClusterDispatchError(
                    f"cluster coordinator rejected CWAIT: {resp!r}")
            payload = json.loads(base64.b64decode(resp[3:]).decode())
            state = payload.get("state")
            for sid_s, gen in (payload.get("gens") or {}).items():
                self._gens[int(sid_s)] = int(gen)
            rec = int(payload.get("recomputes") or 0)
            if rec > self._last_recomputes:
                # Mirror the coordinator's recompute count into the
                # driver's fault counters (it counted them in its own
                # process) so chaos assertions see them here.
                delta = rec - self._last_recomputes
                self._last_recomputes = rec
                for _ in range(delta):
                    faults.record("stageRecomputes")
                if self._ctx is not None:
                    self._metrics().add("tasksRequeued", delta)
            if state == "error":
                raise ClusterDispatchError(
                    payload.get("error")
                    or f"query {self.qid} failed at the coordinator")
            if state == "unknown":
                raise ClusterDispatchError(
                    f"UNAVAILABLE: coordinator does not know query "
                    f"{self.qid} (restarted without its journal?)")
            if state == "done":
                break
            time.sleep(self.poll_ms / 1000.0)
        m = self._metrics()
        m.add("dispatchWaitMs", (time.monotonic() - t0) * 1000.0)
        monitoring.instant(
            "cluster-dispatch-complete", "cluster",
            args={"query": self.qid, "stages": len(self.stages),
                  "remote": True}, qid=self._trace_qid)
        self._fetch_reports(ctx)

    def _fetch_reports(self, ctx) -> None:
        resp = self._call_resilient(f"CREPT {self.qid}",
                                    "worker-report fetch")
        if resp is None or not resp.startswith("OK "):
            return
        try:
            reports = json.loads(
                base64.b64decode(resp[3:]).decode()).get("reports") or {}
            merge_worker_reports(ctx, self._root, reports)
        except Exception:       # stats must never fail the query
            _LOG.warning("cluster: worker-report merge failed",
                         exc_info=True)

    def recompute(self, sid: int) -> None:
        self._call_resilient(f"CREC {self.qid} {sid}",
                             f"recompute of stage s{sid}")

    def reset(self) -> None:
        self._call_resilient(f"CRESET {self.qid}", "query reset")

    def finish(self) -> None:
        self.finished = True
        self._call_resilient(f"CFIN {self.qid}", "query finish")


def remote_prepare(phys, ctx, conf, graph=None):
    """The remote-mode branch of ``cluster.maybe_prepare``: submit over
    the wire and return a :class:`RemoteQueryRun`, or None to stand
    down to local execution (no coordinator address, no dispatchable
    stage, unpicklable plan, or a coordinator that is down at SUBMIT
    time — failover covers mid-query crashes, not a cluster that never
    existed)."""
    addr = parse_addr(str(conf.get(C.CLUSTER_COORDINATOR) or ""))
    base_dir = str(conf.get(C.CLUSTER_DIR) or "")
    if addr is None or not base_dir:
        _LOG.warning("cluster: coordinator.remote=true needs both "
                     "cluster.coordinator and cluster.dir — running "
                     "locally")
        return None
    g, dispatchable, deps = stage_plan(phys.root, graph)
    if not dispatchable:
        return None
    worker_raw = {
        k: v for k, v in phys.conf.raw.items()
        if not k.startswith("spark.rapids.sql.test.faults")
        and k not in (C.CLUSTER_ENABLED.key,
                      C.CLUSTER_COORDINATOR_REMOTE.key)}
    binds = None
    if "plan_binds" in ctx.cache:
        binds = (ctx.cache["plan_binds"], ctx.cache["plan_bind_dtypes"])
    try:
        pickle.dumps((phys.root, worker_raw, binds))
    except Exception as e:
        _LOG.warning("cluster: plan not picklable (%s: %s) — standing "
                     "down to local execution", type(e).__name__, e)
        return None
    kind = cluster_store_kind(conf)
    endpoint = ""
    if kind == "objectstore":
        from spark_rapids_tpu.parallel.transport.objectstore import \
            resolve_endpoint
        endpoint = resolve_endpoint(conf)
    spec = {
        "stages": sorted(dispatchable),
        "deps": {str(s): sorted(deps.get(s, set()) & dispatchable)
                 for s in dispatchable},
        "conf": worker_raw, "store_kind": kind, "endpoint": endpoint,
    }
    blob64 = base64.b64encode(json.dumps(spec).encode()).decode()
    try:
        resp = _roundtrip(addr, f"CSUB {blob64}\n", timeout_s=10.0,
                          retries=3, backoff_ms=50)
    except RendezvousUnavailableError as e:
        _LOG.warning("cluster: coordinator %s unreachable at submit — "
                     "running locally: %s", addr, e)
        return None
    parts = resp.split()
    if len(parts) != 3 or parts[0] != "OK":
        _LOG.warning("cluster: CSUB rejected (%r) — running locally",
                     resp)
        return None
    qid = int(parts[1])
    grant = json.loads(base64.b64decode(parts[2]).decode())
    pkl_path = grant["pkl"]
    prefix = grant.get("prefix") or ""
    pinned_raw = grant.get("conf") or worker_raw
    # The plan pickle carries the PINNED conf (store endpoint + the
    # query's key prefix), so every worker resolves the same store
    # coordinates regardless of its local environment.
    plan_blob = pickle.dumps((phys.root, pinned_raw, binds))
    os.makedirs(os.path.dirname(pkl_path), exist_ok=True)
    tmp = pkl_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(plan_blob)
    os.replace(tmp, pkl_path)
    plan_fp = hashlib.sha256(plan_blob).hexdigest()[:12]
    driver_tags = {id(g.stages[sid].boundary): (sid, f"s{sid}")
                   for sid in dispatchable}
    from spark_rapids_tpu.parallel.cluster.coordinator import \
        ClusterCoordinator
    bcast_tags, bcast_deps = ClusterCoordinator._broadcast_maps(g, deps)
    q = RemoteQueryRun(addr, qid, conf, base_dir, pkl_path,
                       sorted(dispatchable), (kind, endpoint, prefix),
                       plan_fp, driver_tags, bcast_tags, bcast_deps,
                       phys.root)
    q.install(ctx)
    m = q._metrics()
    m.add("stagesDispatched", len(dispatchable))
    from spark_rapids_tpu import monitoring
    monitoring.instant("cluster-submit", "cluster",
                       args={"query": qid, "stages": len(dispatchable),
                             "remote": True})
    return q
