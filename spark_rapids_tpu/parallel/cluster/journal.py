"""Coordinator write-ahead journal (ISSUE 17 tentpole leg b).

The cluster coordinator's in-memory state — worker registrations and
every query's per-stage task state (dispatch / done / generation /
requeue) — dies with the coordinator process. This module persists it
as an append-only JSONL journal under ``<cluster.dir>/journal/``,
reusing the event-log machinery's idiom (monitoring/history.py): one
JSON object per line, appended under a lock, read back torn-line
tolerant. A SIGKILL'd-and-restarted coordinator replays the journal,
re-adopts stage outputs whose transport manifests are still committed,
and requeues only what was actually in flight — bounding a coordinator
crash at ≤1 recompute per affected stage instead of losing every
in-flight query.

Record kinds (all carry ``ts``)::

    {"t":"reg","wid":...}                          worker registration
    {"t":"submit","qid":...,"stages":[sid,...],
     "deps":{sid:[sid,...]},"conf":{...},"pkl":...} query admission
    {"t":"dispatch","qid":...,"sid":...,"gen":...,
     "wid":...}                                    task handed to worker
    {"t":"done","qid":...,"sid":...,"gen":...,
     "wid":...,"bytes":...}                        stage output committed
    {"t":"requeue","qid":...,"sid":...,"gen":...,
     "retries":...}                                recompute scheduled
    {"t":"reset","qid":...}                        whole-query reset
    {"t":"finish","qid":...}                       query finished
    {"t":"replay","ms":...,"queries":[...],
     "workers":[...]}                              a restart recovered

Durability model: appends are buffered (``fsync=False`` default) — the
failover contract already budgets one recompute per in-flight stage,
so a torn/unflushed tail costs at most the recompute the crash was
going to cause anyway. ``cluster.journal.fsync`` upgrades every append
to a true fsync for the paranoid.

Compaction: after the last active query finishes, the journal is
atomically rewritten with only the live registration records, so it
does not grow without bound across a long-lived coordinator.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

_LOG = logging.getLogger("spark_rapids_tpu.cluster")


class Journal:
    """Append-only JSONL WAL with a torn-line-tolerant reader. Safe for
    concurrent appends from coordinator handler threads (one lock, one
    O_APPEND file); never raises out of ``append`` — a journal write
    failure degrades durability, it must not fail a running query."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path), exist_ok=True)

    def append(self, rec: dict) -> None:
        rec = dict(rec)
        rec.setdefault("ts", time.time())
        try:
            line = json.dumps(rec, sort_keys=True)
            with self._lock:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
        except Exception:
            _LOG.warning("journal append failed (%s)", self.path,
                         exc_info=True)

    def records(self) -> List[dict]:
        """All parseable records, in append order; a torn trailing line
        (the crash was mid-append) is skipped, exactly like the event
        log's reader."""
        out: List[dict] = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except FileNotFoundError:
            pass
        return out

    def rewrite(self, recs: List[dict]) -> None:
        """Atomic compaction (tmp + rename): replaces the journal with
        ``recs`` — the same old-complete-or-new-complete contract every
        manifest in this codebase uses."""
        try:
            tmp = self.path + ".tmp"
            with self._lock:
                with open(tmp, "w", encoding="utf-8") as f:
                    for rec in recs:
                        f.write(json.dumps(rec, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
        except Exception:
            _LOG.warning("journal compaction failed (%s)", self.path,
                         exc_info=True)


def replay_state(recs: List[dict]) -> Dict:
    """Fold raw journal records into the recovered logical state:

    - ``workers``: wids seen registering (latest knowledge; liveness is
      re-established by their reconnect heartbeats),
    - ``draining``: wids whose drain (CDRAIN) was journaled but whose
      retirement was not — a restarted coordinator re-marks them
      draining so the retire handshake completes instead of the worker
      polling forever for its CRETIRE,
    - ``queries``: qid -> {"submit": rec, "tasks": {sid: {"status",
      "gen", "wid", "bytes", "retries"}}} for every UNFINISHED query,
    - ``next_qid``: one past the highest qid ever admitted.

    Pure function of the record list so it is unit-testable without a
    coordinator."""
    workers: List[str] = []
    draining: List[str] = []
    queries: Dict[int, dict] = {}
    next_qid = 1
    for r in recs:
        t = r.get("t")
        if t == "reg":
            wid = str(r.get("wid", ""))
            if wid and wid not in workers:
                workers.append(wid)
            if wid in draining:
                draining.remove(wid)
        elif t == "drain":
            wid = str(r.get("wid", ""))
            if wid in workers and wid not in draining:
                draining.append(wid)
        elif t == "retire":
            wid = str(r.get("wid", ""))
            if wid in workers:
                workers.remove(wid)
            if wid in draining:
                draining.remove(wid)
        elif t == "submit":
            try:
                qid = int(r["qid"])
                stages = [int(s) for s in r["stages"]]
            except (KeyError, TypeError, ValueError):
                continue
            next_qid = max(next_qid, qid + 1)
            queries[qid] = {
                "submit": r, "recomputes": 0,
                "tasks": {sid: {"status": "pending", "gen": 0,
                                "wid": None, "bytes": 0, "retries": 0}
                          for sid in stages}}
        elif t in ("dispatch", "done", "requeue"):
            q = queries.get(r.get("qid"))
            if q is None:
                continue
            task = q["tasks"].get(int(r.get("sid", -1)))
            if task is None:
                continue
            gen = int(r.get("gen", 0))
            if t == "dispatch":
                # A dispatch for an older generation is stale news.
                if gen >= task["gen"]:
                    task.update(status="running", gen=gen,
                                wid=r.get("wid"))
            elif t == "done":
                if gen >= task["gen"]:
                    task.update(status="done", gen=gen,
                                wid=r.get("wid"),
                                bytes=int(r.get("bytes", 0)))
            else:  # requeue: gen already bumped by the writer
                if gen >= task["gen"]:
                    task.update(status="pending", gen=gen, wid=None,
                                retries=int(r.get("retries",
                                              task["retries"] + 1)))
                    if r.get("counted", True):
                        # Recompute baseline: a restarted coordinator
                        # must not re-report pre-crash recomputes to a
                        # remote driver as fresh ones.
                        q["recomputes"] = q.get("recomputes", 0) + 1
        elif t == "reset":
            q = queries.get(r.get("qid"))
            if q is not None:
                for task in q["tasks"].values():
                    task.update(status="pending", wid=None, bytes=0)
        elif t == "finish":
            queries.pop(r.get("qid"), None)
    return {"workers": workers, "draining": draining,
            "queries": queries, "next_qid": next_qid}
