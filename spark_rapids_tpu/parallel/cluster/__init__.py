"""Distributed worker runtime (ISSUE 13 tentpole).

N independent worker processes execute ONE query's stage DAG: the
driver-side coordinator (:mod:`coordinator`) partitions the DAG
(parallel/stages.py) into dispatchable stage tasks and assigns them —
locality-aware — to worker processes (:mod:`worker`) that registered
over the rendezvous control plane; each worker executes its assigned
stage and publishes the stage output as owner-tagged shards through the
hostfile shuffle transport (exclusive-manifest mode), where the driver
and dependent stages fetch them. The reference gets this architecture
for free from Spark's driver/executor split with the
RapidsShuffleInternalManager shipping shards over UCX (PAPER.md L1/L3);
this package is that split built directly on the engine's stage DAG and
transport SPI — the SF10K / multi-slice DCN stand-in.

``spark.rapids.sql.cluster.enabled=false`` (the default) leaves every
existing code path byte-for-byte unchanged: the only hooks outside this
package are a ``ctx.cache["cluster"]`` lookup in the exchange's session
opener/materializer and the prepare/recompute/reset calls in the
planner's recovery ladder, all of which no-op when the marker is
absent.

Survivability (ISSUE 17): the coordinator write-ahead-journals its
scheduling state (:mod:`journal`) and can run as a STANDALONE process
(``python -m ...cluster.coordinator``) that survives SIGKILL by
replaying the journal on restart; drivers opt into the out-of-process
coordinator with ``cluster.coordinator.remote=true`` (:mod:`remote`)
and ride out the restart window instead of failing; workers reconnect
with capped backoff instead of dying on a refused poll.

Self-healing (ISSUE 20): :mod:`supervisor` owns the worker pool —
restart with exponential backoff, crash-loop quarantine, straggler
demotion (``CDEMO``) and clean drain/retire (``CDRAIN``/``CRETIRE``);
:mod:`autoscaler` sizes the pool against the ``cluster.autoscale.*``
SLO knobs and defers brownout to a scale-up attempt while headroom
remains. ``scripts/cluster.py --supervise`` is the standalone entry.
"""

from spark_rapids_tpu.parallel.cluster.autoscaler import (    # noqa: F401
    Autoscaler, ScalerState, decide)
from spark_rapids_tpu.parallel.cluster.coordinator import (   # noqa: F401
    ClusterCoordinator, ClusterDispatchError, ClusterExecInfo, QueryRun,
    cluster_enabled, cluster_store_kind, dispatch_timeout_error,
    get_coordinator, maybe_prepare, merge_worker_reports,
    shutdown_coordinator, stage_plan)
from spark_rapids_tpu.parallel.cluster.journal import (       # noqa: F401
    Journal, replay_state)
from spark_rapids_tpu.parallel.cluster.remote import (        # noqa: F401
    RemoteQueryRun, remote_prepare)
from spark_rapids_tpu.parallel.cluster.supervisor import (    # noqa: F401
    Supervisor, drain_order, is_crash_looping, restart_backoff_ms,
    straggler_verdicts)
