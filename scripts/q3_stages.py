"""Stage-by-stage q3 timing on the live backend: each stage is a prefix
of the q3 pipeline ending in a cheap count, so stage N+1 minus stage N
approximates the device cost of the added operator. Hot (scan cache on),
second run of each stage is reported.

Rebased on the flight recorder: the per-stage wall comes from the
query's ``collect`` span (monitoring/recorder.py) instead of an ad-hoc
perf_counter pair, so the number is exactly what trace_export renders —
and a Chrome trace of any stage is one ``monitoring.export_chrome``
call away.

Usage: TPCH_SF=1 python scripts/q3_stages.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _collect_span_secs() -> float:
    """Wall of the newest top-level collect span across all rings."""
    from spark_rapids_tpu import monitoring
    spans = [e for e in monitoring.events()
             if e[0] == "X" and e[1] == "collect" and e[2] == "query"]
    assert spans, "no collect span recorded (trace disabled?)"
    return spans[-1][4] / 1e9


def main():
    from spark_rapids_tpu import monitoring
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.plan.logical import agg_count, agg_sum, col, \
        lit_col

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    d = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    tpch.generate(d, scale=sf)
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    s.set("spark.rapids.sql.trace.enabled", True)
    if os.environ.get("SRT_SHUFFLE_PARTS"):
        s.set("spark.rapids.sql.shuffle.partitions",
              int(os.environ["SRT_SHUFFLE_PARTS"]))

    def read(t):
        return s.read.parquet(*tpch._paths(d, t))

    def stages():
        cust = read("customer") \
            .filter(col("c_mktsegment") == lit_col("BUILDING")) \
            .select("c_custkey")
        orders = read("orders") \
            .filter(col("o_orderdate") < lit_col(tpch.days("1995-03-15"))) \
            .select("o_orderkey", "o_custkey", "o_orderdate",
                    "o_shippriority")
        li = read("lineitem") \
            .filter(col("l_shipdate") > lit_col(tpch.days("1995-03-15"))) \
            .select("l_orderkey", "l_extendedprice", "l_discount")
        co = orders.join_on(cust, ["o_custkey"], ["c_custkey"])
        j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
        g = j.group_by("l_orderkey", "o_orderdate", "o_shippriority").agg(
            agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
            .alias("revenue"))
        full = g.order_by(col("revenue").desc(),
                          col("o_orderdate").asc()).limit(10)
        return [
            ("scan_li", li.agg(agg_count().alias("n"))),
            ("join1_co", co.agg(agg_count().alias("n"))),
            ("join2", j.agg(agg_count().alias("n"))),
            ("agg", g.agg(agg_count().alias("n"))),
            ("full", full),
        ]

    prev = 0.0
    for name, df in stages():
        df.collect()                      # compile + cold
        monitoring.reset()
        out = df.collect()
        dt = _collect_span_secs()
        print(f"{name:10s} hot={dt:7.3f}s  delta={dt - prev:7.3f}s "
              f"-> {out[:1]}")
        prev = dt


if __name__ == "__main__":
    main()
