"""Attribute q3 wall time: per-exec metrics + phase timers.

Usage: python scripts/profile_q3.py [q1|q6|q3|q5] [iters]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    qn = sys.argv[1] if len(sys.argv) > 1 else "q3"
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.ops.base import ExecContext

    data_dir = os.environ.get("TPCH_DIR", "/tmp/srt_tpch_sf1")
    tpch.generate(data_dir, scale=1.0)

    session = TpuSession()
    session.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    session.set("spark.rapids.sql.hasNans", False)
    df = tpch.QUERIES[qn](session, data_dir)

    # Warmup (compile)
    t0 = time.perf_counter()
    df.collect()
    print(f"warmup: {time.perf_counter()-t0:.2f}s")

    for it in range(iters):
        phys = df._physical()
        ctx = ExecContext(phys.conf)
        t0 = time.perf_counter()
        rows = phys.root.collect(ctx, device=phys.root_on_device)
        wall = time.perf_counter() - t0
        print(f"\n=== iter {it}: wall {wall:.3f}s, {len(rows)} rows ===")
        for key, m in sorted(ctx.metrics.items()):
            vals = {k: (round(v / 1e9, 3) if "Time" in k else v)
                    for k, v in m.values.items()}
            print(f"  {key}: {vals}")
        ctx.close()


if __name__ == "__main__":
    main()
