#!/usr/bin/env python
"""Standalone localhost object-store stub for the objectstore shuffle
transport (parallel/transport/objectstore.py).

Serves PUT/GET/DELETE /o/<key>, GET /list?prefix=, GET /health, and an
admin surface for chaos steering:

    POST /admin/latency?ms=N          inject per-request latency
    POST /admin/fail?n=N[&code=503]   fail the next N data-plane requests
    POST /admin/drop?prefix=K         delete keys (exact key or prefix)
    POST /admin/reset                 clear objects + injections
    GET  /admin/stats                 counters as JSON

Usage::

    python scripts/objstore_stub.py [--host 127.0.0.1] [--port 9000]
    SRT_OBJECTSTORE_ENDPOINT=http://127.0.0.1:9000 \
        SRT_SHUFFLE_TRANSPORT=objectstore python -m pytest tests/ ...

With no --port, an OS-assigned port is used and printed. The stub is
in-memory: killing it loses every object (which is the point — the
chaos matrix kills it).
"""

import argparse
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = OS-assigned (printed on stdout)")
    args = ap.parse_args(argv)

    from spark_rapids_tpu.parallel.transport.objectstore import \
        ObjectStoreStub
    stub = ObjectStoreStub(host=args.host, port=args.port)
    print(f"objstore stub listening at {stub.endpoint}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        stub.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
