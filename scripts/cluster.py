"""Launch a pool of cluster worker processes (ISSUE 13 tentpole).

The distributed runtime has two halves: the driver-side coordinator
(started implicitly by any session with
``spark.rapids.sql.cluster.enabled=true``) and N worker processes that
register with it, poll for stage tasks and publish their outputs
through the shuffle transport. This script is the worker half's
launcher:

  * ``--coordinator HOST:PORT`` joins workers to a driver that is
    already running (the driver prints its address, or read it from
    ``get_coordinator(conf).addr``). The script forwards SIGINT/SIGTERM
    to the pool and exits with the first non-zero worker status.
  * ``--demo`` is the self-contained smoke path: generate a small TPC-H
    dataset, start a coordinator in-process, spawn the pool, run one
    query distributed and check it bit-identical against the local run.
  * ``--supervise`` runs the pool under the self-healing supervisor
    (ISSUE 20): dead workers restart with exponential backoff,
    crash-loopers are quarantined, stragglers demoted and scale-down
    drains cleanly. Add ``--autoscale`` to let the SLO loop size the
    pool between ``cluster.autoscale.minWorkers``/``maxWorkers``
    instead of holding ``--workers`` fixed.

Run: python scripts/cluster.py --workers 3 --coordinator 127.0.0.1:41234
     python scripts/cluster.py --supervise --workers 3 \
         --coordinator 127.0.0.1:41234
     python scripts/cluster.py --demo --workers 3 --query q3
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def spawn_workers(addr, n, heartbeat_ms=None, prefix="w"):
    """Spawn n worker subprocesses against coordinator ``addr``."""
    env = dict(os.environ)
    # Fault schedules are per-experiment: never inherit one into a pool.
    env.pop("SRT_FAULTS", None)
    procs = []
    for i in range(n):
        cmd = [sys.executable, "-m",
               "spark_rapids_tpu.parallel.cluster.worker",
               "--coordinator", addr, "--worker-id", f"{prefix}{i}"]
        if heartbeat_ms:
            cmd += ["--heartbeat-ms", str(heartbeat_ms)]
        procs.append(subprocess.Popen(cmd, env=env, cwd=ROOT))
    return procs


def reap(procs, timeout_s=15):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=timeout_s)
        except Exception:
            p.kill()


def run_pool(args):
    procs = spawn_workers(args.coordinator, args.workers,
                          args.heartbeat_ms, args.prefix)
    stop = []

    def on_signal(signum, frame):
        stop.append(signum)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    print(f"cluster.py: {args.workers} worker(s) -> {args.coordinator} "
          f"(pids {[p.pid for p in procs]})")
    rc = 0
    while not stop:
        done = [p for p in procs if p.poll() is not None]
        if done:
            rc = max(abs(p.returncode) for p in done)
            break
        time.sleep(0.25)
    reap(procs)
    return rc


def run_supervised(args):
    """Run the pool under the self-healing supervisor (and optionally
    the SLO autoscaler) instead of bare subprocesses."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.parallel.cluster.autoscaler import Autoscaler
    from spark_rapids_tpu.parallel.cluster.supervisor import Supervisor

    conf = C.TpuConf({})
    sup = Supervisor(args.coordinator, conf=conf, prefix=args.prefix,
                     heartbeat_ms=args.heartbeat_ms)
    scaler = None
    if args.autoscale or conf.get(C.CLUSTER_AUTOSCALE_ENABLED):
        scaler = Autoscaler(sup, conf=conf)
        start_n = scaler.min_workers
    else:
        start_n = args.workers
    for _ in range(start_n):
        sup.add_worker()

    stop = []

    def on_signal(signum, frame):
        stop.append(signum)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    mode = "autoscaled" if scaler else "supervised"
    print(f"cluster.py: {start_n} {mode} worker(s) -> "
          f"{args.coordinator}")
    sup.start()
    if scaler:
        scaler.start()
    try:
        while not stop:
            time.sleep(0.25)
    finally:
        if scaler:
            scaler.stop()
        sup.close()
    return 0


def run_demo(args):
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.parallel import cluster as CL

    d = args.data_dir or tempfile.mkdtemp(prefix="tpch_cluster_demo_")
    if not os.path.exists(os.path.join(d, "lineitem")):
        print(f"cluster.py: generating TPC-H scale={args.scale} in {d}")
        tpch.generate(d, scale=args.scale, files_per_table=3, seed=7)

    def session(cluster=False):
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        # Shuffle-forced plans have independent leaf stages — the demo
        # should show work actually spreading across the pool.
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
        if cluster:
            s.set("spark.rapids.sql.cluster.enabled", True)
            s.set("spark.rapids.sql.cluster.minWorkers", args.workers)
        return s

    t0 = time.perf_counter()
    want = tpch.QUERIES[args.query](session(), d).collect()
    local_s = time.perf_counter() - t0

    s = session(cluster=True)
    co = CL.get_coordinator(s.conf)
    addr = f"{co.addr[0]}:{co.addr[1]}"
    procs = spawn_workers(addr, args.workers, args.heartbeat_ms,
                          args.prefix)
    try:
        df = tpch.QUERIES[args.query](s, d)
        t0 = time.perf_counter()
        got = df.collect()
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = df.collect()
        warm_s = time.perf_counter() - t0
        st = co.stats()
        print(f"cluster.py demo: {args.query} x{args.workers} workers")
        print(f"  local       {local_s:8.3f}s")
        print(f"  distributed {cold_s:8.3f}s cold (worker JIT), "
              f"{warm_s:.3f}s warm")
        print(f"  bit-identical: {got == want}")
        for wid, w in sorted(st["workers"].items()):
            print(f"  {wid}: alive={w['alive']} "
                  f"completed={w['completed']}")
        return 0 if got == want else 1
    finally:
        reap(procs)
        CL.shutdown_coordinator()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--coordinator",
                    help="host:port of a running driver coordinator")
    ap.add_argument("--heartbeat-ms", type=int, default=None)
    ap.add_argument("--prefix", default="w",
                    help="worker-id prefix (ids are <prefix>0..N-1)")
    ap.add_argument("--demo", action="store_true",
                    help="self-contained: coordinator + pool + one query")
    ap.add_argument("--supervise", action="store_true",
                    help="run the pool under the self-healing "
                         "supervisor (restart/quarantine/drain)")
    ap.add_argument("--autoscale", action="store_true",
                    help="with --supervise: SLO autoscaler sizes the "
                         "pool (cluster.autoscale.* knobs)")
    ap.add_argument("--query", default="q3",
                    help="TPC-H query for --demo")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="TPC-H scale factor for --demo datagen")
    ap.add_argument("--data-dir", default=None,
                    help="reuse an existing TPC-H dataset for --demo")
    args = ap.parse_args(argv)
    if not args.demo and not args.coordinator:
        ap.error("--coordinator is required unless --demo")
    if args.demo:
        return run_demo(args)
    if args.supervise or args.autoscale:
        return run_supervised(args)
    return run_pool(args)


if __name__ == "__main__":
    sys.exit(main())
