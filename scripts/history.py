"""Post-hoc query-history reader — the history-server CLI.

Reads the persistent JSONL event log written at query teardown
(``spark.rapids.sql.eventLog.dir`` / ``SRT_EVENT_LOG``; see
``spark_rapids_tpu/monitoring/history.py``) and reconstructs, after
every process that ran the queries has exited:

- per-query ``explain_analyze``-style node reports (observed
  rows/bytes/wall per plan node, span-category breakdown, recovery
  instants, bind-slot values, plan provenance);
- a fleet summary (query counts by status/class/tenant, distinct
  plans, plan-cache hit count, p50/p99 latency).

Usage::

    python scripts/history.py /tmp/srt-events            # list queries
    python scripts/history.py /tmp/srt-events --query 3  # one report
    python scripts/history.py /tmp/srt-events --summary  # fleet rollup

``PATH`` is the event-log directory (every ``*.jsonl`` inside, merged
and time-sorted) or a single log file. Stdlib-only, like the writer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_tpu.monitoring import history  # noqa: E402


def _list(records) -> None:
    for rec in records:
        dur = rec.get("duration_ms", 0.0) or 0.0
        print(f"query {rec.get('query_id')}  [{rec.get('status')}]  "
              f"class={rec.get('class') or '-'}  "
              f"tenant={rec.get('tenant') or '-'}  "
              f"wall={dur:.1f}ms  plan={rec.get('plan_fingerprint')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="event-log directory or .jsonl file")
    ap.add_argument("--query", type=int, default=None,
                    help="render the full report of ONE query id "
                         "(latest record wins when ids repeat)")
    ap.add_argument("--summary", action="store_true",
                    help="print the fleet summary JSON instead of the "
                         "per-query listing")
    args = ap.parse_args(argv)

    records = history.read_events(args.path)
    if not records:
        print(f"no event-log records under {args.path}", file=sys.stderr)
        return 1
    if args.query is not None:
        matches = [r for r in records if r.get("query_id") == args.query]
        if not matches:
            print(f"no record for query {args.query}", file=sys.stderr)
            return 1
        print(history.render_report(matches[-1]))
        return 0
    if args.summary:
        print(json.dumps(history.fleet_summary(records), indent=2,
                         sort_keys=True))
        return 0
    _list(records)
    return 0


if __name__ == "__main__":
    sys.exit(main())
