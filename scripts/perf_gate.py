"""CI perf-regression gate: replay the warmup manifest and fail on >15%
regression of the headline counters vs the committed reference.

The gate measures three headline numbers (ROADMAP item 1's "lock it in"):

- ``compile_s``     — wall clock of replaying the ``scripts/warmup.py``
                      shape manifest with NO persistent kernel cache
                      (every kernel traces + compiles fresh): the
                      cold-compile cliff a fresh server pays.
- ``bind_only_ms``  — median latency of a repeat parameterized query
                      through the plan cache (zero re-plan, zero
                      re-trace): the steady-state serving floor.
- ``scan_gbps``     — post-compile cold-scan throughput of q1+q6
                      through the ingest fast path (the same probe that
                      produces bench.py's scan_gb_per_sec headline).

A fourth probe, ``telemetry_noop_ns``, bounds the metrics-off cost of
the live-telemetry instrumentation sites by an ABSOLUTE budget (no
reference entry — the no-op is a single global load, immune to machine
speed at the budget's scale).

Machine normalization: absolute wall clock is meaningless across CI
runners, so the gate first times a fixed numpy calibration workload and
scales every latency by ``ref_calib_s / my_calib_s`` (and throughput by
the inverse) before comparing. The committed reference
(``PERF_REFERENCE.json``) stores its own calibration time for exactly
this purpose. The tolerance is 15% after normalization
(``PERF_GATE_TOLERANCE`` overrides; CI runners are noisy — loosen there
rather than deleting the gate).

Usage::

    python scripts/perf_gate.py              # compare vs PERF_REFERENCE.json
    python scripts/perf_gate.py --update     # re-measure and commit as ref

Prints one JSON line with measured / normalized / reference values and
per-metric verdicts; exits 1 on any regression beyond tolerance.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_REFERENCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "PERF_REFERENCE.json")


def calibration_s(iters: int = 3) -> float:
    """Fixed numpy workload timing this machine's single-core speed —
    the normalization denominator. Matmul + memcpy + sort roughly
    mirror the engine's host-side mix."""
    import numpy as np
    rng = np.random.default_rng(0)
    a = rng.normal(size=(384, 384))
    buf = rng.normal(size=1 << 20)
    keys = rng.integers(0, 1 << 31, 1 << 19)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(8):
            _ = a @ a
        for _ in range(16):
            _ = buf.copy() * 1.5
        _ = np.sort(keys, kind="stable")
        best = min(best, time.perf_counter() - t0)
    return best


def measure_compile_s() -> dict:
    """Replay the warmup shape manifest with the persistent cache OFF:
    pure trace+compile wall clock."""
    from scripts import warmup
    from spark_rapids_tpu.ops import kernel_cache as kc
    kc.cache().clear()
    buf = io.StringIO()
    t0 = time.perf_counter()
    with contextlib.redirect_stdout(buf):
        rc = warmup.main(["--persistent-dir", ""])
    secs = time.perf_counter() - t0
    report = json.loads(buf.getvalue().strip().splitlines()[-1])
    if rc != 0:
        raise RuntimeError(f"warmup replay failed: {report['shapes']}")
    return {"compile_s": round(secs, 3),
            "kernel_compiles": report["kernel_compiles"],
            "shapes": len(report["shapes"])}


def measure_bind_only_ms(iters: int = 7) -> float:
    """Median collect latency of a repeat parameterized q6-class query:
    a plan-cache hit executing bind-only."""
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    sf = float(os.environ.get("WARMUP_SF", "0.01"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    if not os.path.isdir(tpch_dir):
        tpch.generate(tpch_dir, scale=sf)

    def session():
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.hasNans", False)
        return s

    df = tpch.QUERIES["q6"](session(), tpch_dir)
    df.collect()                        # compile + template into the cache
    samples = []
    for _ in range(iters):
        # A fresh DataFrame each round so the plan-CACHE (not the same
        # object) serves the template; same literals = same key.
        df = tpch.QUERIES["q6"](session(), tpch_dir)
        t0 = time.perf_counter()
        df.collect()
        samples.append((time.perf_counter() - t0) * 1000.0)
    return statistics.median(samples)


def measure_scan_gbps() -> float:
    """Post-compile cold-scan throughput of q1+q6 (bench.py's
    scan_gb_per_sec probe at gate scale)."""
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE
    sf = float(os.environ.get("WARMUP_SF", "0.01"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    if not os.path.isdir(tpch_dir):
        tpch.generate(tpch_dir, scale=sf)

    def session():
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.hasNans", False)
        return s

    dfs = [tpch.QUERIES[q](session(), tpch_dir) for q in ("q1", "q6")]
    for df in dfs:
        df.collect()
    DEVICE_SCAN_CACHE.clear()
    t0 = time.perf_counter()
    for df in dfs:
        df.collect()
    secs = time.perf_counter() - t0
    nbytes = tpch.bytes_scanned("q1", tpch_dir) + \
        tpch.bytes_scanned("q6", tpch_dir)
    return nbytes / secs / 1e9 if secs > 0 else 0.0


def measure_telemetry_noop_ns(calls: int = 200_000) -> float:
    """Per-call cost of a metrics-off ``inc``+``observe`` pair — the
    cost every instrumentation site adds in the default configuration.
    Bounded by an ABSOLUTE budget (like microbench's trace-span
    assert), not a reference metric: the no-op is a single global load,
    so machine variance is irrelevant at the 3µs bound and the
    reference file stays untouched."""
    from spark_rapids_tpu.monitoring import telemetry
    telemetry.configure(False)

    def loop():
        t0 = time.perf_counter_ns()
        for _ in range(calls):
            telemetry.inc("srt_gate_counter")
            telemetry.observe("srt_gate_latency_ms", 1.0)
        return (time.perf_counter_ns() - t0) / calls

    best = min(loop() for _ in range(3))
    telemetry.reset()
    return best


def measure_host_engine_s() -> float:
    """Forced-host q1+q3 wall through the vectorized numpy engine —
    scan+filter+agg plus a two-join pipeline, the shapes the r06
    profile showed dominated by per-row python loops. Gated so a loop
    sneaking back into the sort/agg/join/filter host halves (or a
    matrix-destroying string copy) fails CI, same tolerance machinery
    as the device headlines."""
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    sf = float(os.environ.get("WARMUP_SF", "0.01"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    if not os.path.isdir(tpch_dir):
        tpch.generate(tpch_dir, scale=sf)

    def session():
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.hasNans", False)
        return s

    dfs = [tpch.QUERIES[q](session(), tpch_dir) for q in ("q1", "q3")]
    for df in dfs:
        df.collect_host()           # warm imports + scan path

    def sample():
        t0 = time.perf_counter()
        for df in dfs:
            df.collect_host()
        return time.perf_counter() - t0

    # Best-of-3: the warm wall at this scale is tens of ms, so a single
    # sample is scheduler-jitter-dominated on small CI machines.
    return min(sample() for _ in range(3))


TELEMETRY_NOOP_BUDGET_NS = 3000.0


def measure() -> dict:
    calib = calibration_s()
    out = {"calibration_s": round(calib, 4)}
    out.update(measure_compile_s())
    out["bind_only_ms"] = round(measure_bind_only_ms(), 3)
    out["scan_gbps"] = round(measure_scan_gbps(), 4)
    out["host_engine_s"] = round(measure_host_engine_s(), 3)
    out["telemetry_noop_ns"] = round(measure_telemetry_noop_ns(), 1)
    return out


# metric -> direction ("lower" = regression when it grows)
GATED = {"compile_s": "lower", "bind_only_ms": "lower",
         "scan_gbps": "higher", "host_engine_s": "lower"}


def compare(measured: dict, reference: dict, tolerance: float) -> dict:
    """Normalize by the calibration ratio and verdict each metric."""
    speed = reference["calibration_s"] / max(measured["calibration_s"],
                                             1e-9)
    report = {"speed_ratio": round(speed, 4), "tolerance": tolerance,
              "metrics": {}, "ok": True}
    for name, direction in GATED.items():
        raw = measured[name]
        ref = reference[name]
        # A machine twice as slow (speed < 1) gets its latencies scaled
        # DOWN (and throughput scaled UP) before the comparison.
        norm = raw * speed if direction == "lower" else raw / speed
        if direction == "lower":
            ok = norm <= ref * (1.0 + tolerance)
            delta = norm / ref - 1.0 if ref else 0.0
        else:
            ok = norm >= ref * (1.0 - tolerance)
            delta = 1.0 - norm / ref if ref else 0.0
        report["metrics"][name] = {
            "measured": raw, "normalized": round(norm, 4),
            "reference": ref, "regressionPct": round(delta * 100, 1),
            "ok": ok}
        report["ok"] = report["ok"] and ok
    # Absolute-budget metric (no reference entry, no normalization):
    # the metrics-off telemetry no-op must stay in single-global-load
    # territory on ANY machine.
    noop = measured.get("telemetry_noop_ns")
    if noop is not None:
        ok = noop <= TELEMETRY_NOOP_BUDGET_NS
        report["metrics"]["telemetry_noop_ns"] = {
            "measured": noop, "budgetNs": TELEMETRY_NOOP_BUDGET_NS,
            "ok": ok}
        report["ok"] = report["ok"] and ok
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--reference", default=DEFAULT_REFERENCE)
    ap.add_argument("--update", action="store_true",
                    help="re-measure and write the reference file")
    args = ap.parse_args(argv)
    tolerance = float(os.environ.get("PERF_GATE_TOLERANCE", "0.15"))
    # Pin the DEVICE path: at gate scale the cost model would host-place
    # every query and the gate would measure the host engine instead of
    # compile/bind/scan. (Set before any collect adopts the conf.)
    os.environ.setdefault("SRT_COST", "0")

    measured = measure()
    if args.update:
        measured["note"] = (
            "Committed perf-gate reference (scripts/perf_gate.py "
            "--update). calibration_s normalizes across machines.")
        with open(args.reference, "w") as f:
            json.dump(measured, f, indent=2, sort_keys=True)
            f.write("\n")
        sys.stdout.write(json.dumps({"updated": args.reference,
                                     **measured}) + "\n")
        return 0
    with open(args.reference) as f:
        reference = json.load(f)
    report = compare(measured, reference, tolerance)
    sys.stdout.write(json.dumps(report) + "\n")
    if not report["ok"]:
        bad = [n for n, m in report["metrics"].items() if not m["ok"]]
        sys.stderr.write(
            f"PERF GATE FAILED: {bad} regressed beyond "
            f"{tolerance:.0%} (normalized vs {args.reference})\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
