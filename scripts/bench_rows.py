"""Microbench: vectorized row materialization (ISSUE 4 satellite).

``Exec.collect`` ends every query with ``HostBatch.to_pylist()`` — pure
host CPU inside the wall clock. The old implementation looped rows with
per-element dtype branches; the new one converts each column in one
``ndarray.tolist()`` pass (plus sparse null patching) and decodes
strings off a single contiguous buffer. This script measures both on a
TPC-shaped batch (ints + floats + low-cardinality strings + nulls).

Run: python scripts/bench_rows.py [rows]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                            matrix_to_strings)


def reference_to_list(col):
    """The pre-vectorization per-row loop, verbatim."""
    out = []
    for i in range(col.num_rows):
        if not col.validity[i]:
            out.append(None)
        elif col.dtype.is_string:
            out.append(bytes(col.data[i]).decode("utf-8", "replace"))
        elif col.dtype.is_boolean:
            out.append(bool(col.data[i]))
        elif col.dtype.is_floating:
            out.append(float(col.data[i]))
        else:
            out.append(int(col.data[i]))
    return out


def make_batch(n: int) -> HostBatch:
    rng = np.random.default_rng(7)
    ints = HostColumn(dt.INT64, rng.integers(0, 1 << 40, n),
                      rng.random(n) > 0.02)
    floats = HostColumn(dt.FLOAT64, rng.random(n), rng.random(n) > 0.02)
    flags = np.array([b"AIR", b"RAIL", b"TRUCK", b"SHIP"], object)
    words = flags[rng.integers(0, 4, n)]
    lens = np.array([len(w) for w in words], np.int32)
    m = np.zeros((n, 5), np.uint8)
    for i, w in enumerate(words):
        m[i, :len(w)] = np.frombuffer(w, np.uint8)
    strs = matrix_to_strings(m, lens, rng.random(n) > 0.02)
    return HostBatch(("k", "x", "mode"), [ints, floats, strs])


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    hb = make_batch(n)

    t0 = time.perf_counter()
    old = [list(zip(*[reference_to_list(c) for c in hb.columns]))]
    t_old = time.perf_counter() - t0

    t0 = time.perf_counter()
    new = [hb.to_pylist()]
    t_new = time.perf_counter() - t0

    assert old[0] == new[0], "vectorized materialization diverged!"
    print(f"rows={n}  per-row loop: {t_old:.3f}s   "
          f"vectorized: {t_new:.3f}s   speedup: {t_old / t_new:.2f}x")


if __name__ == "__main__":
    main()
