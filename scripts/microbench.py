"""Microbenchmark engine kernels on the real device: where do q1's 14s go?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp


def _sync(out):
    """block_until_ready on the axon tunnel acks the dispatch, not the
    completion; pull one scalar to force a true round trip."""
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get(jnp.sum(leaves[0].ravel()[:1]))


def timeit(name, fn, *args, n=3):
    # First iteration is compile-inclusive (trace + XLA compile + run);
    # steady-state is the post-warmup min — report both so compile cost
    # and hot-path cost read separately (the kernel-cache story: a second
    # query pays only the steady-state number).
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)
    first = time.perf_counter() - t0
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    steady = min(ts)
    print(f"{name}: first={first*1000:.1f} ms (compile-inclusive) "
          f"steady={steady*1000:.1f} ms")
    return out


def main():
    import spark_rapids_tpu  # noqa: F401  (x64 config)
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
    from spark_rapids_tpu.ops import kernels

    cap = 1 << 20
    rng = np.random.default_rng(0)

    # Columns shaped like q1's lineitem batch
    f64 = lambda: jnp.asarray(rng.uniform(0, 1e5, cap))
    i32 = lambda: jnp.asarray(rng.integers(8000, 11000, cap), jnp.int32)
    s1 = jnp.asarray(rng.integers(65, 68, (cap, 8)), jnp.uint8)
    ones = jnp.ones((cap,), jnp.bool_)
    lens = jnp.full((cap,), 1, jnp.int32)

    nrows = jnp.asarray(cap - 7, jnp.int32)

    cols = [
        DeviceColumn(dt.STRING, s1, ones, lens),          # returnflag
        DeviceColumn(dt.STRING, s1, ones, lens),          # linestatus
        DeviceColumn(dt.FLOAT64, f64(), ones),            # quantity
        DeviceColumn(dt.FLOAT64, f64(), ones),            # extendedprice
        DeviceColumn(dt.FLOAT64, f64(), ones),            # discount
        DeviceColumn(dt.FLOAT64, f64(), ones),            # tax
        DeviceColumn(dt.DATE, i32(), ones),               # shipdate
    ]
    batch = DeviceBatch(tuple(cols), nrows)
    jax.block_until_ready(batch)

    # 1. fingerprint
    fp = jax.jit(lambda b: kernels.key_fingerprint(
        [b.columns[0], b.columns[1]], cap))
    timeit("fingerprint 2 str cols", fp, batch)

    # 2. single stable argsort u32
    keys = jnp.asarray(rng.integers(0, 2**32, cap, dtype=np.uint32))
    timeit("argsort u32 1M", jax.jit(lambda k: jnp.argsort(k, stable=True)),
           keys)

    # 3. group_ids (3 argsorts via fingerprint)
    def _gi(b):
        g_ = kernels.group_ids(b, [0, 1])
        return (g_.perm, g_.group_of_sorted, g_.num_groups, g_.group_leader)
    gi = jax.jit(_gi)
    gt = timeit("group_ids (2 str keys)", gi, batch)
    import types
    g = types.SimpleNamespace(perm=gt[0], group_of_sorted=gt[1],
                              num_groups=gt[2], group_leader=gt[3])

    # 4. segment_sum f64 1M
    gid = g.group_of_sorted
    vals = batch.columns[2].data
    timeit("segment_sum f64 1M->1M segs",
           jax.jit(lambda v, g_: jax.ops.segment_sum(v, g_,
                                                     num_segments=cap)),
           vals, gid)
    vals32 = vals.astype(jnp.float32)
    timeit("segment_sum f32 1M",
           jax.jit(lambda v, g_: jax.ops.segment_sum(v, g_,
                                                     num_segments=cap)),
           vals32, gid)

    # 5. filter compact on the 7-col batch
    keep = batch.columns[6].data <= 10000
    timeit("compact 7col 1M",
           jax.jit(lambda b, k: b.compact(k)), batch, keep)

    # 6. f64 multiply + sum (q1 projections)
    timeit("f64 mul x3 1M", jax.jit(
        lambda a, b, c: a * (1.0 - b) * (1.0 + c)),
        vals, batch.columns[4].data, batch.columns[5].data)

    # 7. gather 7 cols by perm
    perm = jnp.asarray(rng.permutation(cap), jnp.int32)
    timeit("gather 7col 1M", jax.jit(
        lambda b, p: b.gather(p, b.num_rows)), batch, perm)

    # 8. f64 argsort (join/sort path)
    timeit("argsort f64 1M", jax.jit(
        lambda v: jnp.argsort(v, stable=True)), vals)

    # 9. searchsorted 1M into 1M (join probe)
    sk = jnp.sort(keys)
    timeit("searchsorted 1M/1M", jax.jit(
        lambda s, q: jnp.searchsorted(s, q)), sk, keys)

    # 10. full agg update_batch (q1 partial agg analog)
    from spark_rapids_tpu.ops.aggregate import (
        AggSpec, Average, Count, HashAggregateExec, Sum)
    from spark_rapids_tpu.exprs.base import BoundReference as BR
    agg = HashAggregateExec.__new__(HashAggregateExec)
    agg.group_names = ("rf", "ls")
    agg.group_exprs = [BR(0, dt.STRING), BR(1, dt.STRING)]
    agg.aggs = [AggSpec("s1", Sum(BR(2, dt.FLOAT64))),
                AggSpec("s2", Sum(BR(3, dt.FLOAT64))),
                AggSpec("a1", Average(BR(2, dt.FLOAT64))),
                AggSpec("c", Count(BR(2, dt.FLOAT64)))]
    agg.mode = "partial"
    upd = jax.jit(agg._update_batch)
    timeit("q1-like update_batch 1M", upd, batch,
           jnp.asarray(0, jnp.int64))

    from spark_rapids_tpu.ops import kernel_cache as kc
    print("kernel cache:", kc.cache().stats())

    native_bench()
    trace_overhead()
    telemetry_overhead()


def native_bench():
    """Native Pallas kernels vs their jax.numpy twins — the >=2x-on-TPU
    claim, measured. On a CPU backend the kernels only run interpreted
    (SRT_NATIVE_INTERPRET=1), so sizes shrink and the numbers measure
    the interpreter, not the hardware; the speedup claim is only
    meaningful on a real TPU."""
    import jax.ops
    from spark_rapids_tpu.ops import kernel_cache as kc
    from spark_rapids_tpu.ops import native

    if not native.available():
        print("native kernels: unavailable on this backend "
              "(CPU no-ops to the jax.numpy fallback; set "
              "SRT_NATIVE_INTERPRET=1 to run them interpreted)")
        return
    on_tpu = jax.default_backend() == "tpu"
    cap = (1 << 20) if on_tpu else (1 << 12)
    rng = np.random.default_rng(7)
    print(f"native kernels vs jax.numpy twins (cap={cap}, "
          f"{'mosaic' if on_tpu else 'interpreter'}):")

    def duel(name, twin_fn, native_fn, *args):
        # Both sides compile through the kernel-cache interface, so the
        # bench measures exactly what serving traffic dispatches.
        twin = kc.lookup(f"microbench-{name}", ("twin", cap),
                         lambda: jax.jit(twin_fn))
        nat = kc.lookup(f"microbench-{name}", ("native", cap),
                        lambda: jax.jit(native_fn))
        timeit(f"  {name} twin", twin, *args)
        timeit(f"  {name} native", nat, *args)

    # 1. radix rank pass (one stable u32 argsort)
    keys = jnp.asarray(rng.integers(0, 2 ** 32, cap, dtype=np.uint32))
    duel("radix-pass",
         lambda k: jnp.argsort(k, stable=True),
         native.stable_argsort_u32, keys)

    # 2. join probe (double binary search over sorted u64 fingerprints)
    fp = jnp.sort(jnp.asarray(rng.integers(0, 2 ** 63, cap)
                              .astype(np.uint64)))
    q = jnp.asarray(rng.integers(0, 2 ** 63, cap).astype(np.uint64))
    duel("join-probe",
         lambda b, x: (jnp.searchsorted(b, x, side="left"),
                       jnp.searchsorted(b, x, side="right")),
         native.searchsorted_u64_pair, fp, q)

    # 3. RLE decode (sorted low-cardinality column)
    runs = 256
    run_vals = jnp.asarray(rng.normal(size=runs))
    ends = jnp.asarray(np.sort(rng.choice(
        np.arange(1, cap), runs - 1, replace=False)).astype(np.int32))
    run_ends = jnp.concatenate([ends, jnp.asarray([cap], jnp.int32)])
    nrows = jnp.asarray(cap, jnp.int32)

    def rle_twin(rv, re_, n):
        rows = jnp.arange(cap, dtype=jnp.int32)
        ridx = jnp.searchsorted(re_, rows, side="right").astype(jnp.int32)
        data = jnp.take(rv, ridx, mode="clip")
        return jnp.where(rows < n, data, jnp.zeros_like(data))

    duel("rle-decode", rle_twin,
         lambda rv, re_, n: native.rle_decode(rv, re_, cap, n),
         run_vals, run_ends, nrows)

    # 4. segment reduce (sorted gids, int64 sum + f64 min)
    gid = jnp.asarray(np.sort(rng.integers(0, cap // 4, cap))
                      .astype(np.int32))
    vals = jnp.asarray(rng.integers(-1000, 1000, cap).astype(np.int64))
    duel("segment-sum-i64",
         lambda v, g: jax.ops.segment_sum(v, g, num_segments=cap),
         lambda v, g: native.segment_sum_sorted(v, g, cap), vals, gid)
    # f32 so the duel also runs on a real TPU (f64 min/max falls back
    # there — the emulated f64 cannot bitcast into the total-order
    # domain).
    fvals = jnp.asarray(rng.normal(size=cap).astype(np.float32))
    duel("segment-min-f32",
         lambda v, g: jax.ops.segment_min(v, g, num_segments=cap),
         lambda v, g: native.segment_minmax_sorted(v, g, cap, "min"),
         fvals, gid)
    print("native counters:", native.counters())


def trace_overhead(calls: int = 200_000, budget_ns: float = 3000.0):
    """Bound the flight recorder's DISABLED span cost: the no-op path is
    one global load + a shared no-op context manager, so a per-partition
    dispatch wearing a span must cost nanoseconds when tracing is off.
    Prints ns/call for disabled vs enabled and asserts the disabled path
    stays under ``budget_ns`` (generous — real cost is tens of ns; the
    bound only exists to catch an accidental allocation/lock creeping
    into the hot path)."""
    from spark_rapids_tpu import monitoring

    def loop():
        t0 = time.perf_counter_ns()
        for _ in range(calls):
            with monitoring.span("bench", "device-compute"):
                pass
        return (time.perf_counter_ns() - t0) / calls

    monitoring.configure(False)
    disabled = min(loop() for _ in range(3))
    monitoring.configure(True, monitoring.LEVEL_OPERATOR)
    enabled = min(loop() for _ in range(3))
    monitoring.configure(False)
    monitoring.reset()
    print(f"trace span: disabled={disabled:.0f} ns/call "
          f"enabled={enabled:.0f} ns/call")
    assert disabled < budget_ns, \
        f"no-op trace span costs {disabled:.0f} ns/call (> {budget_ns})"


def telemetry_overhead(calls: int = 200_000, budget_ns: float = 3000.0):
    """Bound the metric registry's DISABLED cost: ``inc``/``observe``
    with metrics off is a single module-global load and return, so the
    instrumentation sites (collect funnel, scheduler admit/reject,
    query teardown) must cost nanoseconds in the default-off
    configuration. Same budget philosophy as :func:`trace_overhead` —
    generous vs the tens-of-ns real cost, present to catch a lock or
    allocation creeping ahead of the enabled check."""
    from spark_rapids_tpu.monitoring import telemetry

    def loop():
        t0 = time.perf_counter_ns()
        for _ in range(calls):
            telemetry.inc("srt_bench_counter")
            telemetry.observe("srt_bench_latency_ms", 1.0)
        return (time.perf_counter_ns() - t0) / calls

    telemetry.configure(False)
    disabled = min(loop() for _ in range(3))
    telemetry.configure(True)
    enabled = min(loop() for _ in range(3))
    telemetry.configure(False)
    telemetry.reset()
    print(f"telemetry inc+observe: disabled={disabled:.0f} ns/call "
          f"enabled={enabled:.0f} ns/call")
    assert disabled < budget_ns, \
        f"no-op telemetry costs {disabled:.0f} ns/call (> {budget_ns})"


def host_bench(n: int = 200_000, iters: int = 3):
    """Duel the vectorized host-engine kernels against the per-row
    python loops they replaced (the r06 host path). Each pair computes
    the same result; the loop twin is the removed implementation kept
    here as a benchmark fossil so the speedup stays measurable."""
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.host import (
        HostBatch, HostColumn, encode_key, strings_to_matrix)
    from spark_rapids_tpu.ops.sort import SortOrder, host_sort_indices
    from spark_rapids_tpu.exprs.base import BoundReference as Ref

    rng = np.random.default_rng(7)
    keys = rng.integers(0, n // 50, n)
    vals = rng.uniform(0, 1e4, n)
    words = np.array([b"alpha", b"bravo", b"charlie", b"delta", b"echo"],
                     dtype=object)
    svals = words[rng.integers(0, 5, n)]

    def duel(name, vec, loop):
        tv = min(_wall(vec) for _ in range(iters))
        tl = _wall(loop)    # once is enough, it's the slow one
        print(f"host {name}: vectorized={tv*1000:.1f} ms "
              f"loop={tl*1000:.1f} ms speedup={tl/max(tv,1e-9):.1f}x")

    # 1. string column -> byte matrix (scan/shuffle boundary).
    def enc_vec():
        col = HostColumn(dt.STRING, svals.copy(),
                         np.ones(n, np.bool_))
        return strings_to_matrix(col)

    def enc_loop():
        lens = np.zeros(n, np.int32)
        w = max(len(v) for v in svals)
        m = np.zeros((n, w), np.uint8)
        for i, v in enumerate(svals):
            lens[i] = len(v)
            m[i, :len(v)] = np.frombuffer(v, np.uint8)
        return m, lens

    duel("string-encode", enc_vec, enc_loop)

    # 2. order-preserving sort keys: lexsort vs python sorted.
    hb = HostBatch(("k", "v"), [
        HostColumn(dt.INT64, keys.astype(np.int64), np.ones(n, np.bool_)),
        HostColumn(dt.FLOAT64, vals, np.ones(n, np.bool_))])
    orders = [SortOrder(Ref(1, dt.FLOAT64), ascending=False),
              SortOrder(Ref(0, dt.INT64))]

    def sort_vec():
        return host_sort_indices(hb, orders)

    def sort_loop():
        rows = list(zip(vals.tolist(), keys.tolist(), range(n)))
        rows.sort(key=lambda r: (-r[0], r[1]))
        return [r[2] for r in rows]

    duel("sort-keys", sort_vec, sort_loop)

    # 3. grouped sum: encode+lexsort+reduceat vs dict accumulate.
    def agg_vec():
        kc = HostColumn(dt.INT64, keys.astype(np.int64),
                        np.ones(n, np.bool_))
        code = encode_key(kc)
        order = np.argsort(code, kind="stable")
        sc = code[order]
        flags = np.ones(n, np.bool_)
        flags[1:] = sc[1:] != sc[:-1]
        starts = np.flatnonzero(flags)
        return np.add.reduceat(vals[order], starts)

    def agg_loop():
        acc = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            acc[k] = acc.get(k, 0.0) + v
        return acc

    duel("group-sum", agg_vec, agg_loop)

    # 4. hash-join probe: sorted build + searchsorted vs dict probe.
    bk = np.unique(keys)[: max(1, len(np.unique(keys)) // 2)]

    def join_vec():
        order = np.argsort(bk, kind="stable")
        blo = np.searchsorted(bk[order], keys, "left")
        bhi = np.searchsorted(bk[order], keys, "right")
        return np.flatnonzero(bhi > blo)

    def join_loop():
        bset = set(bk.tolist())
        return [i for i, k in enumerate(keys.tolist()) if k in bset]

    duel("join-probe", join_vec, join_loop)

    # 5. fused filter mask-then-gather vs per-row append.
    def filt_vec():
        keep = vals < 5e3
        return vals[keep], keys[keep]

    def filt_loop():
        ov, ok_ = [], []
        for i in range(n):
            if vals[i] < 5e3:
                ov.append(vals[i])
                ok_.append(keys[i])
        return ov, ok_

    duel("filter-gather", filt_vec, filt_loop)


def _wall(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "host":
        host_bench()
    else:
        main()
