"""Host-sync profiler: on this tunneled chip a device->host read costs a
~70ms round trip, so query wall time ~= device compute + 70ms * syncs.

Rebased on the flight recorder (spark_rapids_tpu/monitoring/): the sync
funnels (jax.device_get, ArrayImpl.__array__/__int__/__float__/__bool__)
are wrapped by monitoring/syncs.py, each blocking read records a ``sync``
span with its engine call sites, and this script aggregates the span
stream per site — so the sync attribution interleaves with the
operator/upload/shuffle spans on the same timeline (trace_export shows
each round trip INSIDE the operator that paid for it) instead of living
in a private ad-hoc timer table.

Usage: python scripts/syncprof.py [q1|q6|q3|q5|q67|xbb_q5|repart] [iters]
Env: TPCH_SF (default 1.0), SYNCPROF_CPU=1 for the hermetic CPU backend.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("SYNCPROF_CPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

import jax  # noqa: E402,F401


def report(wall: float, query_id=None):
    from spark_rapids_tpu.monitoring.syncs import sync_stats
    stats = sync_stats(query_id)
    total = sum(secs for _, secs in stats.values())
    n = sum(cnt for cnt, _ in stats.values())
    print(f"\n  syncs: {n} totalling {total:.3f}s "
          f"({100 * total / max(wall, 1e-9):.0f}% of wall)")
    for site, (cnt, secs) in sorted(stats.items(), key=lambda kv: -kv[1][1]):
        print(f"  {secs:8.3f}s  x{cnt:<5d} {site}")


def main():
    qn = sys.argv[1] if len(sys.argv) > 1 else "q3"
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    from spark_rapids_tpu import monitoring
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import suites, tpch
    from spark_rapids_tpu.monitoring import syncs

    syncs.install()

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    if qn in tpch.QUERIES:
        mod, ddir = tpch, os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    else:
        mod, ddir = suites, os.environ.get("SUITES_DIR",
                                           f"/tmp/srt_suites_sf{sf:g}")
    mod.generate(ddir, scale=sf)

    session = TpuSession()
    session.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    session.set("spark.rapids.sql.hasNans", False)
    if os.environ.get("SRT_SHUFFLE_PARTS"):
        session.set("spark.rapids.sql.shuffle.partitions",
                    int(os.environ["SRT_SHUFFLE_PARTS"]))
    df = mod.QUERIES[qn](session, ddir)

    t0 = time.perf_counter()
    df.collect()
    print(f"warmup: {time.perf_counter() - t0:.2f}s")

    # Sync attribution needs the kernel level; the ring bound keeps even
    # a sync-storm run to a bounded window.
    session.set("spark.rapids.sql.trace.enabled", True)
    session.set("spark.rapids.sql.trace.level", "kernel")
    for it in range(iters):
        monitoring.reset()
        t0 = time.perf_counter()
        rows = df.collect()
        wall = time.perf_counter() - t0
        print(f"\n=== {qn} iter {it}: wall {wall:.3f}s, {len(rows)} rows ===")
        report(wall)


if __name__ == "__main__":
    main()
