"""Host-sync profiler: on this tunneled chip a device->host read costs a
~70ms round trip, so query wall time ~= device compute + 70ms * syncs.
This wraps every sync funnel (jax.device_get, ArrayImpl.__array__ /
__int__ / __float__ / __bool__) and attributes blocking time to the
engine call site — the "where do the round trips come from" view that
jax.profiler traces don't give on a remote backend.

Usage: python scripts/syncprof.py [q1|q6|q3|q5|q67|xbb_q5|repart] [iters]
Env: TPCH_SF (default 1.0), SYNCPROF_CPU=1 for the hermetic CPU backend.
"""
import collections
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("SYNCPROF_CPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

import jax  # noqa: E402

_STATS = collections.defaultdict(lambda: [0, 0.0])   # site -> [count, secs]
_ENABLED = [False]


def _site() -> str:
    """Innermost TWO spark_rapids_tpu frames (helper + its caller)."""
    frames = []
    for f in reversed(traceback.extract_stack()):
        if "spark_rapids_tpu" in f.filename and "syncprof" not in f.filename:
            short = f.filename.split("spark_rapids_tpu/")[-1]
            frames.append(f"{short}:{f.lineno} {f.name}")
            if len(frames) == 2:
                break
    return " <- ".join(frames) if frames else "<outside engine>"


def _wrap(fn, label):
    def wrapper(*a, **k):
        if not _ENABLED[0]:
            return fn(*a, **k)
        t0 = time.perf_counter()
        out = fn(*a, **k)
        dt = time.perf_counter() - t0
        s = _STATS[f"{label} @ {_site()}"]
        s[0] += 1
        s[1] += dt
        return out
    return wrapper


def install():
    from jax._src import array as _arr
    jax.device_get = _wrap(jax.device_get, "device_get")
    for m in ("__array__", "__int__", "__float__", "__bool__", "__index__"):
        if hasattr(_arr.ArrayImpl, m):
            setattr(_arr.ArrayImpl, m,
                    _wrap(getattr(_arr.ArrayImpl, m), m))


def report(wall: float):
    total = sum(s[1] for s in _STATS.values())
    n = sum(s[0] for s in _STATS.values())
    print(f"\n  syncs: {n} totalling {total:.3f}s "
          f"({100 * total / max(wall, 1e-9):.0f}% of wall)")
    for site, (cnt, secs) in sorted(_STATS.items(), key=lambda kv: -kv[1][1]):
        print(f"  {secs:8.3f}s  x{cnt:<5d} {site}")


def main():
    qn = sys.argv[1] if len(sys.argv) > 1 else "q3"
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 1
    install()
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import suites, tpch

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    if qn in tpch.QUERIES:
        mod, ddir = tpch, os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    else:
        mod, ddir = suites, os.environ.get("SUITES_DIR",
                                           f"/tmp/srt_suites_sf{sf:g}")
    mod.generate(ddir, scale=sf)

    session = TpuSession()
    session.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    session.set("spark.rapids.sql.hasNans", False)
    if os.environ.get("SRT_SHUFFLE_PARTS"):
        session.set("spark.rapids.sql.shuffle.partitions",
                    int(os.environ["SRT_SHUFFLE_PARTS"]))
    df = mod.QUERIES[qn](session, ddir)

    t0 = time.perf_counter()
    df.collect()
    print(f"warmup: {time.perf_counter() - t0:.2f}s")

    for it in range(iters):
        _STATS.clear()
        _ENABLED[0] = True
        t0 = time.perf_counter()
        rows = df.collect()
        wall = time.perf_counter() - t0
        _ENABLED[0] = False
        print(f"\n=== {qn} iter {it}: wall {wall:.3f}s, {len(rows)} rows ===")
        report(wall)


if __name__ == "__main__":
    main()
