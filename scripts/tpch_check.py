"""Dev loop: run TPC-H queries on the CPU backend vs the pandas oracle.

Usage: python scripts/tpch_check.py [q2 q4 ... | all] — SF 0.02 data in
/tmp/tpch_check (regenerated when the datagen version bumps).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
import jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")


def main():
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import tpch
    d = os.environ.get("TPCH_CHECK_DIR", "/tmp/tpch_check")
    tpch.generate(d, scale=float(os.environ.get("TPCH_CHECK_SF", "0.02")),
                  files_per_table=4)
    names = sys.argv[1:] or ["all"]
    if names == ["all"]:
        names = list(tpch.QUERIES)
    failed = []
    for qn in names:
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.hasNans", False)
        t0 = time.perf_counter()
        try:
            got = tpch.QUERIES[qn](s, d).collect()
            want = tpch.pandas_query(qn, d)
            ok = tpch.check_result(qn, got, want)
        except Exception as e:
            print(f"{qn}: EXCEPTION {type(e).__name__}: {e}")
            failed.append(qn)
            continue
        status = "ok" if ok else "MISMATCH"
        print(f"{qn}: {status} rows={len(got)} "
              f"({time.perf_counter() - t0:.1f}s)")
        if not ok:
            failed.append(qn)
            for r in got[:3]:
                print("   got ", r)
            for r in want[:3]:
                print("   want", r)
    if failed:
        print("FAILED:", ",".join(failed))
        sys.exit(1)
    print("all ok")


if __name__ == "__main__":
    main()
