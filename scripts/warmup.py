"""Compile-warmup pack: pre-populate the plan cache + persistent kernel
cache from a recorded shape manifest, so a FRESH process serves its
first query without the 7-26s cold-compile cliff (VERDICT weak #10).

A shape manifest is a JSON list of entries::

    [{"module": "tpch",   "query": "q6", "dir": "/data/tpch"},
     {"module": "suites", "query": "q67", "dir": "/data/suites"}]

``module`` names a benchmarks module exposing ``QUERIES`` (tpch or
suites). With no --manifest, the default pack is the 11-query bench
suite over TPCH_DIR/SUITES_DIR (generated at WARMUP_SF if absent —
warmup compiles against the REAL data's batch capacities, which is what
makes the persistent-cache entries reusable by serving traffic).

Replaying a shape does one ``prepare()`` (template into the plan cache)
and one ``collect()`` (kernels traced + compiled + serialized into
``spark.rapids.sql.kernelCache.persistentDir``). A process restarted
with the same persistentDir then deserializes (~ms) instead of
recompiling (~s), and its first collect of each shape is bind-only.

Usage::

    python scripts/warmup.py [--manifest shapes.json]
        [--persistent-dir /var/cache/srt-kernels]
        [--dump-manifest shapes.json]

Prints one JSON line: per-shape seconds, plan-cache/kernel-cache/
persistent-cache counter deltas.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

DEFAULT_QUERIES = {
    "tpch": ["q1", "q6", "q3", "q5", "q12", "q14"],
    "suites": ["repart", "q67", "xbb_q5", "ds_q3", "xbb_q12"],
}


def default_manifest():
    sf = float(os.environ.get("WARMUP_SF", "0.01"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    suites_dir = os.environ.get("SUITES_DIR", f"/tmp/srt_suites_sf{sf:g}")
    out = []
    for mod, queries in DEFAULT_QUERIES.items():
        d = tpch_dir if mod == "tpch" else suites_dir
        out.extend({"module": mod, "query": q, "dir": d} for q in queries)
    return out


def _ensure_data(manifest):
    """Generate any missing default data dirs (real serving deployments
    point the manifest at their own datasets)."""
    from spark_rapids_tpu.benchmarks import suites, tpch
    sf = float(os.environ.get("WARMUP_SF", "0.01"))
    for mod, gen in (("tpch", tpch.generate), ("suites", suites.generate)):
        dirs = {e["dir"] for e in manifest if e["module"] == mod}
        for d in dirs:
            if not os.path.isdir(d):
                gen(d, scale=sf)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--manifest", help="shape manifest JSON to replay")
    ap.add_argument("--dump-manifest",
                    help="write the default shape manifest here and exit")
    ap.add_argument("--persistent-dir",
                    default=os.environ.get(
                        "SRT_KERNEL_CACHE_DIR",
                        "/tmp/srt_bench_kernel_cache"),
                    help="persistent kernel cache directory (empty "
                         "disables the on-disk half)")
    args = ap.parse_args(argv)

    if args.dump_manifest:
        with open(args.dump_manifest, "w") as f:
            json.dump(default_manifest(), f, indent=2)
        print(f"wrote {args.dump_manifest}")
        return 0

    if args.manifest:
        with open(args.manifest) as f:
            manifest = json.load(f)
    else:
        manifest = default_manifest()

    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.benchmarks import suites, tpch
    from spark_rapids_tpu.ops import kernel_cache as kc
    from spark_rapids_tpu.plan import plan_cache as pc

    mods = {"tpch": tpch, "suites": suites}
    _ensure_data(manifest)

    def session():
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.hasNans", False)
        if args.persistent_dir:
            s.set("spark.rapids.sql.kernelCache.persistentDir",
                  args.persistent_dir)
        return s

    kc0 = kc.cache().stats()
    pc0 = pc.counters()
    shapes = {}
    t0 = time.perf_counter()
    for entry in manifest:
        mod = mods[entry["module"]]
        qname = entry["query"]
        label = f"{entry['module']}:{qname}"
        t = time.perf_counter()
        try:
            df = mod.QUERIES[qname](session(), entry["dir"])
            df.prepare()            # template -> plan cache
            df.collect()            # kernels -> (persistent) compile cache
            shapes[label] = round(time.perf_counter() - t, 3)
        except Exception as e:      # one bad shape must not kill the pack
            shapes[label] = f"error: {type(e).__name__}: {e}"
    kc1 = kc.cache().stats()
    report = {
        "shapes": shapes,
        "total_s": round(time.perf_counter() - t0, 3),
        "plan_cache_entries": pc.cache().stats()["entries"],
        "plan_cache_counters": {
            k: pc.counters().get(k, 0) - pc0.get(k, 0)
            for k in ("planCacheHits", "planCacheMisses")},
        "kernel_compiles": kc1["misses"] - kc0["misses"],
        "persistent_dir": args.persistent_dir or None,
        "persistent_hits":
            kc1.get("persistentCacheHits", 0)
            - kc0.get("persistentCacheHits", 0),
        "persistent_misses":
            kc1.get("persistentCacheMisses", 0)
            - kc0.get("persistentCacheMisses", 0),
    }
    sys.stdout.write(json.dumps(report) + "\n")
    errs = [v for v in shapes.values() if isinstance(v, str)]
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
