"""Columnar runtime round-trip tests (ref strategy: SURVEY.md §4 tier 1,
RapidsDeviceMemoryStoreSuite-style pure-unit tests, no cluster)."""

import jax.numpy as jnp
import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar.batch import (
    bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import (
    HostBatch, HostColumn, device_to_host, host_to_device)


def make_host(schema, data):
    return HostBatch.from_pydict(schema, data)


def test_bucket_capacity():
    # Ladder rungs at 2^k and 3*2^(k-1): 8, 12, 16, 24, 32, ...
    assert bucket_capacity(0) == 8
    assert bucket_capacity(8) == 8
    assert bucket_capacity(9) == 12
    assert bucket_capacity(13) == 16
    assert bucket_capacity(17) == 24
    assert bucket_capacity(1000) == 1024
    assert bucket_capacity(1025) == 1536
    assert bucket_capacity(750_000) == 768 * 1024


@pytest.mark.parametrize("dtype,values", [
    (srt.INT32, [1, None, 3, -7]),
    (srt.INT64, [2**40, None, -1, 0]),
    (srt.FLOAT64, [1.5, float("nan"), None, -0.0]),
    (srt.FLOAT32, [1.25, None, 3.5, 0.0]),
    (srt.BOOL, [True, False, None, True]),
    (srt.INT8, [1, -128, 127, None]),
    (srt.DATE, [0, 18628, None, -365]),
    (srt.TIMESTAMP, [0, 1_600_000_000_000_000, None, -1]),
    (srt.STRING, ["hello", "", None, "wörld"]),
])
def test_round_trip(dtype, values):
    hb = make_host([("c", dtype)], {"c": values})
    db = host_to_device(hb)
    assert db.capacity == bucket_capacity(len(values))
    back = device_to_host(db, names=("c",))
    got = back.columns[0].to_list()
    for g, v in zip(got, values):
        if v is None:
            assert g is None
        elif isinstance(v, float) and v != v:
            assert g != g  # NaN
        else:
            assert g == v


def test_compact_filter():
    hb = make_host([("a", srt.INT32), ("s", srt.STRING)],
                   {"a": [1, 2, None, 4, 5], "s": ["x", "yy", "zzz", None, "v"]})
    db = host_to_device(hb)
    keep = jnp.asarray([True, False, True, True, False, True, True, True])
    out = db.compact(keep)
    assert int(out.num_rows) == 3
    back = device_to_host(out, names=("a", "s"))
    assert back.columns[0].to_list() == [1, None, 4]
    assert back.columns[1].to_list() == ["x", "zzz", None]


def test_head_limit():
    hb = make_host([("a", srt.INT64)], {"a": list(range(6))})
    db = host_to_device(hb)
    out = db.head(4)
    assert int(out.live_count()) == 4
    assert device_to_host(out).columns[0].to_list() == [0, 1, 2, 3]
    out2 = db.head(100)
    assert int(out2.live_count()) == 6


def test_concat_batches():
    h1 = make_host([("a", srt.INT32), ("s", srt.STRING)],
                   {"a": [1, None], "s": ["aa", "b"]})
    h2 = make_host([("a", srt.INT32), ("s", srt.STRING)],
                   {"a": [3], "s": [None]})
    h3 = make_host([("a", srt.INT32), ("s", srt.STRING)],
                   {"a": [4, 5, 6], "s": ["longer-string-here", "e", "f"]})
    b1, b2, b3 = (host_to_device(h) for h in (h1, h2, h3))
    out = concat_batches([b1, b2, b3], capacity=32)
    assert int(out.num_rows) == 6
    back = device_to_host(out, names=("a", "s"))
    assert back.columns[0].to_list() == [1, None, 3, 4, 5, 6]
    assert back.columns[1].to_list() == ["aa", "b", None,
                                         "longer-string-here", "e", "f"]


def test_gather():
    hb = make_host([("a", srt.INT32)], {"a": [10, 20, 30, None]})
    db = host_to_device(hb)
    idx = jnp.asarray([3, 1, 0, 0, 0, 0, 0, 0])
    out = db.gather(idx, jnp.asarray(3, jnp.int32))
    back = device_to_host(out)
    assert back.columns[0].to_list() == [None, 20, 10]


def test_config_docs():
    from spark_rapids_tpu import config
    doc = config.generate_docs()
    assert "spark.rapids.sql.enabled" in doc
    assert "spark.rapids.sql.batchSizeBytes" in doc
    c = config.TpuConf({"spark.rapids.sql.enabled": "false"})
    assert c.sql_enabled is False
    assert config.TpuConf().sql_enabled is True
    assert c.get(config.CONCURRENT_TPU_TASKS) == 2
