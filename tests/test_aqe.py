"""AQE-lite (VERDICT r3 item 9): stats-driven auto join strategy
(autoBroadcastJoinThreshold over parquet footer estimates) and
post-shuffle partition coalescing from exact materialized sizes
(GpuCustomShuffleReaderExec.scala:132 analog)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.ops.join import (
    BroadcastHashJoinExec, ShuffledHashJoinExec)
from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
from spark_rapids_tpu.plan.logical import agg_count, agg_sum, col


@pytest.fixture(scope="module")
def pq_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("aqe_pq")
    rng = np.random.default_rng(3)
    big = pa.table({
        "k": rng.integers(0, 50, 20_000, dtype=np.int64),
        "v": rng.uniform(0, 1, 20_000),
    })
    small = pa.table({
        "dk": np.arange(50, dtype=np.int64),
        "w": rng.uniform(0, 1, 50),
    })
    papq.write_table(big, os.path.join(d, "big.parquet"))
    papq.write_table(small, os.path.join(d, "small.parquet"))
    return str(d)


def _join(session, pq_dir):
    big = session.read.parquet(os.path.join(pq_dir, "big.parquet"))
    small = session.read.parquet(os.path.join(pq_dir, "small.parquet"))
    return big.join_on(small, ["k"], ["dk"])


def _find(root, cls):
    out = []

    def walk(n):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(root)
    return out


class TestAutoJoinStrategy:
    def test_small_build_side_broadcasts(self, pq_dir):
        s = TpuSession()
        phys = _join(s, pq_dir)._physical()
        assert _find(phys.root, BroadcastHashJoinExec)
        assert "auto join strategy -> broadcast" in phys.explain()

    def test_large_build_side_shuffles(self, pq_dir):
        s = TpuSession()
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", 64)
        phys = _join(s, pq_dir)._physical()
        joins = _find(phys.root, ShuffledHashJoinExec)
        joins = [j for j in joins
                 if not isinstance(j, BroadcastHashJoinExec)]
        assert joins
        assert "auto join strategy -> shuffle" in phys.explain()

    def test_threshold_minus_one_disables_broadcast(self, pq_dir):
        s = TpuSession()
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
        phys = _join(s, pq_dir)._physical()
        assert not _find(phys.root, BroadcastHashJoinExec)

    def test_both_strategies_agree(self, pq_dir):
        s1 = TpuSession()
        s2 = TpuSession()
        s2.set("spark.rapids.sql.autoBroadcastJoinThreshold", 64)
        r1 = sorted(_join(s1, pq_dir).collect())
        r2 = sorted(_join(s2, pq_dir).collect())
        assert r1 == r2


class TestPartitionCoalescing:
    def _agg(self, session):
        df = session.create_dataframe(
            {"k": list(range(100)) * 4, "v": list(range(400))},
            [("k", srt.INT64), ("v", srt.INT64)], num_partitions=4)
        return df.group_by("k").agg(agg_sum(col("v")).alias("s"),
                                    agg_count().alias("n"))

    def test_undersized_partitions_merge(self):
        s = TpuSession()
        q = self._agg(s)
        phys = q._physical()
        rows = phys.collect()
        assert len(rows) == 100
        # The aggregate exchange coalesced its tiny reduce partitions.
        from spark_rapids_tpu.ops.base import ExecContext
        ctx = ExecContext(phys.conf)
        ctx.cache["engine"] = "device"
        phys.root.collect(ctx, device=True)
        ex = _find(phys.root, ShuffleExchangeExec)
        coalescable = [e for e in ex if e.allow_coalesce]
        assert coalescable
        assert any(e.num_partitions(ctx)
                   < e.partitioning.num_partitions for e in coalescable)
        ctx.close()

    def test_disabled_by_conf(self):
        s = TpuSession()
        s.set("spark.rapids.sql.aqe.coalescePartitions.enabled", False)
        phys = self._agg(s)._physical()
        from spark_rapids_tpu.ops.base import ExecContext
        ctx = ExecContext(phys.conf)
        ctx.cache["engine"] = "device"
        phys.root.collect(ctx, device=True)
        ex = _find(phys.root, ShuffleExchangeExec)
        for e in ex:
            assert e.num_partitions(ctx) == e.partitioning.num_partitions
        ctx.close()

    def test_results_identical_with_and_without(self):
        s1 = TpuSession()
        s2 = TpuSession()
        s2.set("spark.rapids.sql.aqe.coalescePartitions.enabled", False)
        assert sorted(self._agg(s1).collect()) == \
            sorted(self._agg(s2).collect())
