"""Window function tests (ref: WindowFunctionSuite, TPC-DS q67 shape)."""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.exprs.base import BoundReference as Ref
from spark_rapids_tpu.ops.sort import SortOrder
from spark_rapids_tpu.ops.window import (
    DenseRank, Lag, Lead, Rank, RowNumber, WindowAgg, WindowExec,
    WindowExprSpec, WindowFrame, WindowSpec)

from test_ops import compare_engines, source


SCHEMA = [("g", dt.STRING), ("o", dt.INT32), ("v", dt.INT32)]
DATA = {
    "g": ["a", "a", "a", "b", "b", "a", "b", None],
    "o": [1, 2, 2, 1, 3, 4, 3, 1],
    "v": [10, 20, None, 5, 15, 40, 25, 7],
}


def wspec():
    return WindowSpec([Ref(0, dt.STRING)], [SortOrder(Ref(1, dt.INT32))])


class TestWindowRanking:
    def test_row_number_rank_dense(self):
        plan = WindowExec(
            source(SCHEMA, DATA, batches_per_partition=2),
            [WindowExprSpec("rn", RowNumber(), wspec()),
             WindowExprSpec("rk", Rank(), wspec()),
             WindowExprSpec("dr", DenseRank(), wspec())])
        out = compare_engines(plan, sort_result=True)
        bykey = {(r[0], r[1], r[2]): r[3:] for r in out}
        # group a ordered by o: (1,10)rn1 (2,20)rn2 (2,None)rn3 (4,40)rn4
        assert bykey[("a", 1, 10)][0] == 1
        rn_for_o2 = {bykey[("a", 2, 20)][0], bykey[("a", 2, None)][0]}
        assert rn_for_o2 == {2, 3}
        assert bykey[("a", 2, 20)][1] == 2       # rank with tie
        assert bykey[("a", 2, None)][1] == 2
        assert bykey[("a", 4, 40)][1] == 4       # rank skips
        assert bykey[("a", 4, 40)][2] == 3       # dense_rank does not

    def test_lead_lag(self):
        plan = WindowExec(
            source(SCHEMA, DATA),
            [WindowExprSpec("ld", Lead(Ref(2, dt.INT32), 1), wspec()),
             WindowExprSpec("lg", Lag(Ref(2, dt.INT32), 1), wspec())])
        out = compare_engines(plan, sort_result=True)
        bykey = {(r[0], r[1], r[2]): r[3:] for r in out}
        assert bykey[("b", 1, 5)][1] is None     # lag at partition start
        assert bykey[("a", 1, 10)][1] is None
        # b ordered: (1,5) (3,15)/(3,25)... ties among o=3 make lead
        # order-dependent between them; check the stable ones:
        assert bykey[("a", 4, 40)][0] is None    # lead at partition end


class TestWindowAggs:
    def test_whole_partition_agg(self):
        spec = WindowSpec([Ref(0, dt.STRING)], [])
        plan = WindowExec(
            source(SCHEMA, DATA, batches_per_partition=3),
            [WindowExprSpec("s", WindowAgg(
                "sum", Ref(2, dt.INT32),
                WindowFrame(None, None)), spec),
             WindowExprSpec("c", WindowAgg(
                 "count", Ref(2, dt.INT32),
                 WindowFrame(None, None)), spec),
             WindowExprSpec("mx", WindowAgg(
                 "max", Ref(2, dt.INT32),
                 WindowFrame(None, None)), spec)])
        out = compare_engines(plan, sort_result=True)
        for r in out:
            if r[0] == "a":
                assert r[3] == 70 and r[4] == 3 and r[5] == 40
            elif r[0] == "b":
                assert r[3] == 45 and r[4] == 3 and r[5] == 25
            else:
                assert r[3] == 7 and r[4] == 1 and r[5] == 7

    def test_running_sum_with_peers(self):
        # Spark default frame: RANGE UNBOUNDED..CURRENT (ties included).
        plan = WindowExec(
            source(SCHEMA, DATA),
            [WindowExprSpec("rs", WindowAgg(
                "sum", Ref(2, dt.INT32),
                WindowFrame(None, 0, running_with_peers=True)), wspec())])
        out = compare_engines(plan, sort_result=True)
        bykey = {(r[0], r[1], r[2]): r[3] for r in out}
        assert bykey[("a", 1, 10)] == 10
        # peers o=2 (20 and None) both see 10+20 = 30
        assert bykey[("a", 2, 20)] == 30
        assert bykey[("a", 2, None)] == 30
        assert bykey[("a", 4, 40)] == 70

    def test_rows_frame_sliding(self):
        # ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING moving sum.
        plan = WindowExec(
            source(SCHEMA, DATA),
            [WindowExprSpec("ms", WindowAgg(
                "sum", Ref(2, dt.INT32), WindowFrame(1, 1)), wspec())])
        compare_engines(plan, sort_result=True)

    def test_running_min_max(self):
        plan = WindowExec(
            source(SCHEMA, DATA),
            [WindowExprSpec("rmin", WindowAgg(
                "min", Ref(2, dt.INT32),
                WindowFrame(None, 0, running_with_peers=True)), wspec()),
             WindowExprSpec("rmax", WindowAgg(
                 "max", Ref(2, dt.INT32),
                 WindowFrame(None, 0, running_with_peers=True)), wspec())])
        out = compare_engines(plan, sort_result=True)
        bykey = {(r[0], r[1], r[2]): r[3:] for r in out}
        assert bykey[("a", 4, 40)] == (10, 40)
        assert bykey[("a", 1, 10)] == (10, 10)

    def test_running_avg_float(self):
        schema = [("g", dt.INT32), ("o", dt.INT32), ("x", dt.FLOAT64)]
        data = {"g": [1, 1, 1, 2], "o": [1, 2, 3, 1],
                "x": [1.0, 2.0, None, 8.0]}
        plan = WindowExec(
            source(schema, data),
            [WindowExprSpec("ra", WindowAgg(
                "avg", Ref(2, dt.FLOAT64),
                WindowFrame(None, 0, running_with_peers=True)),
                WindowSpec([Ref(0, dt.INT32)],
                           [SortOrder(Ref(1, dt.INT32))]))])
        out = compare_engines(plan, approx_float=True, sort_result=True)
        bykey = {(r[0], r[1]): r[3] for r in out}
        assert bykey[(1, 1)] == 1.0 and bykey[(1, 2)] == 1.5
        assert bykey[(1, 3)] == 1.5  # null adds nothing
        assert bykey[(2, 1)] == 8.0


class TestStringWindows:
    """String-typed window results (lead/lag + whole-partition min/max) —
    device gathers the winner row's bytes instead of running strings
    through the numeric kernel."""

    SSCHEMA = [("g", dt.STRING), ("o", dt.INT32), ("s", dt.STRING)]
    SDATA = {
        "g": ["a", "a", "a", "b", "b", "a", "b", None, "c", "c",
              "d", "d"],
        "o": [1, 2, 3, 1, 2, 4, 3, 1, 1, 2, 1, 2],
        "s": ["mango", None, "apple", "zeta", "kiwi", "pear", None,
              "solo", None, None, "", "é"],
    }

    def test_string_lead_lag(self):
        plan = WindowExec(
            source(self.SSCHEMA, self.SDATA, batches_per_partition=2),
            [WindowExprSpec("ld", Lead(Ref(2, dt.STRING), 1), wspec()),
             WindowExprSpec("lg", Lag(Ref(2, dt.STRING), 1), wspec())])
        out = compare_engines(plan, sort_result=True)
        bykey = {(r[0], r[1]): tuple(r[3:]) for r in out}
        # partition a ordered by o: mango, None, apple, pear
        assert bykey[("a", 1)] == (None, None)
        assert bykey[("a", 2)] == ("apple", "mango")
        assert bykey[("a", 3)] == ("pear", None)
        assert bykey[("a", 4)] == (None, "apple")
        # partition b: zeta, kiwi, None
        assert bykey[("b", 1)] == ("kiwi", None)
        assert bykey[("b", 3)] == (None, "kiwi")
        # null partition key is its own single-row partition
        assert bykey[(None, 1)] == (None, None)

    def test_string_whole_partition_minmax(self):
        spec = WindowSpec([Ref(0, dt.STRING)], [])
        frame = WindowFrame(None, None)
        plan = WindowExec(
            source(self.SSCHEMA, self.SDATA, batches_per_partition=3),
            [WindowExprSpec("mn", WindowAgg(
                "min", Ref(2, dt.STRING), frame), spec),
             WindowExprSpec("mx", WindowAgg(
                 "max", Ref(2, dt.STRING), frame), spec)])
        out = compare_engines(plan, sort_result=True)
        for r in out:
            want = {"a": ("apple", "pear"), "b": ("kiwi", "zeta"),
                    "c": (None, None),          # all-null partition
                    "d": ("", "é"),        # empty + multibyte
                    None: ("solo", "solo")}[r[0]]
            assert tuple(r[3:]) == want, r

    def test_string_window_datagen(self):
        from data_gen import RepeatSeqGen, StringGen, gen_batch
        b = gen_batch(
            [("g", RepeatSeqGen(StringGen(), length=7)),
             ("s", StringGen())], 120, seed=11)
        rows = b.to_pylist()
        data = {"g": [r[0] for r in rows],
                # unique order keys: lead/lag with order ties is
                # tie-break-dependent and not comparable across engines
                "o": list(range(len(rows))),
                "s": [r[1] for r in rows]}
        spec = WindowSpec([Ref(0, dt.STRING)],
                          [SortOrder(Ref(1, dt.INT32))])
        pspec = WindowSpec([Ref(0, dt.STRING)], [])
        plan = WindowExec(
            source(self.SSCHEMA, data, batches_per_partition=3),
            [WindowExprSpec("ld", Lead(Ref(2, dt.STRING), 1), spec),
             WindowExprSpec("lg", Lag(Ref(2, dt.STRING), 2), spec),
             WindowExprSpec("mn", WindowAgg(
                 "min", Ref(2, dt.STRING), WindowFrame(None, None)),
                 pspec),
             WindowExprSpec("mx", WindowAgg(
                 "max", Ref(2, dt.STRING), WindowFrame(None, None)),
                 pspec)])
        compare_engines(plan, sort_result=True)

    def test_string_running_minmax_unsupported(self):
        plan = WindowExec(
            source(self.SSCHEMA, self.SDATA),
            [WindowExprSpec("rm", WindowAgg(
                "min", Ref(2, dt.STRING),
                WindowFrame(None, 0, running_with_peers=True)), wspec())])
        with pytest.raises(NotImplementedError):
            plan.collect(device=True)
