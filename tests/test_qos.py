"""Serving QoS subsystem (ISSUE 14; parallel/qos/).

The contracts under test:

- WFQ drain order is a pure function of the arrival schedule: a seeded
  schedule drains in the same order every run, weighted service tracks
  the weight vector, and SJF orders within a class (un-priced queries
  after every priced one, FIFO among themselves).
- The HARD starvation bound engages: with a lopsided weight vector a
  starved class's head still runs after at most ``starvationBound``
  bypasses, and the engagement is counted.
- Deadline-aware admission rejects at ADMIT time when the cost estimate
  cannot meet ``timeout_ms`` (kind ``deadline-unmeetable``, no
  retry-after hint), while un-priced queries pass admission and the
  in-flight deadline timer remains the backstop.
- Per-tenant quotas: in-flight caps and catalog-byte caps reject with
  kind ``tenant-quota`` (+ retry-after hint); the kernel-cache compile
  budget EVICTS the tenant's oldest entries instead of rejecting.
- QueryRejectedError carries structured fields (kind / queue_depth /
  retry_after_ms) on every rejection path, FIFO and QoS alike.
- scheduler.qos.enabled=false leaves the FIFO scheduler untouched:
  priority/tenant kwargs are pure attribution, grant order is arrival
  order.
- The 1000-query soak (200 in CI; SRT_SOAK=1 runs the full bound): 4
  tenants x mixed classes x parameterized queries at
  maxConcurrentQueries=4 — bit-identical results, p99 bounded vs
  serial, empty leak reports, per-tenant chaos invisible to the other
  tenants, background still progressing.
"""

import os
import threading
import time

import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.faults import QueryCancelledError
from spark_rapids_tpu.memory import oom
from spark_rapids_tpu.parallel import qos as Q
from spark_rapids_tpu.parallel import scheduler as SC
from spark_rapids_tpu.parallel.qos import QosPolicy, TenantQuotas, WfqQueue
from spark_rapids_tpu.parallel.qos.policy import parse_weights
from spark_rapids_tpu.parallel.scheduler import (
    QueryManager, QueryRejectedError)


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    Q.reset_counters()
    oom.reset_degradation()
    yield
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    Q.reset_counters()
    oom.reset_degradation()
    # A test may have rebuilt the process-wide manager in QoS mode;
    # drop it so later modules start from the default FIFO scheduler.
    with SC._MANAGER_LOCK:
        SC._MANAGER = None


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_qos"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=11)
    return d


def _qos_session(tag=None, chaos="", max_concurrent=4, **extra):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries",
          max_concurrent)
    s.set("spark.rapids.sql.scheduler.qos.enabled", True)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    # Chaos sessions only: even an EMPTY armed fault key makes the cost
    # model stand down (plan/cost.py skips under chaos), which would
    # silently turn every deadline-admission test into an un-priced
    # pass-through. clean_state disarms the registry around each test.
    if chaos:
        s.set("spark.rapids.sql.test.faults", chaos)
        s.set("spark.rapids.sql.test.faults.seed", 11)
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    if tag is not None:
        s.set("spark.rapids.sql.test.faults.queryTag", tag)
    for k, v in extra.items():
        s.set(k, v)
    return s


def _policy(weights="8,3,1", bound=8):
    return QosPolicy(weights, bound)


def _drain(q):
    out = []
    while len(q):
        e, engaged = q.pop_next()
        out.append((e.qos_class, e.cost_ms, e.seq, engaged))
    return out


# ---------------------------------------------------------------------------
# WFQ policy units: determinism, weights, SJF, starvation bound
# ---------------------------------------------------------------------------

def test_wfq_drain_order_deterministic_under_seeded_schedule():
    """The drain order of a seeded arrival schedule is identical across
    runs — no clocks, no randomness in the policy."""
    import random

    def build():
        rng = random.Random(7)
        q = WfqQueue(parse_weights("8,3,1"), 8)
        for i in range(60):
            cls = Q.CLASSES[rng.randrange(3)]
            cost = rng.choice([None, float(rng.randrange(1, 500))])
            q.push(cls, cost, threading.Event(), f"t{i % 4}")
        return q

    first = _drain(build())
    second = _drain(build())
    assert first == second
    assert len(first) == 60


def test_wfq_service_tracks_weight_vector():
    """With every class backlogged, grants over a window split close to
    the weight vector (stride scheduling property)."""
    q = WfqQueue(parse_weights("8,3,1"), 1000)   # bound out of the way
    for i in range(120):
        for cls in Q.CLASSES:
            q.push(cls, float(i), threading.Event())
    got = {cls: 0 for cls in Q.CLASSES}
    for _ in range(60):                          # 5 full stride cycles
        e, _engaged = q.pop_next()
        got[e.qos_class] += 1
    assert got["interactive"] == 40
    assert got["batch"] == 15
    assert got["background"] == 5


def test_wfq_sjf_within_class_unpriced_last_fifo():
    """Within one class: priced entries shortest-first; un-priced ones
    after every priced entry, FIFO among themselves."""
    q = WfqQueue(parse_weights("8,3,1"), 8)
    q.push("batch", None, threading.Event())       # seq 1, un-priced
    q.push("batch", 90.0, threading.Event())       # seq 2
    q.push("batch", None, threading.Event())       # seq 3, un-priced
    q.push("batch", 10.0, threading.Event())       # seq 4
    order = [(c, s) for c, _cost, s, _e in _drain(q)]
    assert order == [("batch", 4), ("batch", 2), ("batch", 1),
                     ("batch", 3)]


def test_wfq_starvation_bound_engages():
    """Weights 100:1:1 give background its first grant on fair stride
    terms, but its vtime then jumps a full 1.0 — the stride schedule
    alone would make it wait ~100 interactive grants for the second.
    The hard bound caps that wait at 3 bypasses, flagged as an
    engagement."""
    q = WfqQueue(parse_weights("100,1,1"), 3)
    q.push("background", 1.0, threading.Event())
    q.push("background", 2.0, threading.Event())
    for i in range(20):
        q.push("interactive", float(i), threading.Event())
    drained = []
    for _ in range(6):
        e, engaged = q.pop_next()
        drained.append((e.qos_class, engaged))
    assert drained == [
        ("interactive", False),       # vtime tie -> class rank
        ("background", False),        # fair stride grant, vtime -> 1.0
        ("interactive", False),       # 3 bypasses build up...
        ("interactive", False),
        ("interactive", False),
        ("background", True),         # ...the hard bound fires
    ]
    assert drained[5] == ("background", True)


def test_wfq_reactivation_joins_at_global_vtime():
    """A class idle for many grants re-enters at the CURRENT virtual
    time — it cannot cash in credit for the idle stretch and then
    monopolize the queue."""
    q = WfqQueue(parse_weights("1,1,1"), 1000)
    for i in range(10):
        q.push("interactive", float(i), threading.Event())
    for _ in range(10):
        q.pop_next()                  # interactive vtime advances to 10
    q.push("background", 1.0, threading.Event())
    q.push("interactive", 99.0, threading.Event())
    cq = q._classes["background"]
    assert cq.vtime >= 9.0            # joined at global vtime, not 0
    # One grant each way — background is NOT owed 10 back-to-back slots.
    first, _ = q.pop_next()
    assert first.qos_class == "background"
    second, _ = q.pop_next()
    assert second.qos_class == "interactive"


def test_wfq_discard_is_race_free():
    q = WfqQueue(parse_weights("8,3,1"), 8)
    keep = q.push("batch", 5.0, threading.Event())
    drop = q.push("batch", 1.0, threading.Event())
    q.discard(drop)
    assert len(q) == 1
    e, _ = q.pop_next()
    assert e is keep
    assert q.pop_next() == (None, False)


def test_parse_weights_and_resolve_class_validation():
    assert parse_weights(" 8, 3 ,1 ") == {
        "interactive": 8.0, "batch": 3.0, "background": 1.0}
    with pytest.raises(ValueError, match="3 comma-separated"):
        parse_weights("8,3")
    with pytest.raises(ValueError, match="must be > 0"):
        parse_weights("8,0,1")
    assert Q.resolve_class(None) == "batch"
    assert Q.resolve_class(" Interactive ") == "interactive"
    with pytest.raises(ValueError, match="unknown priority class"):
        Q.resolve_class("realtime")
    assert Q.resolve_tenant(None) == "default"
    assert Q.resolve_tenant("  acme ") == "acme"


# ---------------------------------------------------------------------------
# Structured rejection (satellite: QueryRejectedError regression)
# ---------------------------------------------------------------------------

def test_rejection_error_structured_fields_queue_full():
    """Both scheduler modes shed load with kind/queue_depth/
    retry_after_ms populated — and the message regression-matched by the
    pre-QoS tests ("queue full") is unchanged."""
    for qos in (None, _policy()):
        mgr = QueryManager(1, 0, 50, qos=qos)
        hog = mgr.admit()
        try:
            with pytest.raises(QueryRejectedError,
                               match="queue full") as ei:
                mgr.admit()
            err = ei.value
            assert err.kind == "queue-full"
            assert err.queue_depth == 0
            assert err.retry_after_ms is not None \
                and err.retry_after_ms >= 50.0
            assert "REJECTED" in str(err)
        finally:
            mgr.finish(hog)


def test_rejection_error_structured_fields_admission_timeout():
    for qos in (None, _policy()):
        mgr = QueryManager(1, 4, 30, qos=qos)
        hog = mgr.admit()
        try:
            with pytest.raises(QueryRejectedError,
                               match="timeout") as ei:
                mgr.admit()
            err = ei.value
            assert err.kind == "admission-timeout"
            assert err.queue_depth == 0       # waiter removed first
            assert err.retry_after_ms is not None
        finally:
            mgr.finish(hog)
    assert Q.counters().get("rejected.admission-timeout", 0) >= 1


# ---------------------------------------------------------------------------
# Manager-level WFQ grant order vs FIFO
# ---------------------------------------------------------------------------

def _grant_order(mgr, submissions):
    """Admit ``submissions`` [(priority, cost_ms), ...] while a hog
    holds the only slot; return the observed grant order."""
    hog = mgr.admit()
    order = []
    lock = threading.Lock()
    started = threading.Semaphore(0)

    def waiter(prio, cost):
        started.release()
        t = mgr.admit(None, priority=prio, cost_ms=cost)
        with lock:
            order.append((prio, cost))
        mgr.finish(t)

    threads = []
    for prio, cost in submissions:
        th = threading.Thread(target=waiter, args=(prio, cost))
        th.start()
        threads.append(th)
        started.acquire()
        deadline = time.monotonic() + 10
        while mgr.queued_count < len(threads) \
                and time.monotonic() < deadline:
            time.sleep(0.002)
    assert mgr.queued_count == len(submissions)
    mgr.finish(hog)
    for th in threads:
        th.join(30)
    return order


def test_wfq_grant_order_beats_arrival_order():
    """QoS mode: grants follow class priority + SJF, not arrival."""
    mgr = QueryManager(1, 8, 30000, qos=_policy())
    order = _grant_order(mgr, [("background", 1.0), ("batch", 50.0),
                               ("batch", 5.0), ("interactive", 99.0)])
    # Stride order, not arrival order: interactive first (rank on the
    # vtime tie), then batch's SHORTER job (SJF), then background's
    # fair-share grant, then the longer batch job.
    assert order == [("interactive", 99.0), ("batch", 5.0),
                     ("background", 1.0), ("batch", 50.0)]
    assert Q.counters().get("admitted.interactive") == 1
    assert Q.counters().get("admitted.batch") == 3   # incl. the hog
    assert Q.counters().get("admitted.background") == 1


def test_fifo_mode_ignores_priority_and_cost():
    """scheduler.qos.enabled=false: the kwargs are accepted but grants
    stay in arrival order and tickets carry no class."""
    mgr = QueryManager(1, 8, 30000)          # no QosPolicy: FIFO
    order = _grant_order(mgr, [("background", 1.0), ("batch", 50.0),
                               ("interactive", 99.0)])
    assert order == [("background", 1.0), ("batch", 50.0),
                     ("interactive", 99.0)]
    assert "admitted.interactive" not in Q.counters()
    t = mgr.admit(None, priority="interactive", tenant="acme")
    assert t.qos_class is None and t.tenant == "acme"
    mgr.finish(t)


def test_qos_disabled_by_default_and_gate_resizes_manager(monkeypatch):
    """The default-off gate: a plain conf builds the FIFO manager; the
    same process flips to QoS and back only through the idle-only
    resize.  SRT_QOS is cleared so the test pins the DEFAULT even in
    the qos-on CI matrix entry (where the env turns the gate on)."""
    monkeypatch.delenv("SRT_QOS", raising=False)
    assert Q.qos_enabled(TpuSession().conf) is False
    mgr = SC.get_query_manager(TpuSession().conf)
    assert mgr.qos is None
    mgr2 = SC.get_query_manager(_qos_session().conf)
    assert mgr2.qos is not None and mgr2.qos.sig == ("8,3,1", 8)
    s = _qos_session()
    s.set("spark.rapids.sql.scheduler.qos.weights", "4,2,1")
    assert SC.get_query_manager(s.conf).qos.sig == ("4,2,1", 8)
    assert SC.get_query_manager(TpuSession().conf).qos is None


# ---------------------------------------------------------------------------
# Deadline-aware admission
# ---------------------------------------------------------------------------

def test_deadline_reject_at_admit_vs_unpriced_pass():
    """A priced query that cannot meet its deadline rejects at admit
    (no slot burned, no retry hint); an un-priced one passes and relies
    on the in-flight kill timer."""
    conf = _qos_session().conf
    mgr = QueryManager(2, 4, 1000, qos=_policy())
    with pytest.raises(QueryRejectedError, match="unmeetable") as ei:
        mgr.admit(conf, cost_ms=500.0, deadline_ms=50.0)
    assert ei.value.kind == "deadline-unmeetable"
    assert ei.value.retry_after_ms is None
    assert mgr.active_count == 0
    assert Q.counters().get("rejected.deadline-unmeetable") == 1
    t = mgr.admit(conf, cost_ms=None, deadline_ms=50.0)   # un-priced
    mgr.finish(t)
    t = mgr.admit(conf, cost_ms=10.0, deadline_ms=50.0)   # meetable
    mgr.finish(t)


def test_deadline_slack_and_gate_conf():
    conf = _qos_session(
        **{"spark.rapids.sql.scheduler.qos.deadlineSlack": 3.0}).conf
    mgr = QueryManager(2, 4, 1000, qos=_policy())
    # 30ms estimate * 3.0 slack > 80ms deadline -> reject.
    with pytest.raises(QueryRejectedError, match="unmeetable"):
        mgr.admit(conf, cost_ms=30.0, deadline_ms=80.0)
    off = _qos_session(**{
        "spark.rapids.sql.scheduler.qos.deadlineAdmission.enabled":
            False}).conf
    t = mgr.admit(off, cost_ms=500.0, deadline_ms=50.0)
    mgr.finish(t)


def test_deadline_reject_at_admit_end_to_end(data_dir):
    """With the cost model on, collect(timeout_ms=...) under QoS feeds
    the estimate into admission: an absurd deadline rejects BEFORE
    execution; the same query with a sane deadline runs."""
    s = _qos_session(**{"spark.rapids.sql.cost.enabled": True})
    df = tpch.QUERIES["q6"](s, data_dir)
    with pytest.raises(QueryRejectedError, match="unmeetable"):
        df.collect(timeout_ms=0.0001)
    assert SC.get_query_manager().active_count == 0
    assert df.collect(timeout_ms=120000) \
        == tpch.QUERIES["q6"](_qos_session(), data_dir).collect()


def test_deadline_kill_in_flight_still_works(data_dir):
    """Un-priced queries (cost model off, the test default) pass
    admission; the armed deadline still kills them mid-flight — QoS
    does not replace the in-flight backstop."""
    s = _qos_session(tag=3, chaos="stall@upload/query=3:1")
    df = tpch.QUERIES["q6"](s, data_dir)
    with pytest.raises(QueryCancelledError, match="deadline"):
        df.collect(timeout_ms=300)
    ctx = df._physical().last_ctx
    assert ctx is not None and ctx.last_leak_report == []
    assert SC.counters().get("deadlineKills", 0) >= 1


# ---------------------------------------------------------------------------
# Per-tenant quotas
# ---------------------------------------------------------------------------

def test_tenant_in_flight_quota():
    conf = _qos_session(
        **{"spark.rapids.sql.scheduler.qos.tenantMaxInFlight": 1}).conf
    mgr = QueryManager(4, 8, 1000, qos=_policy())
    t1 = mgr.admit(conf, tenant="a")
    with pytest.raises(QueryRejectedError, match="in-flight cap") as ei:
        mgr.admit(conf, tenant="a")
    assert ei.value.kind == "tenant-quota"
    assert ei.value.retry_after_ms is not None
    t2 = mgr.admit(conf, tenant="b")          # other tenants unaffected
    mgr.finish(t1)
    t3 = mgr.admit(conf, tenant="a")          # cap freed with the query
    mgr.finish(t2)
    mgr.finish(t3)
    assert Q.counters().get("rejected.tenant-quota") == 1


def test_tenant_catalog_bytes_quota():
    """The owner-tagged catalog accounting (BufferCatalog.owned_bytes)
    backs the byte cap: a tenant sitting on registered bytes rejects,
    other tenants admit."""

    class _Catalog:
        def __init__(self, owned):
            self._owned = owned

        def owned_bytes(self):
            return dict(self._owned)

    class _Ctx:
        def __init__(self, owned):
            self._catalog = _Catalog(owned)

    conf = _qos_session(**{
        "spark.rapids.sql.scheduler.qos.tenantMaxCatalogBytes": 1024}).conf
    mgr = QueryManager(4, 8, 1000, qos=_policy())
    t1 = mgr.admit(conf, tenant="a")
    mgr.register_context(t1, _Ctx({t1.query_id: 4096}))
    with pytest.raises(QueryRejectedError,
                       match="catalog-bytes cap") as ei:
        mgr.admit(conf, tenant="a")
    assert ei.value.kind == "tenant-quota"
    t2 = mgr.admit(conf, tenant="b")
    mgr.finish(t1)                            # bytes retire with the query
    t3 = mgr.admit(conf, tenant="a")
    for t in (t2, t3):
        mgr.finish(t)


def test_tenant_kernel_cache_quota_evicts_oldest():
    """Over the compile budget the tenant's OLDEST kernel-cache entries
    are evicted (quotaEvictions) — admission never rejects for it."""
    from spark_rapids_tpu.ops import kernel_cache as KC
    conf = _qos_session(**{
        "spark.rapids.sql.scheduler.qos.tenantMaxKernelCacheEntries":
            3}).conf
    mgr = QueryManager(4, 8, 1000, qos=_policy())
    t1 = mgr.admit(conf, tenant="kq")
    # Query ids restart per manager while the kernel cache is process
    # global: drop any stale same-id entries earlier tests compiled so
    # the eviction accounting below is exact.
    KC.cache().evict_owned({t1.query_id}, keep=0)
    faults.set_query_token(t1.token)
    try:
        for i in range(5):
            KC.cache().get(("qos-quota-test", i), lambda: i)
    finally:
        faults.set_query_token(None)
    owned = [k for k, qid in KC.cache().owners().items()
             if qid == t1.query_id]
    assert len(owned) == 5
    t2 = mgr.admit(conf, tenant="kq")         # admits; budget enforced
    owned = [k for k, qid in KC.cache().owners().items()
             if qid == t1.query_id]
    assert len(owned) == 3
    assert sorted(k[1] for k in owned) == [2, 3, 4]   # oldest two gone
    assert Q.counters().get("quotaEvictions") == 2
    for t in (t1, t2):
        mgr.finish(t)
    KC.cache().evict_owned({t1.query_id}, keep=0)     # leave no residue


def test_tenant_quotas_bookkeeping_units():
    tq = TenantQuotas()
    tq.reserve("a")
    tq.reserve("a")
    tq.reserve("b")
    assert tq.inflight("a") == 2 and tq.inflight("b") == 1
    tq.release("a")
    tq.release("b")
    tq.release("b")                           # over-release clamps at 0
    assert tq.inflight("a") == 1 and tq.inflight("b") == 0
    tq.record_query(7, "a")
    tq.record_query(8, "b")
    assert tq.tenant_of(7) == "a" and tq.tenant_of(None) is None
    assert tq.query_ids("a") == {7}
    assert tq.kernel_entries("a", {"k1": 7, "k2": 8, "k3": None}) == 1
    tq.prune(live_query_ids={8})
    assert tq.tenant_of(7) is None


# ---------------------------------------------------------------------------
# Per-tenant attribution without QoS (the bench sustained block)
# ---------------------------------------------------------------------------

def test_per_tenant_plan_cache_counters(data_dir):
    """Tenant-tagged collects feed planCacheHit/Miss.<tenant> counters
    in BOTH scheduler modes (attribution, not scheduling)."""
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    df = tpch.QUERIES["q6"](s, data_dir)
    df.collect(tenant="acme")
    df2 = tpch.QUERIES["q6"](TpuSession().set(
        "spark.rapids.sql.variableFloatAgg.enabled", True), data_dir)
    df2.collect(tenant="acme")
    got = Q.counters()
    assert got.get("planCacheMiss.acme", 0) \
        + got.get("planCacheHit.acme", 0) >= 2
    assert got.get("planCacheHit.acme", 0) >= 1, got


# ---------------------------------------------------------------------------
# Cross-tenant chaos isolation
# ---------------------------------------------------------------------------

def test_per_tenant_chaos_invisible_to_other_tenants(data_dir):
    """QoS mode, 3 tenants in flight, seeded per-query chaos scoped to
    tenant A's query tag: A recovers (faults actually injected), B and
    C return bit-identical results with ZERO recovery counters."""
    baseline = {qn: tpch.QUERIES[qn](_qos_session(), data_dir).collect()
                for qn in ("q1", "q3", "q6")}
    chaos = "oom@upload/query=1:1,lostoutput@exchange.serve/query=1:1"
    plan = [("A", 1, "q3", "interactive"), ("B", 2, "q6", "batch"),
            ("C", 3, "q1", "background")]
    results, errors, dfs = {}, {}, {}
    barrier = threading.Barrier(len(plan), timeout=60)

    def run(name, tag, qn, prio):
        try:
            df = tpch.QUERIES[qn](_qos_session(tag=tag, chaos=chaos),
                                  data_dir)
            dfs[name] = df
            barrier.wait()
            results[name] = df.collect(priority=prio,
                                       tenant=f"tenant-{name}")
        except BaseException as e:       # pragma: no cover - diagnostics
            errors[name] = e

    threads = [threading.Thread(target=run, args=args) for args in plan]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert not errors, errors
    for name, _tag, qn, _p in plan:
        assert results[name] == baseline[qn], \
            f"tenant {name} ({qn}) diverged under chaos"

    def rec(df):
        m = df.metrics().get("Recovery@query", {})
        return {k: v for k, v in m.items() if v}

    assert rec(dfs["A"]).get("faultsInjected", 0) > 0
    for name in ("B", "C"):
        assert rec(dfs[name]) == {}, \
            f"tenant {name}'s isolation was breached: {rec(dfs[name])}"
    admitted = Q.counters()
    for cls in Q.CLASSES:
        assert admitted.get(f"admitted.{cls}", 0) >= 1


# ---------------------------------------------------------------------------
# The soak (slow; 200 queries in CI, SRT_SOAK=1 runs the full 1000)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_qos_soak_mixed_tenants(data_dir):
    """ISSUE 14 acceptance soak: mixed parameterized queries x 4
    tenants (one per priority class + one chaos tenant) through the QoS
    scheduler at maxConcurrentQueries=4. Every result is bit-identical
    to its solo run, p99 latency stays bounded vs serial, every query's
    leak report is empty, the chaos tenant's faults never cross the
    tenant boundary, and the background class keeps progressing."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col

    total = 1000 if os.environ.get("SRT_SOAK", "").strip() \
        not in ("", "0") else 200
    day0 = tpch.days("1994-01-01")

    def shape_q6(s, i):
        li = tpch._read(s, data_dir, "lineitem")
        lo = day0 + (i % 8) * 30
        f = li.filter(
            (col("l_shipdate") >= lit_col(lo))
            & (col("l_shipdate") < lit_col(lo + 45))
            & (col("l_discount") >= 0.02) & (col("l_quantity") < 30.0))
        return f.agg(agg_sum(col("l_extendedprice") * col("l_discount"))
                     .alias("rev"))

    def shape_sum(s, i):
        li = tpch._read(s, data_dir, "lineitem")
        return li.filter(col("l_quantity") < float(5 + (i % 8) * 4)) \
            .agg(agg_sum(col("l_extendedprice")).alias("s"))

    shapes = [shape_q6, shape_sum]

    def key(i):
        return (i % len(shapes), (i // len(shapes)) % 8)

    tenants = [("interactive", None, ""), ("batch", None, ""),
               ("background", None, ""),
               ("batch", 9, "oom@upload/query=9:2")]
    sessions = [_qos_session(tag=tag, chaos=chaos)
                for _cls, tag, chaos in tenants]
    for s in sessions:
        s.set("spark.rapids.sql.concurrentTpuTasks", 4)

    # Solo reference pass: expected rows per (shape, literal) slot AND
    # the serial latency baseline the p99 bound is measured against.
    expected = {}
    serial = []
    ref = sessions[0]
    for i in range(2 * len(shapes) * 8):
        t0 = time.perf_counter()
        rows = shapes[i % len(shapes)](ref, i).collect()
        serial.append(time.perf_counter() - t0)
        expected.setdefault(key(i), rows)
    serial.sort()
    serial_p50 = serial[len(serial) // 2]

    # Warm every client's session (template plan + kernel compile per
    # conf) before the timed run — the serving-tier steady state the
    # latency bound is specified against.
    for k, s in enumerate(sessions):
        for i in range(len(shapes)):
            shapes[i](s, i).collect(tenant=f"tenant{k}")

    lock = threading.Lock()
    lat = {k: [] for k in range(len(tenants))}
    done = {k: 0 for k in range(len(tenants))}
    failures = []
    per_client = total // len(tenants)

    def client(k):
        cls, _tag, chaos = tenants[k]
        s = sessions[k]
        for j in range(per_client):
            i = k * per_client + j
            df = shapes[i % len(shapes)](s, i)
            t0 = time.perf_counter()
            try:
                rows = df.collect(priority=cls, tenant=f"tenant{k}")
            except BaseException as e:  # pragma: no cover - diagnostics
                with lock:
                    failures.append((k, i, repr(e)))
                return
            took = time.perf_counter() - t0
            ctx = df._physical().last_ctx
            with lock:
                lat[k].append(took)
                done[k] += 1
                if rows != expected[key(i)]:
                    failures.append((k, i, "rows diverged from solo run"))
                if ctx is None or ctx.last_leak_report != []:
                    failures.append((k, i, "leaked buffers"))
                if not chaos:
                    m = df.metrics().get("Recovery@query", {})
                    hit = {kk: v for kk, v in m.items() if v}
                    if hit:
                        failures.append((k, i, f"chaos crossed: {hit}"))

    threads = [threading.Thread(target=client, args=(k,),
                                name=f"qos-soak-{k}")
               for k in range(len(tenants))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(900)
    assert failures == [], failures[:10]
    assert all(done[k] == per_client for k in done), done

    # p99 vs serial: the acceptance bound, with a small absolute floor
    # so millisecond-scale CI queries don't fail on timer jitter.
    clean = sorted(x for k in (0, 1, 2) for x in lat[k])
    p99 = clean[min(int(0.99 * len(clean)), len(clean) - 1)]
    bound = max(2.0 * serial_p50, serial_p50 + 0.25)
    assert p99 <= bound, \
        f"p99 {p99 * 1000:.1f}ms > bound {bound * 1000:.1f}ms " \
        f"(serial p50 {serial_p50 * 1000:.1f}ms)"

    got = Q.counters()
    # Background kept progressing the whole soak under heavier classes.
    assert got.get("admitted.background", 0) >= per_client
    assert got.get("admitted.interactive", 0) >= per_client
    # The chaos tenant actually injected faults (the isolation above
    # was tested against something real).
    assert faults.counters().get("faultsInjected", 0) > 0
    # Per-tenant plan-cache counters saw every clean tenant (the chaos
    # tenant bypasses the plan cache by design — an armed fault
    # schedule targets per-plan state).
    for k in (0, 1, 2):
        assert got.get(f"planCacheHit.tenant{k}", 0) > 0, got
