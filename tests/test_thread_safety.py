"""Thread-safety audit regressions (ISSUE 4 satellite): the kernel
cache, the metrics dicts, and the scan-side caches under concurrent
collects — two queries pipelining simultaneously must not corrupt LRU
order or counter totals.
"""

import threading

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.ops.base import Metrics


# ---------------------------------------------------------------------------
# KernelCache under contention
# ---------------------------------------------------------------------------

def test_kernel_cache_concurrent_lookups_consistent():
    """N threads hammer one bounded cache with overlapping keys: every
    lookup is a hit or a miss (no lost updates), the LRU never exceeds
    its bound, and every returned entry is a CompiledKernel."""
    cache = kc.KernelCache(max_entries=16)
    nthreads, iters, nkeys = 8, 400, 48
    errors = []

    def worker(tid):
        try:
            for i in range(iters):
                key = ("k", (tid * 7 + i) % nkeys)
                entry, _hit = cache.get(
                    key, lambda: kc.CompiledKernel(lambda x=i: x))
                assert isinstance(entry, kc.CompiledKernel)
        except BaseException as e:       # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    s = cache.stats()
    assert s["hits"] + s["misses"] == nthreads * iters, s
    assert s["entries"] <= 16, s
    assert s["misses"] >= nkeys, s     # every key missed at least once
    # LRU invariant: the resident keys are exactly the tracked entries.
    assert len(cache.keys()) == s["entries"]


def test_compiled_kernel_first_call_times_once():
    """Racing first calls record compile time exactly once and every
    caller gets the result."""
    calls = []

    def fn(x):
        calls.append(x)
        return x * 2

    entry = kc.CompiledKernel(fn)
    outs = []
    barrier = threading.Barrier(6)

    def run(i):
        barrier.wait()
        outs.append(entry(i))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(outs) == [0, 2, 4, 6, 8, 10]
    assert entry.compiled and entry.compile_ns >= 0


# ---------------------------------------------------------------------------
# Metrics counters under contention
# ---------------------------------------------------------------------------

def test_metrics_add_is_atomic():
    m = Metrics(owner="t")
    nthreads, iters = 8, 5000

    def bump():
        for _ in range(iters):
            m.add("n", 1)

    threads = [threading.Thread(target=bump) for _ in range(nthreads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.values["n"] == nthreads * iters, m.values


def test_metrics_for_registers_one_entry_across_threads():
    from spark_rapids_tpu.ops.base import ExecContext, InMemorySourceExec
    from spark_rapids_tpu.columnar.host import HostBatch
    ctx = ExecContext()
    op = InMemorySourceExec(
        (("a", dt.INT64),),
        [[HostBatch.from_pydict((("a", dt.INT64),), {"a": [1]})]])
    got = []
    barrier = threading.Barrier(8)

    def reg():
        barrier.wait()
        got.append(ctx.metrics_for(op))

    threads = [threading.Thread(target=reg) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(g is got[0] for g in got), "metrics_for raced two entries"


# ---------------------------------------------------------------------------
# Two queries pipelining simultaneously
# ---------------------------------------------------------------------------

def _query(session, lo, n, parts):
    from spark_rapids_tpu.plan.logical import col
    data = {"k": [i % 7 for i in range(lo, lo + n)],
            "v": list(range(lo, lo + n))}
    df = session.create_dataframe(
        data, (("k", dt.INT64), ("v", dt.INT64)), num_partitions=parts)
    return df.filter(col("v") % 3 != 0)


def test_concurrent_collects_keep_counter_totals():
    """Two sessions collect concurrently (each with the pipeline on):
    results stay correct and each query's Recovery/Pipeline/operator
    counters tally independently (no cross-talk, no lost updates)."""
    from spark_rapids_tpu.plan.logical import col

    def expected(lo, n):
        return [(i % 7, i) for i in range(lo, lo + n) if i % 3 != 0]

    errors = []
    iters = 4

    def run(lo):
        try:
            s = TpuSession()
            s.set("spark.rapids.sql.pipeline.enabled", True)
            for _ in range(iters):
                df = _query(s, lo, 4000, 4)
                got = sorted(df.collect())
                assert got == sorted(expected(lo, 4000)), \
                    f"query@{lo} wrong rows"
        except BaseException as e:      # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=run, args=(lo,))
               for lo in (0, 100000)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_kernel_cache_totals_across_concurrent_queries():
    """The process-global cache's hit+miss delta equals the sum of the
    per-query deltas — concurrent collects may not lose counts."""
    s0 = kc.cache().stats()
    test_concurrent_collects_keep_counter_totals()
    s1 = kc.cache().stats()
    total = (s1["hits"] + s1["misses"]) - (s0["hits"] + s0["misses"])
    assert total >= 0
    assert s1["entries"] <= kc.cache().max_entries
