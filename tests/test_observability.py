"""ML hand-off + observability surfaces (VERDICT r4 item 10):
DataFrame.to_jax zero-host-round-trip export (ColumnarRdd.scala:41-49),
DataFrame.metrics (GpuExec.scala:27-56), trace annotations in timed(),
and the catalog's alloc-debug leak report (RapidsConf.scala:288)."""

import logging

import jax.numpy as jnp
import pytest

from spark_rapids_tpu import FLOAT64, INT64, STRING
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import agg_sum, col


def _df(s):
    return s.create_dataframe(
        {"k": [1, 2, 2, 3, 3, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0],
         "name": ["a", "bb", "ccc", "d", "e", "f"]},
        [("k", INT64), ("v", FLOAT64), ("name", STRING)])


def test_to_jax_device_export():
    s = TpuSession()
    out = _df(s).filter(col("k") > 1).to_jax()
    assert isinstance(out["k"], jnp.ndarray)
    assert out["k"].shape == (5,)
    assert sorted(out["k"].tolist()) == [2, 2, 3, 3, 3]
    assert out["v"].dtype == jnp.float64
    # Strings export as byte matrices + lengths.
    assert out["name"].ndim == 2
    assert out["name__len"].tolist() == [2, 3, 1, 1, 1]


def test_to_jax_rejects_nulls():
    s = TpuSession()
    df = s.create_dataframe({"x": [1.0, None, 3.0]}, [("x", FLOAT64)])
    with pytest.raises(ValueError, match="nulls"):
        df.to_jax()


def test_metrics_after_collect():
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    df = _df(s).group_by("k").agg(agg_sum(col("v")).alias("sv"))
    assert df.metrics() == {}
    df.collect()
    m = df.metrics()
    assert any("HashAggregateExec" in k for k in m)
    agg_metrics = next(v for k, v in m.items() if "HashAggregate" in k)
    assert agg_metrics.get("totalTime", 0) > 0


def test_memory_debug_leak_report(tmp_path, caplog):
    from spark_rapids_tpu.memory import BufferCatalog
    from tests.test_memory import make_batch
    cat = BufferCatalog(spill_dir=str(tmp_path), debug=True)
    cat.add_batch(make_batch(3))
    leaks = cat.leak_report()
    assert len(leaks) == 1
    bid, size, stack = leaks[0]
    assert size > 0 and "test_observability" in stack
    with caplog.at_level(logging.WARNING, "spark_rapids_tpu.memory"):
        cat.close()
    assert any("leaked" in r.message for r in caplog.records)


def test_audit_groups_exempt_from_metrics_level():
    """The metrics verbosity filter must never drop the per-query audit
    entries — and the exemption set is ONE registry (ops/base.py), not
    per-call-site tuples (ISSUE 9 satellite)."""
    from spark_rapids_tpu.ops.base import (audit_metric_groups,
                                           query_metrics_entry,
                                           register_audit_metric_group)
    # The five built-in audit groups are pre-registered.
    assert {"Recovery", "Pipeline", "Scheduler", "Transport",
            "Cost"} <= audit_metric_groups()
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.metrics.level", "ESSENTIAL")
    df = _df(s).group_by("k").agg(agg_sum(col("v")).alias("sv"))
    df.collect()
    phys = df._physical()
    # Seed audit counters that ESSENTIAL would filter if they were
    # operator metrics, plus a THIRD-PARTY group registered through the
    # same funnel.
    from spark_rapids_tpu.parallel import scheduler as SC
    SC.metrics_entry(phys.last_ctx).add("crossQueryEvictions", 2)
    query_metrics_entry(phys.last_ctx, "Recovery").add(
        "stageRecomputes", 1)
    query_metrics_entry(phys.last_ctx, "MyPlugin").add("customCounter", 3)
    assert "MyPlugin" in audit_metric_groups()
    m = df.metrics()
    # Operator entries are filtered down to the ESSENTIAL set...
    agg = next(v for k, v in m.items() if "HashAggregate" in k)
    assert set(agg) <= {"numOutputRows", "totalTime"}
    # ...audit entries keep every counter, including the plugin's.
    assert m["Scheduler@query"]["crossQueryEvictions"] == 2
    assert m["Recovery@query"]["stageRecomputes"] == 1
    assert m["MyPlugin@query"]["customCounter"] == 3
    # Idempotent re-registration.
    register_audit_metric_group("MyPlugin")
    assert "MyPlugin" in audit_metric_groups()


def test_transient_error_retries_query_once(monkeypatch):
    """Failure recovery (SURVEY 5.3): a transient backend error retries
    the whole query on a fresh context; deterministic errors do not."""
    from spark_rapids_tpu.plan.logical import agg_count
    s = TpuSession()
    df = _df(s).agg(agg_count().alias("n"))
    phys = df._physical()
    calls = {"n": 0}
    orig = type(phys.root).collect

    def flaky(self, ctx, device=True):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: Socket closed")
        return orig(self, ctx, device)

    monkeypatch.setattr(type(phys.root), "collect", flaky)
    assert phys.collect() == [(6,)]
    assert calls["n"] == 2

    calls["n"] = 0

    def hard(self, ctx, device=True):
        calls["n"] += 1
        raise ValueError("deterministic bug")

    monkeypatch.setattr(type(phys.root), "collect", hard)
    with pytest.raises(ValueError):
        phys.collect()
    assert calls["n"] == 1
