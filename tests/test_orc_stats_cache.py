"""Eviction discipline of the ORC stripe-stats cache (io/scan.py): a
true LRU — hits refresh recency, updates of resident keys never evict,
and eviction at capacity removes the coldest entry, so warm stripes
survive a full cache."""

import spark_rapids_tpu.io.scan as scan


class _FakeStat:
    pass


def _key(i):
    return (f"/data/f{i}.orc", 0.0, 100, 0)


def test_orc_stats_cache_is_lru(monkeypatch):
    monkeypatch.setattr(scan, "_ORC_STATS_CACHE_MAX", 3)
    cache = scan._ORC_STATS_CACHE
    cache.clear()

    def touch(i, entry=None):
        """The cache discipline _orc_stripe_stats applies, extracted:
        move-to-end on hit; evict-oldest only when inserting NEW."""
        key = _key(i)
        cached = cache.get(key)
        if cached is not None:
            cache.move_to_end(key)
        if entry is not None:
            if key not in cache:
                while len(cache) >= scan._ORC_STATS_CACHE_MAX:
                    cache.popitem(last=False)
            cache[key] = entry
            cache.move_to_end(key)

    touch(0, {"a": (0, 1, 0, 10)})
    touch(1, {"a": (0, 1, 0, 10)})
    touch(2, {"a": (0, 1, 0, 10)})
    assert list(cache) == [_key(0), _key(1), _key(2)]

    # A hit refreshes recency: 0 becomes warmest.
    touch(0)
    assert list(cache) == [_key(1), _key(2), _key(0)]

    # Updating a RESIDENT key at capacity evicts nothing.
    touch(1, {"a": (0, 1, 0, 10), "b": (5, 9, 0, 10)})
    assert len(cache) == 3
    assert list(cache) == [_key(2), _key(0), _key(1)]

    # Inserting a genuinely new key evicts only the coldest (2) — the
    # warm entries 0 and 1 survive at capacity.
    touch(3, {"a": (0, 1, 0, 10)})
    assert list(cache) == [_key(0), _key(1), _key(3)]
    cache.clear()


def test_orc_stats_cache_real_path(tmp_path, monkeypatch):
    """End-to-end through _orc_stripe_stats: repeated probes of the same
    stripe are hits (stay resident + warm), and new stripes evict the
    coldest, not the warmest."""
    import pyarrow as pa
    import pyarrow.orc as paorc

    paths = []
    for i in range(4):
        p = str(tmp_path / f"t{i}.orc")
        paorc.write_table(pa.table({"x": [i, i + 1, i + 2]}), p)
        paths.append(p)

    monkeypatch.setattr(scan, "_ORC_STATS_CACHE_MAX", 3)
    cache = scan._ORC_STATS_CACHE
    cache.clear()

    def probe(i):
        unit = scan.ScanUnit(paths[i], 0, 3)
        stats, rows = scan._orc_stripe_stats(unit, ["x"])
        assert rows == 3 and stats["x"].min == i
        return next(k for k in cache if k[0] == paths[i])

    k0, k1, k2 = probe(0), probe(1), probe(2)
    probe(0)                              # hit: 0 refreshes
    assert list(cache) == [k1, k2, k0]
    k3 = probe(3)                         # new key: evicts coldest (1)
    assert k1 not in cache
    assert list(cache) == [k2, k0, k3]
    cache.clear()
