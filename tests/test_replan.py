"""Runtime adaptive re-planning (ISSUE 7 tentpole, parallel/replan.py).

A shuffled hash join whose build side MATERIALIZES small — the planner's
footer estimate said big (filters keep their child's size, Spark's
non-CBO stats), the observed shuffle said tiny — demotes to a broadcast
hash join mid-query: the probe side never shuffles, results match the
oracle, and lineage-scoped recovery still covers the re-planned stages.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks.compare import compare_results
from spark_rapids_tpu.ops.join import (
    BroadcastHashJoinExec, ShuffledHashJoinExec)
from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
from spark_rapids_tpu.plan.logical import agg_sum, col


@pytest.fixture(scope="module")
def pq_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("replan_pq")
    rng = np.random.default_rng(5)
    # Probe: 60k rows (big enough to exercise multi-partition shuffles,
    # small enough to keep XLA join-kernel compiles light under the full
    # suite). Build: a dim whose FILTERED size is tiny but whose footer
    # estimate (filter keeps child size) stays above the threshold.
    papq.write_table(pa.table({
        "k": rng.integers(0, 500, 60_000, dtype=np.int64),
        "v": rng.uniform(0, 1, 60_000),
    }), os.path.join(d, "big.parquet"))
    papq.write_table(pa.table({
        "dk": np.arange(2000, dtype=np.int64),
        "w": rng.uniform(0, 1, 2000),
        "flag": rng.integers(0, 100, 2000, dtype=np.int64),
    }), os.path.join(d, "dim.parquet"))
    return str(d)


def _session(**conf):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # Placement off: these tests pin the DEVICE plan to exercise the
    # runtime-replan layer in isolation.
    s.set("spark.rapids.sql.cost.enabled", False)
    # Estimates exceed this, observed (filtered) bytes do not — the
    # static planner keeps the shuffled join, the runtime demotes it.
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", 20_000)
    for k, v in conf.items():
        s.set(k, v)
    return s


def _skew_join(session, pq_dir):
    big = session.read.parquet(os.path.join(pq_dir, "big.parquet"))
    dim = session.read.parquet(os.path.join(pq_dir, "dim.parquet")) \
        .filter(col("flag") == 3)
    return big.join_on(dim, ["k"], ["dk"]) \
        .group_by("k").agg(agg_sum(col("w")).alias("sw"))


def _find(root, cls):
    out = []

    def walk(n):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(root)
    return out


class TestRuntimeDemotion:
    def test_statically_planned_as_shuffle(self, pq_dir):
        phys = _skew_join(_session(), pq_dir)._physical()
        shj = [j for j in _find(phys.root, ShuffledHashJoinExec)
               if type(j) is ShuffledHashJoinExec]
        assert shj, "estimate must keep the shuffled join statically"

    def test_demotes_and_matches_oracle(self, pq_dir):
        df = _skew_join(_session(), pq_dir)
        got = df.collect()
        m = df.metrics()["Cost@query"]
        assert m["joinDemotions"] == 1
        assert m["replanChecks"] == 1
        assert m["replanObservedBytes"] > 0
        want = df.collect_host()
        assert compare_results(got, want, sort=True)

    def test_probe_shuffle_skipped(self, pq_dir):
        df = _skew_join(_session(), pq_dir)
        phys = df._physical()
        phys.collect()
        ctx = phys.last_ctx
        join = [j for j in _find(phys.root, ShuffledHashJoinExec)
                if type(j) is ShuffledHashJoinExec][0]
        build_ex = join.children[1]
        probe_ex = join.children[0]
        # The build exchange materialized; the probe exchange never did.
        assert f"shuffle:{id(build_ex):x}:dev" in ctx.cache
        assert f"shuffle:{id(probe_ex):x}:dev" not in ctx.cache
        assert ctx.cache.get(f"replan-skip:{id(probe_ex):x}")
        # The delegate is a broadcast join over the materialized build.
        from spark_rapids_tpu.parallel import replan as RP
        delegate = RP.demoted(ctx, join)
        assert isinstance(delegate, BroadcastHashJoinExec)

    def test_disabled_by_conf(self, pq_dir):
        df = _skew_join(_session(**{
            "spark.rapids.sql.aqe.replan.enabled": False}), pq_dir)
        got = df.collect()
        assert "joinDemotions" not in df.metrics().get("Cost@query", {})
        ref = _skew_join(_session(), pq_dir).collect()
        assert compare_results(got, ref, sort=True)

    def test_threshold_minus_one_disables(self, pq_dir):
        df = _skew_join(_session(**{
            "spark.rapids.sql.autoBroadcastJoinThreshold": -1}), pq_dir)
        df.collect()
        assert "joinDemotions" not in df.metrics().get("Cost@query", {})

    def test_observed_above_threshold_keeps_shuffle(self, pq_dir):
        df = _skew_join(_session(**{
            "spark.rapids.sql.autoBroadcastJoinThreshold": 64}), pq_dir)
        got = df.collect()
        m = df.metrics()["Cost@query"]
        assert m["replanChecks"] == 1
        assert "joinDemotions" not in m
        want = df.collect_host()
        assert compare_results(got, want, sort=True)


class TestByteAwareCoalesce:
    def test_byte_target_limits_merging(self):
        """AQE coalescing merges by observed bytes as well as rows: a
        one-byte target keeps every reduce partition separate even when
        the row target would merge them all."""
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.ops.base import ExecContext
        from spark_rapids_tpu.plan.logical import agg_count

        def agg_df(session):
            df = session.create_dataframe(
                {"k": list(range(100)) * 4, "v": list(range(400))},
                [("k", srt.INT64), ("v", srt.INT64)], num_partitions=4)
            return df.group_by("k").agg(agg_count().alias("n"))

        s1 = TpuSession()
        phys = agg_df(s1)._physical()
        ctx = ExecContext(phys.conf)
        ctx.cache["engine"] = "device"
        phys.root.collect(ctx, device=True)
        coalescable = [e for e in _find(phys.root, ShuffleExchangeExec)
                       if e.allow_coalesce]
        assert any(e.num_partitions(ctx) < e.partitioning.num_partitions
                   for e in coalescable)
        ctx.close()

        s2 = TpuSession()
        s2.set("spark.rapids.sql.aqe.coalescePartitions.targetBytes", 1)
        phys2 = agg_df(s2)._physical()
        ctx2 = ExecContext(phys2.conf)
        ctx2.cache["engine"] = "device"
        phys2.root.collect(ctx2, device=True)
        for e in _find(phys2.root, ShuffleExchangeExec):
            assert e.num_partitions(ctx2) == e.partitioning.num_partitions
        ctx2.close()


class TestReplanChaos:
    """ISSUE 7 satellite: faults injected during/after a runtime
    re-plan — the demoted plan's stages still recover lineage-scoped."""

    def test_lost_build_output_recomputes_one_stage(self, pq_dir):
        want = _skew_join(_session(), pq_dir).collect()
        df = _skew_join(_session(**{
            "spark.rapids.sql.test.faults": "lostoutput@exchange.serve:1",
            "spark.rapids.sql.test.faults.seed": 7,
            "spark.rapids.sql.retry.backoffMs": 1,
        }), pq_dir)
        got = df.collect()
        assert compare_results(got, want, sort=True)
        m = df.metrics()
        assert m["Recovery@query"]["stageRecomputes"] == 1
        assert m["Cost@query"]["joinDemotions"] >= 1
