"""Persistent compilation cache (ISSUE 4 satellite): compiled XLA
executables behind ``spark.rapids.sql.kernelCache.persistentDir``
serialize to disk and are served back (persistentCacheHits) after the
in-memory caches are dropped — the in-process proxy for surviving a
process restart (first_run_s -> steady state).

JAX's compilation-cache dir is process-global and STICKY once set — a
test that enabled it would tax every later compile of the pytest
process with disk serialization. The enable-and-hit scenario therefore
runs in a throwaway subprocess; only side-effect-free pieces run
in-process.
"""

import os
import subprocess
import sys

import pytest

from spark_rapids_tpu.ops import kernel_cache as kc


def test_empty_dir_never_enables():
    assert not kc.configure_persistent("")
    assert not kc.configure_persistent(None)
    assert kc.persistent_stats()["dir"] is None


_SUBPROCESS_BODY = r"""
import glob, os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
import jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from spark_rapids_tpu.ops import kernel_cache as kc

d = tempfile.mkdtemp()
# Compile BEFORE enabling: proves configure_persistent resets jax's
# "cache usable" latch instead of requiring process-start configuration.
jax.jit(lambda x: x + 1)(jnp.arange(4)).block_until_ready()

assert kc.configure_persistent(d), "enable failed"
assert kc.configure_persistent(d), "not idempotent"
s = kc.cache().stats()
assert s.get("persistentCacheDir") == d, s
assert "persistentCacheHits" in s and "persistentCacheMisses" in s, s

f = jax.jit(lambda x: x * 3 + 1)
f(jnp.arange(16)).block_until_ready()
files = glob.glob(os.path.join(d, "*"))
assert files, "persistent cache wrote nothing"

before = kc.persistent_stats()["hits"]
# Drop jax's in-memory executable caches: the SAME computation must now
# come back from disk (what a restarted process would do).
jax.clear_caches()
g = jax.jit(lambda x: x * 3 + 1)
g(jnp.arange(16)).block_until_ready()
after = kc.persistent_stats()["hits"]
assert after > before, (before, after)

# The session conf wires through the planner.
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.plan.logical import col
s = TpuSession()
s.set("spark.rapids.sql.kernelCache.persistentDir", d)
df = s.create_dataframe({"a": [1, 2, 3]}, (("a", dt.INT64),))
assert df.select((col("a") * 2).alias("b")).collect() == \
    [(2,), (4,), (6,)]
assert kc.persistent_stats()["dir"] == d
print("PERSISTENT_CACHE_OK")
"""


def test_enable_write_and_hit_in_subprocess():
    # Bounded (~20s: one jax import + a handful of tiny compiles) and
    # fully isolated — the sticky global cache dies with the subprocess.
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c", _SUBPROCESS_BODY],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "PERSISTENT_CACHE_OK" in out.stdout
